"""Residency bookkeeping for tiered graph storage.

One ``TierStore`` per compiled graph. Every dense/level block registers
at ``enable_tiering`` time with its device footprint (int8 cells plus
the packed bit planes when the block is bit-kernel eligible); the store
then tracks, per block:

* **resident** — the device arrays (``(cells, bits)``) held hot, or a
  ``sharded`` flag when the mesh backend owns the placement,
* **pinned** — overlay-touched blocks that must stay hot until the next
  compaction fold rebuilds the graph (a fresh fold gets a fresh store,
  which is how pins reset),
* **access counters** — a total plus an exponentially decayed "recent"
  score the placement sweep and the eviction policy order by.

Placement policy: promote on miss (a streamed block stays resident if
it fits under ``budget * headroom`` after evicting colder unpinned
blocks), demote coldest-first, never evict pinned blocks (pins may
overshoot the budget — the gauges make that visible rather than hiding
it). All bookkeeping runs under one internal lock; device arrays are
only *referenced* here, never synced, so the lock discipline lint's
no-host-sync-under-lock rule holds.

Metric families owned here (see docs/operations.md "Metrics
reference"): ``engine_tier_hot_bytes`` / ``engine_tier_cold_bytes`` /
``engine_tier_hot_blocks`` / ``engine_tier_cold_blocks`` /
``engine_tier_pinned_blocks`` gauges, ``engine_tier_hits_total`` /
``engine_tier_misses_total`` / ``engine_tier_promotions_total`` /
``engine_tier_demotions_total`` counters, and the
``engine_tier_miss_stall_seconds`` histogram that prices what demand
streaming costs the dispatch path.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.metrics import metrics

# Fraction of the budget admissions aim for; the slack absorbs the next
# stream-in without an eviction storm on every miss.
HEADROOM = 0.85

# Multiplicative decay applied to each block's "recent" score per
# placement sweep; ~5 sweeps of silence cost a block its heat.
DECAY = 0.5


class _Entry:
    __slots__ = ("idx", "nbytes", "level", "payload", "sharded", "pinned",
                 "accesses", "recent")

    def __init__(self, idx: int, nbytes: int, level: int):
        self.idx = idx
        self.nbytes = int(nbytes)
        self.level = int(level)
        self.payload: Optional[tuple] = None
        self.sharded = False
        self.pinned = False
        self.accesses = 0
        self.recent = 0.0


class TierStore:
    def __init__(self, budget_bytes: int, arena, headroom: float = HEADROOM):
        self.budget_bytes = max(0, int(budget_bytes))
        self.arena = arena
        self.headroom = float(headroom)
        self._lock = threading.Lock()
        self._entries: Dict[int, _Entry] = {}
        self._hot_bytes = 0
        # Demand-set cache: (seed ranges, query ranges, overlay watermark)
        # -> active block tuple. Bounded; see demand_cache_get/put.
        self._demand: Dict[tuple, tuple] = {}
        self._hits = metrics.counter("engine_tier_hits_total")
        self._misses = metrics.counter("engine_tier_misses_total")
        self._promotions = metrics.counter("engine_tier_promotions_total")
        self._demotions = metrics.counter("engine_tier_demotions_total")
        self._stall = metrics.histogram("engine_tier_miss_stall_seconds")
        from .prefetch import Prefetcher
        self.prefetcher = Prefetcher()

    # ------------------------------------------------------------------
    # registration / introspection

    def register(self, idx: int, nbytes: int, level: int) -> None:
        with self._lock:
            self._entries[idx] = _Entry(idx, nbytes, level)

    def total_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values())

    def hot_bytes(self) -> int:
        with self._lock:
            return self._hot_bytes

    def stats(self) -> dict:
        with self._lock:
            hot = [e for e in self._entries.values()
                   if e.payload is not None or e.sharded]
            cold_n = len(self._entries) - len(hot)
            return {
                "blocks": len(self._entries),
                "hot_blocks": len(hot),
                "cold_blocks": cold_n,
                "hot_bytes": self._hot_bytes,
                "cold_bytes": sum(e.nbytes for e in self._entries.values()
                                  if e.payload is None and not e.sharded),
                "pinned_blocks": sum(1 for e in self._entries.values()
                                     if e.pinned),
                "accesses": {i: e.accesses
                             for i, e in self._entries.items()},
            }

    def entry_resident(self, idx: int) -> bool:
        with self._lock:
            e = self._entries.get(idx)
            return bool(e and (e.payload is not None or e.sharded))

    def entry_accesses(self, idx: int) -> int:
        with self._lock:
            e = self._entries.get(idx)
            return e.accesses if e else 0

    def peek(self, idx: int) -> Optional[tuple]:
        """Resident payload without recording an access (incremental
        edits and tests; dispatches go through lookup)."""
        with self._lock:
            e = self._entries.get(idx)
            return e.payload if e else None

    # ------------------------------------------------------------------
    # dispatch path

    def lookup(self, active: Sequence[int]
               ) -> Tuple[Dict[int, tuple], List[int]]:
        """Record one access per active block; return the resident
        payloads and the (level-ordered) list of blocks that must
        stream in."""
        hot: Dict[int, tuple] = {}
        missing: List[_Entry] = []
        n_hit = n_miss = 0
        with self._lock:
            for i in active:
                e = self._entries[i]
                e.accesses += 1
                e.recent += 1.0
                if e.payload is not None:
                    hot[i] = e.payload
                    n_hit += 1
                else:
                    missing.append(e)
                    n_miss += 1
        if n_hit:
            self._hits.inc(n_hit)
        if n_miss:
            self._misses.inc(n_miss)
        missing.sort(key=lambda e: (e.level, e.idx))
        return hot, [e.idx for e in missing]

    def observe_stall(self, seconds: float) -> None:
        self._stall.observe(max(0.0, float(seconds)))

    def admit(self, idx: int, payload: tuple,
              pinned: bool = False) -> bool:
        """Promote a freshly streamed block if it fits under
        ``budget * headroom`` after evicting colder unpinned residents;
        otherwise leave it transient (the dispatch that streamed it
        holds the only reference and it dies with the dispatch).
        Pinned admits always stick."""
        cap = int(self.budget_bytes * self.headroom)
        evicted: List[int] = []
        with self._lock:
            e = self._entries[idx]
            if e.payload is not None:
                e.payload = payload
                e.pinned = e.pinned or pinned
                return True
            if not pinned and e.nbytes + self._hot_bytes > cap:
                victims = sorted(
                    (v for v in self._entries.values()
                     if v.payload is not None and not v.pinned),
                    key=lambda v: (v.recent, v.accesses))
                freed = 0
                need = e.nbytes + self._hot_bytes - cap
                take = []
                for v in victims:
                    if freed >= need or v.recent >= e.recent:
                        break
                    take.append(v)
                    freed += v.nbytes
                if freed < need:
                    return False
                for v in take:
                    v.payload = None
                    self._hot_bytes -= v.nbytes
                    evicted.append(v.idx)
            e.payload = payload
            e.pinned = e.pinned or pinned
            self._hot_bytes += e.nbytes
        self._promotions.inc()
        if evicted:
            self._demotions.inc(len(evicted))
        return True

    def replace(self, idx: int, payload: tuple) -> None:
        """Swap the resident payload in place (incremental cell edits on
        a hot block). No-op for cold blocks."""
        with self._lock:
            e = self._entries.get(idx)
            if e is not None and e.payload is not None:
                e.payload = payload

    def demote(self, idx: int) -> bool:
        with self._lock:
            e = self._entries.get(idx)
            if e is None or e.payload is None or e.pinned:
                return False
            e.payload = None
            self._hot_bytes -= e.nbytes
        self._demotions.inc()
        return True

    def pin(self, idx: int) -> None:
        with self._lock:
            e = self._entries.get(idx)
            if e is not None:
                e.pinned = True

    def mark_sharded(self, idxs: Sequence[int]) -> None:
        """Account blocks the mesh backend placed (sharded device
        arrays are owned by ShardedGraph, not streamed per dispatch)."""
        with self._lock:
            for i in idxs:
                e = self._entries.get(i)
                if e is not None and not e.sharded:
                    e.sharded = True
                    self._hot_bytes += e.nbytes

    # ------------------------------------------------------------------
    # placement sweep (compaction thread)

    def place(self) -> List[int]:
        """Periodic sweep: decay recency, demote resident unpinned
        blocks that have gone cold while over headroom, and return the
        pinned-but-cold block indices the caller should materialize
        (overlay-touched blocks promote eagerly so the write path never
        pays their stream-in)."""
        cap = int(self.budget_bytes * self.headroom)
        demoted: List[int] = []
        want_hot: List[int] = []
        with self._lock:
            for e in self._entries.values():
                e.recent *= DECAY
            if self._hot_bytes > cap:
                for e in sorted((v for v in self._entries.values()
                                 if v.payload is not None and not v.pinned),
                                key=lambda v: (v.recent, v.accesses)):
                    if self._hot_bytes <= cap:
                        break
                    e.payload = None
                    self._hot_bytes -= e.nbytes
                    demoted.append(e.idx)
            want_hot = [e.idx for e in self._entries.values()
                        if e.pinned and e.payload is None and not e.sharded]
        if demoted:
            self._demotions.inc(len(demoted))
        return want_hot

    # ------------------------------------------------------------------
    # demand-set cache

    def demand_cache_get(self, key: tuple) -> Optional[tuple]:
        with self._lock:
            return self._demand.get(key)

    def demand_cache_put(self, key: tuple, active: tuple) -> None:
        with self._lock:
            if len(self._demand) >= 64:
                self._demand.pop(next(iter(self._demand)))
            self._demand[key] = active

    # ------------------------------------------------------------------
    # gauges

    def publish_gauges(self) -> None:
        s = self.stats()
        metrics.gauge("engine_tier_hot_bytes").set(s["hot_bytes"])
        metrics.gauge("engine_tier_cold_bytes").set(s["cold_bytes"])
        metrics.gauge("engine_tier_hot_blocks").set(s["hot_blocks"])
        metrics.gauge("engine_tier_cold_blocks").set(s["cold_blocks"])
        metrics.gauge("engine_tier_pinned_blocks").set(s["pinned_blocks"])

    def close(self) -> None:
        self.prefetcher.shutdown()
