"""Tiered graph storage: HBM-hot / host-cold block arenas.

The compiled graph's dense/level blocks are the residency unit. Hot
blocks keep their device arrays under an explicit byte budget
(``--device-graph-budget-bytes``); cold blocks live in host RAM as npz
arenas in the ``persistence/codec.py`` format (or on disk, mmapped, when
a spill directory is configured) and stream onto the device on frontier
demand. ``TierStore`` owns the placement bookkeeping and every
``engine_tier_*`` metric family; ``ColdArena`` owns the cold bytes;
``Prefetcher`` owns the double-buffered stream-in window.
"""

from .arena import ColdArena
from .prefetch import Prefetcher
from .tiers import TierStore

__all__ = ["ColdArena", "Prefetcher", "TierStore"]
