"""Host-cold block arena.

Cold blocks are stored as columnar COO payloads (``dst_local`` /
``src_local`` index columns, plus the ``base_*`` columns for closured
blocks) — ~8 bytes per edge, an order of magnitude smaller than the
dense ``int8`` cells they expand into on promotion. Two backings:

* **In-memory (default):** each block is one uncompressed ``.npz`` blob
  built exactly like ``persistence/codec.encode_bulk_cols`` (BytesIO +
  ``np.savez``, decoded with ``allow_pickle=False``). The blob
  duplicates the compiled graph's host COO for the block; that is the
  honest cost of keeping the arena self-contained, and it is what lets
  a future compile drop its host arrays entirely.
* **Spill directory:** each block becomes a ``codec.save`` directory of
  flat ``.npy`` columns, read back with ``codec.load(..., mmap=True)``
  so a stream-in touches pages on demand instead of materializing a
  second host copy (npz/zip members cannot be mmapped — see codec).
"""

from __future__ import annotations

import io
import os
import shutil
import threading
from typing import Dict, Optional

import numpy as np

from ..persistence import codec


class ColdArena:
    """Keyed store of cold block payloads (``{column: ndarray}``)."""

    def __init__(self, spill_dir: Optional[str] = None):
        self._lock = threading.Lock()
        self._blobs: Dict[int, bytes] = {}
        self._nbytes: Dict[int, int] = {}
        self._spill_dir = spill_dir
        if spill_dir is not None:
            os.makedirs(spill_dir, exist_ok=True)

    def _path(self, key: int) -> str:
        return os.path.join(self._spill_dir, "block-%d" % key)

    def put(self, key: int, arrays: Dict[str, np.ndarray]) -> int:
        """Store a block's columns; returns the payload size in bytes
        (host RAM for the in-memory backing, file bytes when spilled)."""
        arrays = {k: np.ascontiguousarray(v) for k, v in arrays.items()}
        if self._spill_dir is not None:
            n = codec.save(self._path(key), arrays)
            with self._lock:
                self._nbytes[key] = n
            return n
        bio = io.BytesIO()
        np.savez(bio, **arrays)
        blob = bio.getvalue()
        with self._lock:
            self._blobs[key] = blob
            self._nbytes[key] = len(blob)
        return len(blob)

    def get(self, key: int) -> Dict[str, np.ndarray]:
        """Decode one block's columns. Spilled blocks come back as
        read-only mmaps; in-memory blobs decode with allow_pickle=False
        (same trust boundary as the WAL codec)."""
        if self._spill_dir is not None:
            return codec.load(self._path(key), mmap=True)
        with self._lock:
            blob = self._blobs[key]
        with np.load(io.BytesIO(blob)) as z:
            return {k: z[k] for k in z.files}

    def has(self, key: int) -> bool:
        with self._lock:
            return key in self._nbytes

    def drop(self, key: int) -> None:
        with self._lock:
            self._blobs.pop(key, None)
            self._nbytes.pop(key, None)
        if self._spill_dir is not None:
            shutil.rmtree(self._path(key), ignore_errors=True)

    @property
    def nbytes(self) -> int:
        with self._lock:
            return sum(self._nbytes.values())

    def block_nbytes(self, key: int) -> int:
        with self._lock:
            return self._nbytes.get(key, 0)
