"""Double-buffered cold-block stream-in.

A two-worker executor bounds the number of in-flight host→device copies
to two: one block can be decoding/uploading while the previous one is
still landing — the classic double buffer. Callers submit the missing
blocks of a dispatch in **stratification order** (level L before level
L+1), so the block needed earliest is the first to arrive and, on real
accelerators where uploads are async, the copy for level L+1 overlaps
the compute that consumes level L inside the same dispatch.

The pool is shared per ``TierStore`` (per compiled graph), not per
dispatch: concurrent dispatches naturally serialize their stream-ins
through the same bounded window instead of oversubscribing host decode.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, Sequence


class Prefetcher:
    def __init__(self, workers: int = 2):
        self._workers = max(1, int(workers))
        self._lock = threading.Lock()
        self._pool = None

    def _ensure_pool(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self._workers,
                    thread_name_prefix="tier-prefetch")
            return self._pool

    def fetch(self, keys: Sequence[int],
              fn: Callable[[int], object]) -> Dict[int, Future]:
        """Submit ``fn(key)`` for every key, preserving the given order
        (earliest-needed first). Returns ``{key: Future}``; the caller
        waits per key and accounts the wall time it actually blocked as
        miss stall."""
        pool = self._ensure_pool()
        return {k: pool.submit(fn, k) for k in keys}

    def shutdown(self) -> None:
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)
