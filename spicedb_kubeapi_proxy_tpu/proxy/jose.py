"""JWS (JWT) signature verification primitives — pure stdlib.

Supports the asymmetric algorithms kube's OIDC authenticator accepts
(RS256/384/512, ES256/384): RSASSA-PKCS1-v1_5 via one modular
exponentiation against the JWK modulus, ECDSA via textbook short-
Weierstrass point arithmetic over P-256/P-384. No third-party crypto
dependency: verification needs only public-key math, and the proxy image
must not grow a pip requirement for it (the reference gets this from
kube's apiserver libraries, /root/reference/pkg/proxy/authn.go:40-47).

Symmetric algorithms (HS*) are deliberately ABSENT: accepting them would
let anyone holding the (public!) JWKS document mint tokens.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
from typing import Optional


class JoseError(Exception):
    pass


def b64url_decode(s: str) -> bytes:
    try:
        return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))
    except (ValueError, TypeError) as e:
        raise JoseError(f"bad base64url segment: {e}") from None


def b64url_encode(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).decode().rstrip("=")


def parse_compact(token: str) -> tuple[dict, dict, bytes, bytes]:
    """Split a compact JWS into (header, claims, signing_input, signature).
    Structure-only — no signature or claims validation happens here."""
    parts = token.split(".")
    if len(parts) != 3:
        raise JoseError(f"compact JWS needs 3 segments, got {len(parts)}")
    h, p, s = parts
    try:
        header = json.loads(b64url_decode(h))
        claims = json.loads(b64url_decode(p))
    except ValueError as e:
        raise JoseError(f"bad JWS JSON: {e}") from None
    if not isinstance(header, dict) or not isinstance(claims, dict):
        raise JoseError("JWS header/claims must be objects")
    return header, claims, f"{h}.{p}".encode(), b64url_decode(s)


_HASHES = {
    "RS256": "sha256", "RS384": "sha384", "RS512": "sha512",
    "ES256": "sha256", "ES384": "sha384",
}

# DER DigestInfo prefixes for EMSA-PKCS1-v1_5 (RFC 8017 §9.2 notes)
_DIGEST_INFO = {
    "sha256": bytes.fromhex("3031300d060960864801650304020105000420"),
    "sha384": bytes.fromhex("3041300d060960864801650304020205000430"),
    "sha512": bytes.fromhex("3051300d060960864801650304020305000440"),
}


def rsa_pkcs1v15_verify(n: int, e: int, message: bytes, sig: bytes,
                        hash_name: str) -> bool:
    """RSASSA-PKCS1-v1_5: recover EM = sig^e mod n and compare against the
    deterministic expected encoding (full-length compare, no parsing of
    attacker-controlled padding — immune to lenient-padding bugs)."""
    k = (n.bit_length() + 7) // 8
    if len(sig) != k:
        return False
    s = int.from_bytes(sig, "big")
    if s >= n:
        return False
    em = pow(s, e, n).to_bytes(k, "big")
    digest = hashlib.new(hash_name, message).digest()
    t = _DIGEST_INFO[hash_name] + digest
    ps_len = k - len(t) - 3
    if ps_len < 8:
        return False
    expected = b"\x00\x01" + b"\xff" * ps_len + b"\x00" + t
    return hmac.compare_digest(em, expected)


# -- elliptic curves ---------------------------------------------------------


class Curve:
    """Short-Weierstrass curve y² = x³ + ax + b over GF(p), order n."""

    __slots__ = ("p", "a", "b", "n", "gx", "gy", "size")

    def __init__(self, p, a, b, n, gx, gy):
        self.p, self.a, self.b, self.n = p, a, b, n
        self.gx, self.gy = gx, gy
        self.size = (n.bit_length() + 7) // 8

    def on_curve(self, P: Optional[tuple]) -> bool:
        if P is None:
            return True
        x, y = P
        return (y * y - (x * x * x + self.a * x + self.b)) % self.p == 0

    def add(self, P, Q):
        if P is None:
            return Q
        if Q is None:
            return P
        p = self.p
        x1, y1 = P
        x2, y2 = Q
        if x1 == x2:
            if (y1 + y2) % p == 0:
                return None  # P + (-P)
            m = (3 * x1 * x1 + self.a) * pow(2 * y1, -1, p) % p
        else:
            m = (y2 - y1) * pow(x2 - x1, -1, p) % p
        x3 = (m * m - x1 - x2) % p
        return x3, (m * (x1 - x3) - y1) % p

    def mul(self, k: int, P) -> Optional[tuple]:
        R = None
        while k:
            if k & 1:
                R = self.add(R, P)
            P = self.add(P, P)
            k >>= 1
        return R


P256 = Curve(
    p=0xffffffff00000001000000000000000000000000ffffffffffffffffffffffff,
    a=-3,
    b=0x5ac635d8aa3a93e7b3ebbd55769886bc651d06b0cc53b0f63bce3c3e27d2604b,
    n=0xffffffff00000000ffffffffffffffffbce6faada7179e84f3b9cac2fc632551,
    gx=0x6b17d1f2e12c4247f8bce6e563a440f277037d812deb33a0f4a13945d898c296,
    gy=0x4fe342e2fe1a7f9b8ee7eb4a7c0f9e162bce33576b315ececbb6406837bf51f5,
)

P384 = Curve(
    p=int("fffffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff"
          "effffffff0000000000000000ffffffff", 16),
    a=-3,
    b=int("b3312fa7e23ee7e4988e056be3f82d19181d9c6efe8141120314088f5013875a"
          "c656398d8a2ed19d2a85c8edd3ec2aef", 16),
    n=int("ffffffffffffffffffffffffffffffffffffffffffffffffc7634d81f4372ddf"
          "581a0db248b0a77aecec196accc52973", 16),
    gx=int("aa87ca22be8b05378eb1c71ef320ad746e1d3b628ba79b9859f741e082542a38"
           "5502f25dbf55296c3a545e3872760ab7", 16),
    gy=int("3617de4a96262c6f5d9e98bf9292dc29f8f41dbd289a147ce9da3113b5f0b8c0"
           "0a60b1ce1d7e819d7a431d7c90ea0e5f", 16),
)

_CURVES = {"ES256": P256, "ES384": P384, "P-256": P256, "P-384": P384}


def ecdsa_verify(curve: Curve, qx: int, qy: int, message: bytes,
                 sig: bytes, hash_name: str) -> bool:
    """ECDSA over the given curve; ``sig`` is the JWS raw ``r || s``
    fixed-width encoding (RFC 7518 §3.4), not DER."""
    if len(sig) != 2 * curve.size:
        return False
    r = int.from_bytes(sig[:curve.size], "big")
    s = int.from_bytes(sig[curve.size:], "big")
    n = curve.n
    if not (0 < r < n and 0 < s < n):
        return False
    Q = (qx, qy)
    if not curve.on_curve(Q) or Q is None:
        return False
    digest = hashlib.new(hash_name, message).digest()
    e = int.from_bytes(digest, "big")
    # left-truncate the digest to the order's bit length (FIPS 186-4)
    extra = max(0, 8 * len(digest) - n.bit_length())
    e >>= extra
    w = pow(s, -1, n)
    u1 = e * w % n
    u2 = r * w % n
    R = curve.add(curve.mul(u1, (curve.gx, curve.gy)), curve.mul(u2, Q))
    if R is None:
        return False
    return R[0] % n == r


def verify_jws(header: dict, signing_input: bytes, sig: bytes,
               jwk: dict) -> bool:
    """Verify one JWS signature against one JWK. The caller has already
    picked the key (kid) and validated that ``alg`` is allowed."""
    alg = header.get("alg")
    hash_name = _HASHES.get(alg)
    if hash_name is None:
        raise JoseError(f"unsupported alg {alg!r}")
    kty = jwk.get("kty")
    if alg.startswith("RS"):
        if kty != "RSA":
            raise JoseError(f"alg {alg} needs an RSA key, got {kty!r}")
        n = int.from_bytes(b64url_decode(jwk["n"]), "big")
        e = int.from_bytes(b64url_decode(jwk["e"]), "big")
        return rsa_pkcs1v15_verify(n, e, signing_input, sig, hash_name)
    if alg.startswith("ES"):
        if kty != "EC":
            raise JoseError(f"alg {alg} needs an EC key, got {kty!r}")
        curve = _CURVES.get(jwk.get("crv", ""))
        if curve is None or curve is not _CURVES[alg]:
            raise JoseError(
                f"curve {jwk.get('crv')!r} does not match alg {alg}")
        qx = int.from_bytes(b64url_decode(jwk["x"]), "big")
        qy = int.from_bytes(b64url_decode(jwk["y"]), "big")
        return ecdsa_verify(curve, qx, qy, signing_input, sig, hash_name)
    raise JoseError(f"unsupported alg {alg!r}")


# -- signing (test fixtures / local issuance only) ---------------------------


def rsa_pkcs1v15_sign(n: int, d: int, message: bytes,
                      hash_name: str) -> bytes:
    """Produce an RSASSA-PKCS1-v1_5 signature from a raw private exponent.
    Exists for JWKS test fixtures — the proxy itself never signs."""
    k = (n.bit_length() + 7) // 8
    digest = hashlib.new(hash_name, message).digest()
    t = _DIGEST_INFO[hash_name] + digest
    em = b"\x00\x01" + b"\xff" * (k - len(t) - 3) + b"\x00" + t
    return pow(int.from_bytes(em, "big"), d, n).to_bytes(k, "big")


def ecdsa_sign(curve: Curve, d: int, message: bytes, k: int,
               hash_name: str) -> bytes:
    """Raw-``r||s`` ECDSA signature with an explicit nonce ``k`` —
    test-fixture helper; real signers need RFC 6979 or a CSPRNG nonce."""
    n = curve.n
    digest = hashlib.new(hash_name, message).digest()
    e = int.from_bytes(digest, "big") >> max(
        0, 8 * len(digest) - n.bit_length())
    R = curve.mul(k, (curve.gx, curve.gy))
    r = R[0] % n
    s = pow(k, -1, n) * (e + r * d) % n
    if r == 0 or s == 0:
        raise JoseError("bad nonce")
    return r.to_bytes(curve.size, "big") + s.to_bytes(curve.size, "big")
