"""In-memory kube-apiserver implementing the Upstream interface.

One implementation, two consumers: the test suite's FakeKube (which adds
failure injection on top — the role envtest's real apiserver plays in
the reference e2e suite, e2e/util_test.go:65-102) and the self-contained
demo (`proxy/demo.py`, the reference's `mage dev:up` flow without a kind
cluster). CRUD + list + merge-patch + watch over JSON resources; content
shape follows kube conventions (kind lists, Status errors,
resourceVersion).

ownerReference garbage collection (reference e2e exercises a REAL kube
GC controller over cascading deletes, e2e/e2e_test.go:156-186): objects
get a uid at create; deleting an owner schedules a BACKGROUND cascade —
dependents whose ownerReferences all dangle are deleted (recursively,
honoring finalizers); ``propagationPolicy=Orphan`` strips the deleted
owner's references instead. Foreground propagation is approximated as
background (the fake has no blocking foreground finalizer).
"""

from __future__ import annotations

import asyncio
import json

from . import kubeproto
from .requestinfo import parse_request_info
from .types import ProxyRequest, ProxyResponse, json_response, kube_status


def kind_for(resource: str) -> str:
    singular = resource[:-1] if resource.endswith("s") else resource
    return "".join(p.capitalize() for p in singular.split("-"))


def _strip_directives(v):
    """Remove strategic-merge $patch directives from a value being
    stored verbatim (the replace fallback when a list isn't mergeable by
    name) — the real apiserver never persists directives."""
    if isinstance(v, dict):
        return {k: _strip_directives(x) for k, x in v.items()
                if k != "$patch"}
    if isinstance(v, list):
        return [_strip_directives(x) for x in v
                if not (isinstance(x, dict) and x.get("$patch") == "delete")]
    return v


class InMemoryKube:
    def __init__(self):
        # (resource, namespace, name) -> object dict
        self.objects: dict[tuple, dict] = {}
        self.rv = 0
        self._watchers: list[tuple[str, str, asyncio.Queue]] = []
        # deletion propagation intent remembered across a finalizer wait
        self._pending_gc_policy: dict[tuple, str] = {}

    # -- seeding -------------------------------------------------------------

    def put(self, resource: str, name: str, ns: str = "",
            obj: dict | None = None) -> dict:
        """Seed an object directly (demo/test setup), notifying watchers."""
        obj = dict(obj or {})
        obj.setdefault("apiVersion", "v1")
        obj.setdefault("kind", kind_for(resource))
        meta = obj.setdefault("metadata", {})
        meta["name"] = name
        if ns:
            meta["namespace"] = ns
        self.rv += 1
        meta["resourceVersion"] = str(self.rv)
        self.objects[(resource, ns, name)] = obj
        self._notify(resource, ns, {"type": "ADDED", "object": obj})
        return obj

    # -- upstream interface --------------------------------------------------

    async def __call__(self, req: ProxyRequest) -> ProxyResponse:
        # the dual-write workflow replays raw requests without a parsed
        # request_info (dtx/activity.py write_to_kube)
        info = req.request_info or parse_request_info(
            req.method, req.path, req.query)
        if not info.is_resource_request:
            if info.path.startswith(("/api", "/apis", "/openapi", "/version")):
                return json_response(200, {"kind": "APIVersions",
                                           "versions": ["v1"]})
            return kube_status(404, "not found")
        res, ns, name = info.resource, info.namespace, info.name
        if info.verb == "get":
            obj = self.objects.get((res, ns, name))
            if obj is None:
                return kube_status(404, f'{res} "{name}" not found', "NotFound")
            return json_response(200, obj)
        if info.verb == "list" or info.verb == "watch":
            if info.verb == "watch":
                bookmarks = (req.query.get("allowWatchBookmarks") or
                             ["false"])[0] in ("true", "1", "True")
                accept = next((v for k, v in req.headers.items()
                               if k.lower() == "accept"), "")
                return self._start_watch(
                    res, ns, bookmarks=bookmarks,
                    proto="protobuf" in accept.lower())
            items = [o for (r, n_, _), o in sorted(self.objects.items())
                     if r == res and (not ns or n_ == ns)]
            return json_response(200, {
                "kind": kind_for(res) + "List",
                "apiVersion": "v1",
                "metadata": {"resourceVersion": str(self.rv)},
                "items": items,
            })
        if info.verb == "create":
            try:
                obj = json.loads(req.body)
            except ValueError:
                return kube_status(400, "invalid body")
            if not isinstance(obj, dict):
                return kube_status(400, "body must be an object")
            name = (obj.get("metadata") or {}).get("name", "")
            if not name:
                return kube_status(400, "name required")
            key = (res, ns, name)
            if key in self.objects:
                return kube_status(409, f'{res} "{name}" already exists',
                                   "AlreadyExists")
            self.rv += 1
            if not isinstance(obj.get("metadata"), dict):
                obj["metadata"] = {"name": name}
            obj["metadata"]["resourceVersion"] = str(self.rv)
            # kube stamps a uid at create; the GC matches owner refs on it
            obj["metadata"].setdefault("uid", f"uid-{self.rv}")
            if ns:
                obj["metadata"]["namespace"] = ns
            obj.setdefault("kind", kind_for(res))
            self.objects[key] = obj
            self._notify(res, ns, {"type": "ADDED", "object": obj})
            return json_response(201, obj)
        if info.verb == "update":
            key = (res, ns, name)
            if key not in self.objects:
                return kube_status(404, f'{res} "{name}" not found', "NotFound")
            try:
                obj = json.loads(req.body)
            except ValueError:
                return kube_status(400, "invalid body")
            if not isinstance(obj, dict):
                return kube_status(400, "body must be an object")
            # optimistic concurrency: a stale resourceVersion in the body
            # is a genuine 409 Conflict (real apiserver semantics — the
            # dual-write path must cope with conflicts the FAKE detects,
            # not only injected ones)
            sent_rv = (obj.get("metadata") or {}).get("resourceVersion")
            cur_rv = (self.objects[key].get("metadata") or {}) \
                .get("resourceVersion")
            if sent_rv and cur_rv and sent_rv != cur_rv:
                return kube_status(
                    409,
                    f'Operation cannot be fulfilled on {res} "{name}": '
                    "the object has been modified; please apply your "
                    "changes to the latest version and try again",
                    "Conflict")
            self.rv += 1
            obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
            self.objects[key] = obj
            self._notify(res, ns, {"type": "MODIFIED", "object": obj})
            return self._finalize_if_cleared(key, obj) \
                or json_response(200, obj)
        if info.verb == "patch":
            key = (res, ns, name)
            if key not in self.objects:
                return kube_status(404, f'{res} "{name}" not found', "NotFound")
            try:
                patch = json.loads(req.body)
            except ValueError:
                return kube_status(400, "invalid patch body", "BadRequest")
            if not isinstance(patch, dict):
                return kube_status(
                    415, "only merge/strategic-merge patch objects "
                         "supported", "BadRequest")
            ctype = next((v for k, v in req.headers.items()
                          if k.lower() == "content-type"), "")
            strategic = "strategic-merge-patch" in ctype
            obj = json.loads(json.dumps(self.objects[key]))

            def merge(dst, src):
                # JSON Merge Patch (RFC 7386): null deletes the key.
                # Strategic-merge additionally merges LISTS OF OBJECTS by
                # their "name" key (the dominant patchMergeKey in kube
                # schemas; the real apiserver consults the type's openapi
                # — this fake approximates the common convention) and
                # honors $patch: delete directives.
                for k, v in src.items():
                    if v is None:
                        dst.pop(k, None)
                    elif isinstance(v, dict) and isinstance(dst.get(k), dict):
                        merge(dst[k], v)
                    elif strategic and isinstance(v, list) \
                            and isinstance(dst.get(k), list) \
                            and all(isinstance(x, dict) and "name" in x
                                    for x in v + dst[k]):
                        by_name = {x["name"]: x for x in dst[k]}
                        for x in v:
                            if x.get("$patch") == "delete":
                                by_name.pop(x["name"], None)
                            elif x["name"] in by_name:
                                merge(by_name[x["name"]], x)
                            else:
                                by_name[x["name"]] = x
                        dst[k] = list(by_name.values())
                    else:
                        dst[k] = _strip_directives(v) if strategic else v

            merge(obj, patch)
            self.rv += 1
            obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
            self.objects[key] = obj
            self._notify(res, ns, {"type": "MODIFIED", "object": obj})
            return self._finalize_if_cleared(key, obj) \
                or json_response(200, obj)
        if info.verb == "delete":
            key = (res, ns, name)
            obj = self.objects.get(key)
            if obj is None:
                return kube_status(404, f'{res} "{name}" not found', "NotFound")
            meta = obj.setdefault("metadata", {})
            if meta.get("finalizers"):
                # kube finalizer semantics: the object is not removed —
                # it gains deletionTimestamp and waits for controllers to
                # clear the finalizers; DELETE returns the terminating
                # object, not a Status
                if not meta.get("deletionTimestamp"):
                    import datetime

                    meta["deletionTimestamp"] = datetime.datetime.now(
                        datetime.timezone.utc).strftime(
                            "%Y-%m-%dT%H:%M:%SZ")
                    # remember the propagation intent across the
                    # finalizer wait (kube records it as an orphan/
                    # foreground finalizer) so the eventual GC honors it
                    self._pending_gc_policy[key] = \
                        self._propagation_policy(req)
                    self.rv += 1
                    meta["resourceVersion"] = str(self.rv)
                    self._notify(res, ns,
                                 {"type": "MODIFIED", "object": obj})
                return json_response(200, obj)
            self.objects.pop(key, None)
            self.rv += 1
            self._notify(res, ns, {"type": "DELETED", "object": obj})
            self._schedule_gc(obj, self._propagation_policy(req))
            return json_response(200, {"kind": "Status", "status": "Success",
                                       "code": 200})
        return kube_status(405, f"verb {info.verb} not supported")

    def _finalize_if_cleared(self, key: tuple, obj: dict):
        """A terminating object whose last finalizer was just removed is
        deleted for real (what the apiserver does when a controller
        clears its finalizer)."""
        meta = obj.get("metadata") or {}
        if meta.get("deletionTimestamp") and not meta.get("finalizers"):
            res, ns, _ = key
            self.objects.pop(key, None)
            self.rv += 1
            self._notify(res, ns, {"type": "DELETED", "object": obj})
            self._schedule_gc(obj,
                              self._pending_gc_policy.pop(key, "Background"))
            return json_response(200, obj)
        return None

    # -- ownerReference garbage collection -----------------------------------

    @staticmethod
    def _propagation_policy(req: ProxyRequest) -> str:
        """DeleteOptions propagationPolicy, from the query or the DELETE
        body (both places kube accepts it); default Background."""
        q = (req.query.get("propagationPolicy") or [None])[0]
        if q:
            return q
        if req.body:
            try:
                opts = json.loads(req.body)
                if isinstance(opts, dict) and opts.get("propagationPolicy"):
                    return opts["propagationPolicy"]
            except ValueError:
                pass
        return "Background"

    def _schedule_gc(self, owner: dict, policy: str = "Background") -> None:
        """Run the GC pass for a just-removed owner in the BACKGROUND
        (kube's GC is a controller, not part of the DELETE request);
        without a running loop (direct sync use) it runs inline."""
        try:
            asyncio.get_running_loop().create_task(
                self._gc_cascade(owner, policy))
        except RuntimeError:
            # no event loop: degenerate to synchronous collection
            for step in self._gc_steps(owner, policy):
                step()

    async def _gc_cascade(self, owner: dict, policy: str) -> None:
        await asyncio.sleep(0)  # after the DELETE response is written
        for step in self._gc_steps(owner, policy):
            step()
            await asyncio.sleep(0)  # one watch-visible step at a time

    def _gc_steps(self, owner: dict, policy: str):
        """Yield thunks, one per dependent action. A dependent is
        collected only when ALL of its ownerReferences dangle (kube GC
        semantics); Orphan strips the deleted owner's reference
        instead of deleting."""
        okind = owner.get("kind") or ""
        ometa = owner.get("metadata") or {}
        oname, ouid = ometa.get("name") or "", ometa.get("uid")
        ons = ometa.get("namespace") or ""
        for key, obj in list(self.objects.items()):
            if self.objects.get(key) is not obj:
                continue  # already collected by a recursive step
            res, ns, name = key
            meta = obj.get("metadata") or {}
            refs = meta.get("ownerReferences") or []
            mine = [r for r in refs
                    if r.get("kind") == okind and r.get("name") == oname
                    and (not r.get("uid") or not ouid
                         or r.get("uid") == ouid)
                    # namespaced dependents reference same-namespace or
                    # cluster-scoped owners (kube invariant)
                    and (not ons or ns == ons)]
            if not mine:
                continue
            if policy == "Orphan":
                yield self._gc_orphan_step(key, obj, mine)
                continue
            others = [r for r in refs if r not in mine]
            if any(self._owner_exists(r, ns) for r in others):
                continue  # a living owner still holds it
            yield self._gc_delete_step(key, obj)

    def _owner_exists(self, ref: dict, dependent_ns: str) -> bool:
        kind, name = ref.get("kind") or "", ref.get("name") or ""
        for (res, ns, n), o in self.objects.items():
            if n == name and o.get("kind") == kind \
                    and ns in ("", dependent_ns):
                if ref.get("uid") and (o.get("metadata") or {}).get("uid") \
                        and ref["uid"] != o["metadata"]["uid"]:
                    continue
                return True
        return False

    def _gc_orphan_step(self, key, obj, refs_to_strip):
        def step():
            if self.objects.get(key) is not obj:
                return
            meta = obj.setdefault("metadata", {})
            meta["ownerReferences"] = [
                r for r in meta.get("ownerReferences") or []
                if r not in refs_to_strip]
            if not meta["ownerReferences"]:
                del meta["ownerReferences"]
            self.rv += 1
            meta["resourceVersion"] = str(self.rv)
            self._notify(key[0], key[1], {"type": "MODIFIED", "object": obj})
        return step

    def _gc_delete_step(self, key, obj):
        def step():
            if self.objects.get(key) is not obj:
                return
            res, ns, _ = key
            meta = obj.setdefault("metadata", {})
            if meta.get("finalizers"):
                # finalized dependents terminate, they don't vanish
                if not meta.get("deletionTimestamp"):
                    import datetime

                    meta["deletionTimestamp"] = datetime.datetime.now(
                        datetime.timezone.utc).strftime(
                            "%Y-%m-%dT%H:%M:%SZ")
                    self.rv += 1
                    meta["resourceVersion"] = str(self.rv)
                    self._notify(res, ns,
                                 {"type": "MODIFIED", "object": obj})
                return
            self.objects.pop(key, None)
            self.rv += 1
            self._notify(res, ns, {"type": "DELETED", "object": obj})
            self._schedule_gc(obj)  # recurse: grandchildren
        return step

    # -- watch ---------------------------------------------------------------

    def _notify(self, res: str, ns: str, event: dict) -> None:
        for r, n_, q in self._watchers:
            if r == res and (not n_ or n_ == ns):
                q.put_nowait(event)

    def _start_watch(self, res: str, ns: str, bookmarks: bool = False,
                     proto: bool = False) -> ProxyResponse:
        q: asyncio.Queue = asyncio.Queue()
        # emit existing objects as initial ADDED events (kube semantics with
        # resourceVersion=0 watches)
        for (r, n_, _), o in sorted(self.objects.items()):
            if r == res and (not ns or n_ == ns):
                q.put_nowait({"type": "ADDED", "object": o})
        if bookmarks:
            # kube sends an initial-events-end bookmark carrying only a
            # resourceVersion; clients use it to mark their sync point
            q.put_nowait({"type": "BOOKMARK", "object": {
                "kind": kind_for(res), "apiVersion": "v1",
                "metadata": {"resourceVersion": str(self.rv)}}})
        entry = (res, ns, q)
        self._watchers.append(entry)

        def encode(ev: dict) -> bytes:
            if not proto:
                return (json.dumps(ev) + "\n").encode()
            # protobuf negotiation: length-prefixed raw WatchEvent whose
            # object rides a magic-prefixed Unknown (what a real apiserver
            # sends for Accept: application/vnd.kubernetes.protobuf);
            # the fake's object payload carries the ObjectMeta shape every
            # keying path reads (proxy/kubeproto.py)
            obj = ev.get("object") or {}
            meta = obj.get("metadata") or {}
            body = kubeproto.encode_object_meta_only(
                meta.get("name", ""), meta.get("namespace", ""))
            env = kubeproto.encode_unknown(
                obj.get("apiVersion", "v1"), obj.get("kind", ""), body)
            return kubeproto.encode_watch_frame(ev["type"], env)

        async def frames():
            try:
                while True:
                    ev = await q.get()
                    if ev is None:
                        return
                    yield encode(ev)
            finally:
                # client disconnect / generator close: stop fanning events
                # into a dead queue (long-running demos would leak)
                if entry in self._watchers:
                    self._watchers.remove(entry)

        return ProxyResponse(
            status=200,
            headers={"Content-Type": kubeproto.WATCH_CONTENT_TYPE if proto
                     else "application/json",
                     "Transfer-Encoding": "chunked"},
            stream=frames(),
        )

    def emit_watch_event(self, res: str, event_type: str, name: str,
                         ns: str = "") -> None:
        """Emit a synthetic watch event for an (existing or ad-hoc) object
        — lets tests inject upstream events without a write round trip."""
        obj = self.objects.get((res, ns, name))
        if obj is None:
            obj = {"kind": kind_for(res), "metadata": {"name": name}}
            if ns:
                obj["metadata"]["namespace"] = ns
        obj = json.loads(json.dumps(obj))  # private copy
        self.rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
        self._notify(res, ns, {"type": event_type, "object": obj})

    def emit_bookmark(self, res: str, ns: str = "") -> None:
        """Emit a BOOKMARK event to watchers (kube sends these
        periodically; tests use this to exercise the passthrough)."""
        self._notify(res, ns, {"type": "BOOKMARK", "object": {
            "kind": kind_for(res), "apiVersion": "v1",
            "metadata": {"resourceVersion": str(self.rv)}}})

    def stop_watches(self):
        for _, _, q in list(self._watchers):
            q.put_nowait(None)
        self._watchers.clear()
