"""In-memory transport: invoke the proxy handler chain with zero network.

Mirrors /root/reference/pkg/inmemory/transport.go:18-137: a client whose
"round trip" calls the handler directly. Used for embedded-mode clients
(reference README's "sub-microsecond" path) and benchmarks.
"""

from __future__ import annotations

import json
from typing import Optional

from .types import ProxyRequest, ProxyResponse


class InMemoryClient:
    """A minimal kube-ish client over a handler callable."""

    def __init__(self, handler, user: Optional[str] = None,
                 groups: Optional[list] = None):
        self.handler = handler  # async (ProxyRequest) -> ProxyResponse
        self.user = user
        self.groups = groups or []

    def _headers(self, extra: Optional[dict] = None) -> dict:
        h = {"Content-Type": "application/json"}
        if self.user:
            # embedded-mode identity headers (reference authn.go:78-119,
            # authHeaderTransport server.go:363-389)
            h["X-Remote-User"] = self.user
            if self.groups:
                h["X-Remote-Group"] = ",".join(self.groups)
        if extra:
            h.update(extra)
        return h

    async def request(self, method: str, path: str, body=None,
                      query: Optional[dict] = None,
                      headers: Optional[dict] = None) -> ProxyResponse:
        return await self.handler(ProxyRequest(
            method=method,
            path=path,
            query=query or {},
            headers=self._headers(headers),
            body=(json.dumps(body).encode() if isinstance(body, (dict, list))
                  else (body or b"")),
        ))

    async def get(self, path: str, **kw) -> ProxyResponse:
        return await self.request("GET", path, **kw)

    async def post(self, path: str, body, **kw) -> ProxyResponse:
        return await self.request("POST", path, body=body, **kw)

    async def put(self, path: str, body, **kw) -> ProxyResponse:
        return await self.request("PUT", path, body=body, **kw)

    async def delete(self, path: str, **kw) -> ProxyResponse:
        return await self.request("DELETE", path, **kw)
