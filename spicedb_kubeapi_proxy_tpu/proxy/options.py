"""Options: configuration, completion, validation.

Mirrors /root/reference/pkg/proxy/options.go:49-449: rule-file parsing into
a matcher, engine endpoint selection (``embedded://`` in-process engine —
which IS the TPU engine here, also reachable as ``tpu://`` per the
BASELINE.json north star), workflow database path, upstream kube
connection, authentication mode, and functional options for embedding.
"""

from __future__ import annotations

import argparse
import logging
from dataclasses import dataclass, field
from typing import Optional

from ..authz import AuthzDeps
from ..dtx import ActivityHandler, WorkflowEngine, register_workflows
from ..dtx.workflow import LOCK_MODE_OPTIMISTIC, LOCK_MODE_PESSIMISTIC
from ..engine import Engine
from ..rules.matcher import MapMatcher
from .authn import HeaderAuthenticator
from .server import Server
from .upstream import HttpUpstream

EMBEDDED_ENDPOINT = "embedded://"
TPU_ENDPOINT = "tpu://"
REMOTE_ENDPOINT_PREFIX = "tcp://"  # remote engine host (engine/remote.py)

DEFAULT_WORKFLOW_DB = "/tmp/dtx.sqlite"  # reference options.go:41


class OptionsError(ValueError):
    pass


def parse_bool_flag(v) -> bool:
    """argparse type for ``--flag``, ``--flag=true`` and ``--flag=false``
    (kube-style boolean flags; used by --authz-cache, default on)."""
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in ("1", "true", "t", "yes", "y", "on"):
        return True
    if s in ("0", "false", "f", "no", "n", "off"):
        return False
    raise argparse.ArgumentTypeError(f"expected a boolean, got {v!r}")


def _parse_mesh_spec(spec: str) -> dict:
    """Mesh spec parsing (parallel/mesh.py), re-raised as OptionsError."""
    from ..parallel.mesh import MeshSpecError, parse_mesh_spec

    try:
        return parse_mesh_spec(spec)
    except MeshSpecError as e:
        raise OptionsError(str(e)) from None


def _probe_device_backend(timeout: float) -> None:
    """Initialize the jax backend in a THROWAWAY subprocess first: the
    remotely-attached TPU plugin blocks forever (no error) when its
    tunnel is down, and a hang must surface as a boot failure with a
    clear message, not as a ready-but-frozen proxy. Same pattern as
    bench.py's probe. The subprocess also warms nothing — the real
    in-process init happens lazily afterwards."""
    import subprocess
    import sys as _sys

    try:
        p = subprocess.run(
            [_sys.executable, "-c",
             # honor an explicit JAX_PLATFORMS=cpu despite the image's
             # sitecustomize override (same guard as tests/conftest.py)
             "import os, jax;\n"
             "os.environ.get('JAX_PLATFORMS') == 'cpu' and "
             "jax.config.update('jax_platforms', 'cpu');\n"
             "print(jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        raise OptionsError(
            f"device backend did not answer within {timeout:.0f}s "
            "(hung TPU plugin / tunnel down?) — fix the device "
            "attachment, lower --engine-probe-timeout, or set it to 0 "
            "to skip the probe") from None
    if p.returncode != 0:
        raise OptionsError(
            "device backend probe failed: "
            f"{(p.stderr or p.stdout).strip()[-400:]}")
    log = logging.getLogger("sdbkp.options")
    log.info("device backend probe: %s", p.stdout.strip() or "?")


@dataclass
class Options:
    # engine backend: embedded:// | tpu:// (both in-process; tpu:// is the
    # default and runs the reachability kernels on the available JAX
    # backend) | tcp://host:port (a remote engine host, engine/remote.py —
    # the reference's remote-SpiceDB deployment shape, options.go:325-369)
    engine_endpoint: str = TPU_ENDPOINT
    engine_token: Optional[str] = None  # bearer token for tcp:// endpoints
    # tcp:// transport security (reference remote-endpoint flag shape:
    # --spicedb-insecure / --spicedb-skip-verify-ca / --spicedb-ca-path,
    # options.go:325-369): TLS with full verification is the DEFAULT;
    # plaintext requires the explicit opt-out
    engine_insecure: bool = False
    engine_ca_file: Optional[str] = None  # custom CA (default: system)
    engine_skip_verify_ca: bool = False
    engine_client_cert_file: Optional[str] = None  # mutual-TLS client pair
    engine_client_key_file: Optional[str] = None
    # verification/SNI name when dialing an address that isn't the cert's
    # name (e.g. tcp://10.0.0.5:50051 with a DNS-named certificate)
    engine_server_name: Optional[str] = None
    bootstrap_files: list = field(default_factory=list)
    bootstrap_content: Optional[str] = None  # yaml text
    rule_files: list = field(default_factory=list)
    rule_content: Optional[str] = None
    # upstream kube-apiserver — three resolution modes, first match wins
    # (reference RestConfigFunc, options.go:223-263): explicit URL flags,
    # a kubeconfig file (honoring current-context / --kubeconfig-context),
    # or the in-cluster service-account environment
    upstream_url: Optional[str] = None
    upstream_token: Optional[str] = None
    upstream_ca_file: Optional[str] = None
    upstream_client_cert: Optional[str] = None
    upstream_client_key: Optional[str] = None
    upstream_insecure: bool = False
    kubeconfig: Optional[str] = None
    kubeconfig_context: Optional[str] = None
    # an injected upstream callable overrides the URL (embedding/tests)
    upstream: Optional[object] = None
    # serving
    bind_host: str = "127.0.0.1"
    bind_port: int = 8443
    # TLS serving (reference secure-serving, server.go:164-202): cert+key
    # enable HTTPS; a client CA additionally enables client-certificate
    # authentication (CN -> user, O -> groups, authn.go:40-47) and makes
    # X-Remote-* identity headers trusted ONLY from cert-bearing peers
    tls_cert_file: Optional[str] = None
    tls_key_file: Optional[str] = None
    tls_client_ca_file: Optional[str] = None
    # CNs of cert-authenticated FRONT PROXIES allowed to assert end-user
    # identity via X-Remote-* headers (kube --requestheader-allowed-names)
    tls_requestheader_allowed_names: list = field(default_factory=list)
    # kube static token file (token,user,uid[,groups]) for Bearer authn
    token_auth_file: Optional[str] = None
    # OIDC bearer authentication (kube --oidc-* option names; the last of
    # the reference's four built-in authn modes, authn.go:40-47)
    oidc_issuer_url: Optional[str] = None
    oidc_client_id: Optional[str] = None
    oidc_username_claim: str = "sub"
    oidc_username_prefix: Optional[str] = None  # "-" disables prefixing
    oidc_groups_claim: Optional[str] = None
    oidc_groups_prefix: str = ""
    oidc_ca_file: Optional[str] = None
    oidc_signing_algs: str = "RS256"  # comma-separated
    # repeatable key=value pairs every token must carry verbatim
    oidc_required_claims: list = field(default_factory=list)
    # dual-write. None resolves to <data_dir>/dtx.sqlite when a data dir
    # is configured (durable dual-writes live WITH the durable store),
    # else the historical default — an explicit path always wins
    workflow_database_path: Optional[str] = None
    lock_mode: str = LOCK_MODE_PESSIMISTIC
    # relationship-store snapshot: loaded at boot when the file exists,
    # saved on graceful shutdown (in-process engines only)
    snapshot_path: Optional[str] = None
    # durable persistence (persistence/): write-ahead log + snapshot
    # checkpoints + crash recovery under this directory. Unset = the
    # in-memory store (today's behavior; every existing test).
    # In-process engines only — a tcp:// engine host owns its own disk.
    data_dir: Optional[str] = None
    wal_fsync: str = "interval:100"  # always | interval:<ms> | off
    checkpoint_wal_bytes: int = 64 << 20
    checkpoint_wal_records: int = 50_000
    checkpoint_keep: int = 2
    # >0 coalesces concurrent list prefilters into fused device dispatches
    # (seconds of added latency traded for per-dispatch amortization)
    lookup_batch_window: float = 0.0
    # revision-keyed decision cache + singleflight on the authorization
    # hot path (engine/decision_cache.py): repeats at an unchanged store
    # revision serve host-side with zero device dispatches. In-process
    # engines only (a tcp:// engine host caches on the host; pass the
    # same flags there). Default ON; --authz-cache=false restores the
    # byte-identical uncached behavior.
    authz_cache: bool = True
    authz_cache_size: int = 65536  # max cached decisions (LRU entries)
    authz_cache_mask_bytes: int = 256 << 20  # resident lookup-mask budget
    # device-resident delta overlay (ops/reachability.py): fixed overlay
    # capacity per compiled graph (part of the jit signature — appends
    # never re-specialize) and the occupancy fraction that wakes the
    # background compactor (engine/compaction.py). 0 threshold disables
    # compaction: overlay overflow then falls back to a synchronous
    # recompile on the next fully-consistent read. In-process engines
    # only — a tcp:// engine host owns its own overlay (same flags there).
    delta_capacity: int = 4096
    compact_threshold: float = 0.75
    # tiered graph storage (storage/, docs/operations.md "Tiered graph
    # storage"): device byte budget for resident dense blocks. 0 =
    # classic all-resident placement; > 0 keeps hot blocks on device
    # under the cap, parks cold ones in host arenas, and streams them
    # into dispatches on demand. Emulatable on CPU (the budget gates
    # the same placement bookkeeping). In-process engines only.
    device_graph_budget_bytes: int = 0
    # request caveat context (caveats/, docs/operations.md "Caveats &
    # conditional grants"): forward caller attributes (client IP from
    # the trusted header below — last XFF hop — user, verb/resource) to the engine so
    # conditional grants resolve per request; off = request-dependent
    # caveats fail closed (tuple-context-only caveats still evaluate)
    caveat_context: bool = True
    caveat_ip_header: str = "x-forwarded-for"
    # -- scale-out sharding (scaleout/) --------------------------------------
    # explicit versioned shard map: inline JSON or a path to a JSON file
    # ({"version": 1, "groups": [["h:p", "h:p"], ["h:p"]]}). When set,
    # the proxy builds a scatter-gather planner over the named engine
    # groups (each group an endpoint list = its own failover set) and
    # --engine-endpoint must stay at its in-process default (the planner
    # IS the engine client). Tuples partition by (namespace, resource-
    # type) consistent hashing; global (cluster-scoped) tuples replicate
    # to every group. docs/operations.md "Scale-out sharding".
    shard_map: Optional[str] = None
    # durable cross-shard split-write journal (dtx-style); None lands it
    # beside the workflow DB. A mid-split crash replays to completion on
    # the next boot.
    shard_journal_path: Optional[str] = None
    # vector-keyed client-side decision cache: entries key by the full
    # per-shard revision vector, never serve after ANY component
    # advances, and are TTL-bounded (the planner cannot see the
    # engine-side expiration/caveat verdict-flip watermarks). Off by
    # default — it only helps when every write flows through THIS
    # proxy replica (the per-group host-side caches stay exact
    # regardless).
    shard_cache: bool = False
    # online rebalance (scaleout/rebalance.py): a TARGET shard map
    # (inline JSON or path, same grammar as --shard-map) with a HIGHER
    # version. On boot the planner starts the live tuple mover — plan /
    # copy / catch-up / dual-write / per-slice cutover / GC — taking
    # the fleet from the current map to this one with no drain;
    # progress rides /readyz as `rebalance: moving=K copied=J lag=...`.
    rebalance_to: Optional[str] = None
    # live schema migration (migration/): a schema-DSL file to migrate
    # the serving engine(s) to at boot, with no downtime — diff
    # classification (a typed refusal for incompatible changes),
    # dual-compile, journaled backfill of affected tuples, and an
    # atomic cutover at a revision. Sharded deployments coordinate the
    # cut across every group; progress rides /readyz as
    # `migration: phase=... lag=...`.
    migrate_schema: Optional[str] = None
    # >0 probes the device backend in a SUBPROCESS with this timeout
    # before building an in-process engine: the remotely-attached TPU
    # plugin HANGS (not errors) when its tunnel is down, which would
    # otherwise pass /readyz and then freeze the first authorization.
    # 0 = skip (tests, CPU-only use); the CLI defaults it on for serving.
    engine_probe_timeout: float = 0.0
    # /debug/config stays 404 unless explicitly enabled — even a sanitized
    # topology dump is opt-in, not default-on
    enable_debug_config: bool = False
    # multi-chip: "auto" (all local devices, graph-majority axes) or
    # "data=D,graph=G"; None/"" = single device. In-process engines only —
    # a tcp:// engine host owns its own mesh.
    engine_mesh: Optional[str] = None
    # "Name=true,Other=false" over utils/features.py gates
    feature_gates: Optional[str] = None
    # API discovery caching (reference disk-cached RESTMapper discovery,
    # server.go:228-243): TTL in seconds; a directory makes it survive
    # restarts. 0 disables caching.
    discovery_cache_ttl: float = 600.0
    discovery_cache_dir: Optional[str] = None
    # -- dependency resilience (utils/resilience.py) -------------------------
    # per-attempt connect budget and per-request total deadline for the
    # upstream kube-apiserver (deadline 0 = unlimited; it covers watch
    # ESTABLISHMENT only, never the long-lived frame stream)
    upstream_connect_timeout: float = 5.0
    upstream_request_deadline: float = 30.0
    # transport retries for idempotent upstream requests (GET/HEAD) that
    # failed before a status line arrived; writes are never retried
    upstream_retries: int = 1
    # tcp:// engine endpoints: per-attempt connect budget, TOTAL
    # response budget per call (shared across retries, so a stalled host
    # stalls a handler for at most this long), and transport retries for
    # read ops (check/lookup/revision — never relationship writes)
    engine_connect_timeout: float = 10.0
    engine_read_timeout: float = 300.0
    engine_retries: int = 2
    # circuit breakers (one for the upstream, one per engine endpoint):
    # consecutive transport failures to open, and how long an open
    # circuit waits before admitting a half-open probe
    breaker_failure_threshold: int = 5
    breaker_reset_seconds: float = 10.0
    # layered retry budgets (utils/resilience.RetryBudget): ONE token
    # bucket per dependency stack — the upstream gets its own, and a
    # single shared bucket spans the whole engine client stack
    # (RemoteEngine transport retries, FailoverEngine re-aims, planner
    # scatter re-issues), so a shard brownout is bounded to
    # burst + ratio × attempts total retries instead of
    # N_layers × N_retries × attempts (metastable-failure guard).
    # ratio = tokens deposited per first attempt; burst = bucket cap.
    # ratio 0 with a huge burst approximates unbudgeted retries.
    retry_budget_ratio: float = 0.1
    retry_budget_burst: float = 20.0
    # -- admission control (admission/) --------------------------------------
    # cost-classed, per-tenant (= authenticated user) fair queueing with
    # an adaptive concurrency limit and priority load shedding in front
    # of every engine-bound request; shed requests get the fail-closed
    # kube 503 + Retry-After. Off by default (today's behavior).
    admission: bool = False
    admission_initial_concurrency: float = 32.0
    admission_min_concurrency: float = 4.0
    admission_max_concurrency: float = 512.0
    admission_tenant_rate: float = 50.0  # fair-share refill, cost units/s
    admission_tenant_burst: float = 100.0  # per-tenant debt cap
    admission_tenant_queue_depth: int = 32
    admission_queue_depth: int = 256  # global bound; lowest priority sheds
    admission_queue_timeout: float = 1.0  # max queue wait before shedding
    # -- observability (obs/) ------------------------------------------------
    # request tracing (obs/trace.py): tail-sampling keep probability for
    # ordinary traces — error/shed/slow traces are ALWAYS kept. 0
    # disables tracing entirely (no spans recorded, /debug/traces 404s).
    trace_sample: float = 0.1
    # traces at or above this request duration are always kept, and a
    # slow-request log line is emitted
    trace_slow_ms: float = 250.0
    # recent-trace ring capacity served by /debug/traces
    trace_ring: int = 256
    # /debug/traces stays 404 unless explicitly enabled (same posture as
    # /debug/config: traces name other subjects' requests and timings)
    enable_debug_traces: bool = False
    # decision audit log (obs/audit.py): file path or "stderr"; None =
    # no audit. One JSON line per authorization decision — denies
    # always, allows rate-capped at audit_allow_rps lines/second.
    audit_log: Optional[str] = None
    audit_allow_rps: float = 10.0
    # live SLO monitor (obs/slo.py): "class=latency_ms:target_pct" list
    # ("check=25:99.9,lookup=100:99"); None = monitor off unless
    # enable_debug_slo turns it on with the default objective set.
    # Burn rates are computed over slo_windows (seconds), sampled every
    # slo_tick_seconds, exposed as slo_* metrics and (flag-gated,
    # authenticated) at /debug/slo.
    slo_objectives: Optional[str] = None
    slo_windows: str = "60,300,3600"
    slo_tick_seconds: float = 5.0
    enable_debug_slo: bool = False
    # -- elastic scale-out (autoscale/, scaleout/frontier.py) ----------------
    # SLO-driven autoscaler: "off" (default), "dry-run" (proposals are
    # counted and surfaced on /readyz, nothing moves), or "apply"
    # (proposals drive REAL grow/shrink map transitions through the
    # rebalance coordinator). Requires --shard-map.
    autoscale: str = "off"
    # policy knobs as key=value CSV (autoscale/policy.py parse_policy),
    # e.g. "max_groups=6,grow_occupancy=0.7"; None = all defaults
    autoscale_policy: Optional[str] = None
    autoscale_tick_seconds: float = 15.0
    # cross-shard frontier exchange (scaleout/frontier.py): lifts the
    # cluster-scoped-only restriction on cross-namespace reference
    # types by iterating boundary-frontier rounds instead of
    # replicating tuples; fail-closed after frontier_max_rounds
    frontier_exchange: bool = False
    frontier_max_rounds: int = 8

    def _parse_remote(self) -> Optional[list[tuple[str, int]]]:
        """[(host, port), ...] for tcp:// endpoints, None otherwise;
        raises on a malformed endpoint. A COMMA-SEPARATED list
        (``tcp://h1:p1,h2:p2`` — repeating the tcp:// prefix is
        tolerated) names a replicated engine set with automatic
        client-side leader failover (engine/remote.py FailoverEngine).
        The host:port list grammar itself has ONE owner —
        ``parallel/failover.py parse_peers`` (the engine host's --peers
        flag) — so the two flags can never drift apart."""
        if not self.engine_endpoint.startswith(REMOTE_ENDPOINT_PREFIX):
            return None
        from ..parallel.failover import FailoverError, parse_peers

        stripped = ",".join(
            p.strip()[len(REMOTE_ENDPOINT_PREFIX):]
            if p.strip().startswith(REMOTE_ENDPOINT_PREFIX) else p.strip()
            for p in self.engine_endpoint.split(","))
        try:
            return parse_peers(stripped)
        except FailoverError:
            raise OptionsError(
                f"invalid engine endpoint {self.engine_endpoint!r} "
                "(expected tcp://host:port[,host2:port2,...])") from None

    def validate(self) -> None:
        remote = self._parse_remote()
        if self.shard_map:
            if remote is not None:
                raise OptionsError(
                    "shard-map and a tcp:// engine-endpoint are mutually "
                    "exclusive: the shard map names every group's "
                    "endpoints itself")
            for bad, why in (
                    (self.bootstrap_files or self.bootstrap_content,
                     "bootstrap"),
                    (self.snapshot_path, "snapshot-path"),
                    (self.data_dir, "data-dir"),
                    (self.lookup_batch_window > 0, "lookup-batch-window"),
                    (self.engine_mesh, "engine-mesh")):
                if bad:
                    raise OptionsError(
                        f"{why} applies to in-process engines; with "
                        "--shard-map each engine group owns its own")
            from ..scaleout import ShardMapError, load_shard_map

            try:
                smap = load_shard_map(self.shard_map)
            except ShardMapError as e:
                raise OptionsError(str(e)) from None
            if self.rebalance_to:
                try:
                    target = load_shard_map(self.rebalance_to)
                except ShardMapError as e:
                    raise OptionsError(
                        f"rebalance-to: {e}") from None
                if target.version <= smap.version:
                    raise OptionsError(
                        f"rebalance-to map version {target.version} "
                        f"must exceed the current shard-map version "
                        f"{smap.version}")
                if target.n_groups < smap.n_groups - 1:
                    raise OptionsError(
                        "rebalance-to can retire at most ONE group per "
                        "map version: group indices are identity across "
                        "a transition, and a shrink drains + GCs the "
                        "retiring tail group before commit — chain "
                        "single-group shrinks to go further")
                if target.n_groups == smap.n_groups - 1 \
                        and target.groups != smap.groups[:-1]:
                    raise OptionsError(
                        "a shrink map must keep the surviving groups' "
                        "endpoints byte-identical and retire only the "
                        "LAST group (ring points are keyed by group "
                        "index; reordering would silently remap "
                        "untouched slices)")
        elif self.rebalance_to:
            raise OptionsError(
                "rebalance-to requires --shard-map (it is a transition "
                "between two shard maps)")
        if self.autoscale not in ("off", "dry-run", "apply"):
            raise OptionsError(
                f"autoscale must be off, dry-run, or apply "
                f"(got {self.autoscale!r})")
        if self.autoscale != "off" and not self.shard_map:
            raise OptionsError(
                "autoscale requires --shard-map (it proposes and "
                "drives shard-map transitions)")
        if self.autoscale_policy is not None:
            from ..autoscale import AutoscaleError, parse_policy

            try:
                parse_policy(self.autoscale_policy)
            except AutoscaleError as e:
                raise OptionsError(f"autoscale-policy: {e}") from None
        if self.frontier_exchange and not self.shard_map:
            raise OptionsError(
                "frontier-exchange requires --shard-map (it is a "
                "cross-shard join protocol)")
        if self.frontier_max_rounds < 1:
            raise OptionsError("frontier-max-rounds must be >= 1")
        if self.migrate_schema:
            # parse NOW: an unreadable or syntactically-broken target
            # schema must fail option validation, not surface later as
            # a failed migration against a serving engine
            from ..models.schema import SchemaError, parse_schema

            try:
                with open(self.migrate_schema) as f:
                    parse_schema(f.read())
            except OSError as e:
                raise OptionsError(f"migrate-schema: {e}") from None
            except SchemaError as e:
                raise OptionsError(f"migrate-schema: {e}") from None
        if remote is None and self.engine_endpoint not in (EMBEDDED_ENDPOINT,
                                                           TPU_ENDPOINT):
            raise OptionsError(
                f"unsupported engine endpoint {self.engine_endpoint!r} "
                f"(supported: {EMBEDDED_ENDPOINT}, {TPU_ENDPOINT}, "
                f"{REMOTE_ENDPOINT_PREFIX}host:port)")
        if remote and (self.bootstrap_files or self.bootstrap_content):
            raise OptionsError(
                "bootstrap applies to in-process engines; a tcp:// engine "
                "host owns its own bootstrap")
        if remote and self.snapshot_path:
            raise OptionsError(
                "snapshot-path applies to in-process engines; pass it to "
                "the tcp:// engine host instead")
        if remote and self.data_dir:
            raise OptionsError(
                "data-dir applies to in-process engines; pass it to "
                "the tcp:// engine host instead")
        if self.data_dir and self.snapshot_path:
            raise OptionsError(
                "data-dir and snapshot-path are mutually exclusive (the "
                "data dir owns snapshots AND the write-ahead log)")
        if self.data_dir:
            from ..persistence.wal import WalError, parse_fsync_policy

            try:
                parse_fsync_policy(self.wal_fsync)
            except WalError as e:
                raise OptionsError(str(e)) from None
            if self.checkpoint_wal_bytes < 1 \
                    or self.checkpoint_wal_records < 1:
                raise OptionsError(
                    "checkpoint-wal-bytes/records must be >= 1")
            if self.checkpoint_keep < 1:
                raise OptionsError("checkpoint-keep must be >= 1")
        if remote and self.lookup_batch_window > 0:
            raise OptionsError(
                "lookup-batch-window applies to in-process engines; batch "
                "on the tcp:// engine host instead")
        if remote and self.engine_mesh:
            raise OptionsError(
                "engine-mesh applies to in-process engines; configure the "
                "mesh on the tcp:// engine host instead")
        if remote is None and not self.shard_map and (
                self.engine_insecure or self.engine_ca_file or
                self.engine_skip_verify_ca or self.engine_client_cert_file
                or self.engine_server_name):
            raise OptionsError(
                "engine-insecure/ca-file/skip-verify-ca/client-cert/"
                "server-name apply only to tcp:// engine endpoints "
                "(or shard-map groups)")
        if self.engine_insecure and (
                self.engine_ca_file or self.engine_skip_verify_ca or
                self.engine_client_cert_file or self.engine_server_name):
            raise OptionsError(
                "engine-insecure (plaintext) excludes the TLS options "
                "(engine-ca-file/skip-verify-ca/client-cert/server-name)")
        if bool(self.engine_client_cert_file) != \
                bool(self.engine_client_key_file):
            raise OptionsError(
                "engine-client-cert-file and engine-client-key-file "
                "must be set together")
        if self.engine_mesh:
            _parse_mesh_spec(self.engine_mesh)  # raises OptionsError
        if self.feature_gates:
            from ..utils.features import FeatureGateError, features

            try:
                features.validate_spec(self.feature_gates)
            except FeatureGateError as e:
                raise OptionsError(str(e)) from None
        if self.lock_mode not in (LOCK_MODE_PESSIMISTIC, LOCK_MODE_OPTIMISTIC):
            raise OptionsError(f"invalid lock mode {self.lock_mode!r}")
        if self.upstream_retries < 0 or self.engine_retries < 0:
            raise OptionsError("retry counts must be >= 0")
        if self.upstream_connect_timeout <= 0 \
                or self.engine_connect_timeout <= 0 \
                or self.engine_read_timeout <= 0:
            raise OptionsError("connect/read timeouts must be > 0")
        if self.upstream_request_deadline < 0:
            raise OptionsError(
                "upstream-request-deadline must be >= 0 (0 = unlimited)")
        if self.breaker_failure_threshold < 1:
            raise OptionsError("breaker-failure-threshold must be >= 1")
        if self.breaker_reset_seconds < 0:
            raise OptionsError("breaker-reset-seconds must be >= 0")
        if self.retry_budget_ratio < 0:
            raise OptionsError("retry-budget-ratio must be >= 0")
        if self.retry_budget_burst < 1:
            raise OptionsError("retry-budget-burst must be >= 1")
        if self.admission:
            from ..admission import validate_config

            try:
                # ONE owner for the bounds, shared with the engine-host
                # CLI so the two flag surfaces can never drift
                validate_config(
                    self.admission_initial_concurrency,
                    self.admission_min_concurrency,
                    self.admission_max_concurrency,
                    self.admission_tenant_rate,
                    self.admission_tenant_burst,
                    self.admission_tenant_queue_depth,
                    self.admission_queue_depth,
                    self.admission_queue_timeout)
            except ValueError as e:
                raise OptionsError(str(e)) from None
        if not 0.0 <= self.trace_sample <= 1.0:
            raise OptionsError("trace-sample must be in [0, 1]")
        if self.trace_slow_ms < 0:
            raise OptionsError("trace-slow-ms must be >= 0")
        if self.trace_ring < 1:
            raise OptionsError("trace-ring must be >= 1")
        if self.audit_allow_rps <= 0:
            raise OptionsError("audit-allow-rps must be > 0")
        if self.slo_objectives:
            from ..obs.slo import SLOError, parse_objectives

            try:
                parse_objectives(self.slo_objectives)
            except SLOError as e:
                raise OptionsError(str(e)) from None
        if self.slo_objectives or self.enable_debug_slo:
            try:
                windows = [float(w) for w in
                           self.slo_windows.split(",") if w.strip()]
            except ValueError:
                windows = []
            if not windows or any(w <= 0 for w in windows):
                raise OptionsError(
                    "slo-windows must be a comma list of seconds > 0")
            if self.slo_tick_seconds <= 0:
                raise OptionsError("slo-tick-seconds must be > 0")
            if self.slo_tick_seconds > min(windows):
                raise OptionsError(
                    "slo-tick-seconds must not exceed the shortest "
                    "slo-window (a window sampled less than once per "
                    "span would be blind)")
        if self.authz_cache_size < 1:
            raise OptionsError("authz-cache-size must be >= 1")
        if self.authz_cache_mask_bytes < 0:
            raise OptionsError("authz-cache-mask-bytes must be >= 0")
        from ..engine.compaction import validate_overlay_config

        try:
            # ONE owner for the overlay flag bounds, shared with the
            # engine-host CLI
            validate_overlay_config(self.delta_capacity,
                                    self.compact_threshold)
        except ValueError as e:
            raise OptionsError(str(e)) from None
        if self.device_graph_budget_bytes < 0:
            raise OptionsError("device-graph-budget-bytes must be >= 0 "
                               "(0 disables tiered graph storage)")
        if not (self.caveat_ip_header or "").strip():
            raise OptionsError("caveat-ip-header must not be empty "
                               "(set --caveat-context=false to disable "
                               "request context instead)")
        if bool(self.tls_cert_file) != bool(self.tls_key_file):
            raise OptionsError(
                "tls-cert-file and tls-key-file must be set together")
        if self.tls_client_ca_file and not self.tls_cert_file:
            raise OptionsError(
                "tls-client-ca-file requires tls-cert-file/tls-key-file")
        if self.tls_requestheader_allowed_names and \
                not self.tls_client_ca_file:
            raise OptionsError(
                "tls-requestheader-allowed-names requires "
                "tls-client-ca-file")
        if self.oidc_issuer_url and not self.oidc_client_id:
            raise OptionsError("oidc-issuer-url requires oidc-client-id")
        if not self.oidc_issuer_url and (
                self.oidc_required_claims or any(
                    x is not None for x in (
                        self.oidc_client_id, self.oidc_username_prefix,
                        self.oidc_groups_claim, self.oidc_ca_file))):
            raise OptionsError(
                "oidc-* options require oidc-issuer-url")
        for rc in self.oidc_required_claims:
            if "=" not in rc:
                raise OptionsError(
                    f"oidc-required-claim {rc!r} must be key=value")
        if self.oidc_issuer_url:
            from .oidc import OIDCError, parse_signing_algs

            try:
                parse_signing_algs(self.oidc_signing_algs)
            except OIDCError as e:
                raise OptionsError(f"oidc-signing-algs: {e}") from None
        if not (self.rule_files or self.rule_content):
            raise OptionsError("at least one rule file is required")
        if self.upstream_url and self.kubeconfig:
            raise OptionsError(
                "upstream-url and kubeconfig are mutually exclusive")
        if self.kubeconfig_context and not self.kubeconfig:
            raise OptionsError("kubeconfig-context requires kubeconfig")
        if not self.upstream_url and any((
                self.upstream_token, self.upstream_ca_file,
                self.upstream_client_cert, self.upstream_client_key,
                self.upstream_insecure)):
            raise OptionsError(
                "upstream-token/ca-file/client-cert/client-key/insecure "
                "only apply with upstream-url; kubeconfig and in-cluster "
                "modes carry their own credentials")
        if self.upstream is None and not self.upstream_url \
                and not self.kubeconfig:
            from .kubeconfig import in_cluster_available

            if not in_cluster_available():
                raise OptionsError(
                    "an upstream kube-apiserver is required: pass "
                    "--upstream-url or --kubeconfig, or run in-cluster")

    def complete(self) -> "CompletedConfig":
        self.validate()
        if self.feature_gates:
            from ..utils.features import features

            features.apply_spec(self.feature_gates)
        rule_text = "\n---\n".join(
            [open(f).read() for f in self.rule_files]
            + ([self.rule_content] if self.rule_content else []))
        matcher = MapMatcher.from_yaml(rule_text)
        remote = self._parse_remote()
        if remote is not None or self.shard_map:
            from ..engine.remote import FailoverEngine, RemoteEngine

            ssl_context = None
            if not self.engine_insecure:
                from ..utils.tlsconf import (
                    TLSConfigError,
                    client_ssl_context,
                )

                try:
                    ssl_context = client_ssl_context(
                        self.engine_ca_file, self.engine_skip_verify_ca,
                        self.engine_client_cert_file,
                        self.engine_client_key_file)
                except TLSConfigError as e:
                    raise OptionsError(str(e)) from None
            from ..utils.resilience import RetryBudget

            # ONE budget for the WHOLE engine client stack: every
            # group's RemoteEngine/FailoverEngine and the planner's
            # scatter re-issues draw from the same bucket
            engine_budget = RetryBudget(
                "engine-stack", ratio=self.retry_budget_ratio,
                burst=self.retry_budget_burst)
            client_kw = dict(
                ssl_context=ssl_context,
                server_hostname=self.engine_server_name,
                connect_timeout=self.engine_connect_timeout,
                timeout=self.engine_read_timeout,
                retries=self.engine_retries,
                breaker_failure_threshold=self.breaker_failure_threshold,
                breaker_reset_seconds=self.breaker_reset_seconds,
                retry_budget=engine_budget)
            if self.shard_map:
                # scale-out (scaleout/): one client per engine GROUP
                # (multi-endpoint groups get client-side leader
                # failover), a scatter-gather planner in front, and a
                # durable split-write journal beside the workflow DB
                from ..scaleout import (
                    ShardedEngine,
                    ShardMapError,
                    ShardVectorCache,
                    SplitJournal,
                    load_shard_map,
                )

                try:
                    # validate() parsed this already, but the file can
                    # change between the two reads — the second load
                    # must fail as cleanly as the first
                    smap = load_shard_map(self.shard_map)
                except ShardMapError as e:
                    raise OptionsError(str(e)) from None
                def group_client(eps):
                    if len(eps) == 1:
                        return RemoteEngine(*eps[0],
                                            token=self.engine_token,
                                            **client_kw)
                    return FailoverEngine(list(eps),
                                          token=self.engine_token,
                                          **client_kw)

                groups = [group_client(eps) for eps in smap.groups]
                journal_path = self.shard_journal_path
                if journal_path is None:
                    import os as _osj

                    base = self.workflow_database_path \
                        or DEFAULT_WORKFLOW_DB
                    journal_path = _osj.path.join(
                        _osj.path.dirname(_osj.path.abspath(base)),
                        "scaleout-journal.sqlite")
                frontier_cfg = None
                if self.frontier_exchange:
                    from ..scaleout import FrontierConfig

                    frontier_cfg = FrontierConfig(
                        max_rounds=self.frontier_max_rounds)
                engine = ShardedEngine(
                    smap, groups, journal=SplitJournal(journal_path),
                    cache=(ShardVectorCache() if self.shard_cache
                           else None),
                    retry_budget=engine_budget,
                    frontier=frontier_cfg,
                    # lets a persisted mid-rebalance transition
                    # reconstruct clients for groups the target map
                    # ADDED beyond --shard-map at the next boot
                    client_factory=group_client)
                if self.rebalance_to:
                    from ..scaleout import (
                        RebalanceError,
                        ShardMapError as _SME,
                        load_shard_map as _load_target,
                    )

                    try:
                        # validate() parsed this already, but the file
                        # can change between the two reads — the second
                        # load must fail as cleanly as the first
                        target = _load_target(self.rebalance_to)
                    except _SME as e:
                        raise OptionsError(
                            f"rebalance-to: {e}") from None
                    active = engine._active_transition
                    if active is not None:
                        # a persisted transition already resumed at
                        # recovery; the flag must agree with it
                        if active.new_map.version != target.version:
                            raise OptionsError(
                                "rebalance-to names map version "
                                f"{target.version} but a transition to "
                                f"version {active.new_map.version} is "
                                "already in flight")
                    elif target.version <= engine.map.version:
                        # the move already completed (the journal's
                        # durable "done" record made the target map
                        # authoritative at recovery) — re-running it
                        # against the GC'd sources would route the
                        # moved slices to empty groups
                        import logging as _logging

                        _logging.getLogger("sdbkp.options").info(
                            "rebalance-to v%d already completed; "
                            "serving it (update --shard-map and drop "
                            "the flag)", target.version)
                    else:
                        try:
                            engine.begin_rebalance(target)
                        except RebalanceError as e:
                            raise OptionsError(str(e)) from None
            elif len(remote) == 1:
                engine = RemoteEngine(*remote[0],
                                      token=self.engine_token,
                                      **client_kw)
            else:
                # a replicated engine set: route to the current leader,
                # re-resolve on its death (kill-the-leader failover)
                engine = FailoverEngine(remote, token=self.engine_token,
                                        **client_kw)
        else:
            import os as _os

            if _os.environ.get("JAX_PLATFORMS") == "cpu":
                # honor an explicit cpu request IN-PROCESS too: the
                # image's sitecustomize override would otherwise attach
                # the TPU plugin here even though the probe subprocess
                # (which applies the same guard) reported cpu
                import jax as _jax

                try:
                    _jax.config.update("jax_platforms", "cpu")
                except Exception:  # already initialized: keep selection
                    pass
            if self.engine_probe_timeout > 0:
                _probe_device_backend(self.engine_probe_timeout)
            bootstrap = "\n---\n".join(
                [open(f).read() for f in self.bootstrap_files]
                + ([self.bootstrap_content] if self.bootstrap_content else []))
            mesh = None
            if self.engine_mesh:
                from ..parallel import make_mesh

                mesh = make_mesh(**_parse_mesh_spec(self.engine_mesh))
            engine = Engine(bootstrap=bootstrap or None, mesh=mesh,
                            delta_capacity=self.delta_capacity,
                            device_graph_budget_bytes=(
                                self.device_graph_budget_bytes or None))
            if self.compact_threshold > 0:
                # background overlay folds + overlay-full write
                # back-pressure (engine/compaction.py); 0 restores the
                # synchronous-recompile fallback on overflow
                engine.enable_compaction(self.compact_threshold)
            if self.data_dir:
                engine.enable_persistence(
                    self.data_dir, wal_fsync=self.wal_fsync,
                    checkpoint_wal_bytes=self.checkpoint_wal_bytes,
                    checkpoint_wal_records=self.checkpoint_wal_records,
                    checkpoint_keep=self.checkpoint_keep)
                # boot crash matrix for a live schema migration killed
                # mid-flight (migration/migrator.py): no persisted cut
                # -> clean abort, cut persisted -> finish the cutover
                engine.recover_schema_migration()
            else:
                engine.load_snapshot_if_exists(self.snapshot_path)
            if self.lookup_batch_window > 0:
                engine.enable_lookup_batching(self.lookup_batch_window)
            if self.authz_cache:
                engine.enable_decision_cache(
                    max_entries=self.authz_cache_size,
                    max_mask_bytes=self.authz_cache_mask_bytes)
        if self.migrate_schema:
            # start the live migration once the engine is fully
            # configured (persistence recovered, caches installed):
            # every engine shape takes it — in-process and sharded via
            # begin_schema_migration, a tcp:// host via the wire op. An
            # incompatible change fails BOOT with the typed reasons;
            # the serving engine never saw any state change.
            from ..models.schema import SchemaError as _SchemaErr

            with open(self.migrate_schema) as f:
                _mig_text = f.read()
            # the bootstrap path auto-appends the workflow definitions
            # (models/bootstrap.py): give the migration target the same
            # treatment, or omitting them from the operator's file
            # would falsely classify as "removed definition"
            import re as _re

            from ..models.bootstrap import WORKFLOW_DEFS as _WF

            _missing = [n for n in ("lock", "workflow", "activity")
                        if not _re.search(
                            rf"definition\s+{n}\b", _mig_text)]
            if _missing:
                _mig_text = "\n".join(
                    [_mig_text] + [_WF[n] for n in _missing])
            try:
                if hasattr(engine, "begin_schema_migration"):
                    engine.begin_schema_migration(_mig_text)
                else:
                    engine.migrate_begin(_mig_text)
            except _SchemaErr as e:
                raise OptionsError(
                    f"migrate-schema: {e}") from None
        upstream = self.upstream
        if upstream is None:
            from ..utils.resilience import RetryBudget as _RB
            from .kubeconfig import UpstreamConfig

            if self.upstream_url:
                uc = UpstreamConfig(
                    url=self.upstream_url,
                    token=self.upstream_token,
                    ca_file=self.upstream_ca_file,
                    client_cert=self.upstream_client_cert,
                    client_key=self.upstream_client_key,
                    insecure_skip_verify=self.upstream_insecure,
                )
            elif self.kubeconfig:
                from .kubeconfig import load_kubeconfig

                uc = load_kubeconfig(self.kubeconfig,
                                     self.kubeconfig_context)
            else:
                from .kubeconfig import in_cluster_config

                uc = in_cluster_config()
            upstream = HttpUpstream(
                uc.url,
                token=uc.token,
                ca_file=uc.ca_file,
                client_cert=uc.client_cert,
                client_key=uc.client_key,
                insecure_skip_verify=uc.insecure_skip_verify,
                connect_timeout=self.upstream_connect_timeout,
                request_deadline=self.upstream_request_deadline,
                retries=self.upstream_retries,
                breaker_failure_threshold=self.breaker_failure_threshold,
                breaker_reset_seconds=self.breaker_reset_seconds,
                retry_budget=_RB("upstream",
                                 ratio=self.retry_budget_ratio,
                                 burst=self.retry_budget_burst),
            )
        # durable dual-writes live with the durable store: an unset path
        # lands the workflow DB inside --data-dir when one is configured
        wf_db = self.workflow_database_path
        if wf_db is None:
            if self.data_dir:
                import os as _os2

                _os2.makedirs(self.data_dir, exist_ok=True)
                wf_db = _os2.path.join(self.data_dir, "dtx.sqlite")
            else:
                wf_db = DEFAULT_WORKFLOW_DB
        workflow = WorkflowEngine(db_path=wf_db)
        register_workflows(workflow)
        ActivityHandler(engine, upstream).register(workflow)
        discovery_cache = None
        if self.discovery_cache_ttl > 0:
            from ..utils.discovery import DiscoveryCache

            discovery_cache = DiscoveryCache(
                ttl=self.discovery_cache_ttl,
                cache_dir=self.discovery_cache_dir)
        # breakers surface on /readyz with per-dependency reasons; an
        # injected upstream/engine without one simply isn't tracked.
        # A sharded planner contributes one breaker PER GROUP (its own
        # clients'), so /readyz names the degraded group
        engine_breakers = [getattr(engine, "breaker", None)]
        for g in getattr(engine, "groups", ()):
            engine_breakers.append(getattr(g, "breaker", None))
        dep_breakers = tuple(
            b for b in ([getattr(upstream, "breaker", None)]
                        + engine_breakers) if b is not None)
        admission = None
        if self.admission:
            from ..admission import AdmissionController

            admission = AdmissionController(
                initial_concurrency=self.admission_initial_concurrency,
                min_concurrency=self.admission_min_concurrency,
                max_concurrency=self.admission_max_concurrency,
                tenant_rate=self.admission_tenant_rate,
                tenant_burst=self.admission_tenant_burst,
                tenant_depth=self.admission_tenant_queue_depth,
                global_depth=self.admission_queue_depth,
                queue_timeout=self.admission_queue_timeout,
                dependency="admission")
        # observability: the tracer is process-global (the engine and
        # remote client record spans through it); configure from flags
        # here, the ONE place serving configuration lands
        from ..obs import AuditLog
        from ..obs.trace import tracer

        tracer.configure(sample=self.trace_sample,
                         slow_ms=self.trace_slow_ms,
                         ring=self.trace_ring)
        audit = None
        if self.audit_log:
            audit = AuditLog(self.audit_log,
                             allow_rps=self.audit_allow_rps)
        slo_monitor = None
        if self.slo_objectives or self.enable_debug_slo:
            from ..obs.slo import (
                SLOMonitor,
                default_objectives,
                parse_objectives,
            )

            objectives = (parse_objectives(self.slo_objectives)
                          if self.slo_objectives else default_objectives())
            slo_monitor = SLOMonitor(
                objectives,
                windows=[float(w) for w in self.slo_windows.split(",")
                         if w.strip()],
                tick_seconds=self.slo_tick_seconds)
            slo_monitor.start()
        autoscale_controller = None
        if self.autoscale != "off" and self.shard_map:
            from ..autoscale import (
                AutoscaleController,
                AutoscalePolicy,
                PolicyConfig,
                parse_policy,
            )

            policy_cfg = (parse_policy(self.autoscale_policy)
                          if self.autoscale_policy else PolicyConfig())
            autoscale_controller = AutoscaleController(
                engine, AutoscalePolicy(policy_cfg),
                mode=self.autoscale,
                slo_monitor=slo_monitor,
                tick_seconds=self.autoscale_tick_seconds)
            autoscale_controller.start()
        deps = AuthzDeps(
            matcher=matcher, engine=engine, upstream=upstream,
            workflow=workflow, default_lock_mode=self.lock_mode,
            discovery_cache=discovery_cache,
            breakers=dep_breakers,
            admission=admission,
            audit=audit,
            caveat_context_enabled=self.caveat_context,
            caveat_ip_header=self.caveat_ip_header,
        )
        ssl_context = None
        if self.tls_cert_file:
            import ssl

            ssl_context = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ssl_context.load_cert_chain(self.tls_cert_file,
                                        self.tls_key_file)
            if self.tls_client_ca_file:
                ssl_context.load_verify_locations(self.tls_client_ca_file)
                # OPTIONAL, not REQUIRED: cert-less clients still reach
                # health endpoints and get clean 401s on resources
                # (kube-apiserver semantics) instead of handshake failures
                ssl_context.verify_mode = ssl.CERT_OPTIONAL
        token_authenticators = []
        if self.token_auth_file:
            from .authn import TokenFileAuthenticator

            token_authenticators.append(
                TokenFileAuthenticator(self.token_auth_file))
        if self.oidc_issuer_url:
            from .oidc import OIDCAuthenticator, parse_signing_algs

            token_authenticators.append(OIDCAuthenticator(
                issuer_url=self.oidc_issuer_url,
                client_id=self.oidc_client_id,
                username_claim=self.oidc_username_claim,
                username_prefix=self.oidc_username_prefix,
                groups_claim=self.oidc_groups_claim,
                groups_prefix=self.oidc_groups_prefix,
                ca_file=self.oidc_ca_file,
                required_claims=dict(
                    rc.split("=", 1) for rc in self.oidc_required_claims),
                signing_algs=parse_signing_algs(self.oidc_signing_algs),
            ))
        token_authenticator = None
        if len(token_authenticators) == 1:
            token_authenticator = token_authenticators[0]
        elif token_authenticators:
            from .oidc import ChainTokenAuthenticator

            token_authenticator = ChainTokenAuthenticator(
                token_authenticators)
        server = Server(deps, HeaderAuthenticator(),
                        host=self.bind_host, port=self.bind_port,
                        config_dump=(self.debug_dump()
                                     if self.enable_debug_config else None),
                        ssl_context=ssl_context,
                        client_ca_configured=bool(self.tls_client_ca_file),
                        requestheader_allowed_names=tuple(
                            self.tls_requestheader_allowed_names),
                        token_authenticator=token_authenticator,
                        enable_debug_traces=self.enable_debug_traces,
                        slo_monitor=slo_monitor,
                        enable_debug_slo=self.enable_debug_slo,
                        autoscale_controller=autoscale_controller)
        return CompletedConfig(self, engine, workflow, deps, server,
                               slo_monitor, autoscale_controller)

    # fields safe to expose on /debug/config — an ALLOWLIST so a future
    # credential-bearing Options field fails safe (omitted) instead of
    # leaking until someone extends a denylist
    _DUMP_FIELDS = (
        "engine_endpoint", "engine_mesh", "bootstrap_files", "rule_files",
        "upstream_url", "upstream_insecure", "kubeconfig",
        "kubeconfig_context", "bind_host", "bind_port",
        "workflow_database_path", "lock_mode", "snapshot_path",
        "data_dir", "wal_fsync", "checkpoint_wal_bytes",
        "checkpoint_wal_records", "checkpoint_keep",
        "authz_cache", "authz_cache_size", "authz_cache_mask_bytes",
        "delta_capacity", "compact_threshold",
        "device_graph_budget_bytes",
        "caveat_context", "caveat_ip_header",
        "shard_map", "shard_journal_path", "shard_cache",
        "rebalance_to", "migrate_schema",
        "upstream_connect_timeout", "upstream_request_deadline",
        "upstream_retries", "engine_connect_timeout", "engine_read_timeout",
        "engine_retries", "breaker_failure_threshold",
        "breaker_reset_seconds",
        "retry_budget_ratio", "retry_budget_burst",
        "admission", "admission_initial_concurrency",
        "admission_min_concurrency", "admission_max_concurrency",
        "admission_tenant_rate", "admission_tenant_burst",
        "admission_tenant_queue_depth", "admission_queue_depth",
        "admission_queue_timeout",
        "trace_sample", "trace_slow_ms", "trace_ring",
        "enable_debug_traces", "audit_log", "audit_allow_rps",
        "slo_objectives", "slo_windows", "slo_tick_seconds",
        "enable_debug_slo",
        "autoscale", "autoscale_policy", "autoscale_tick_seconds",
        "frontier_exchange", "frontier_max_rounds",
    )

    def debug_dump(self) -> dict:
        """Secret-free options dump for /debug/config (the reference
        sanitizes via debugmap struct tags, options.go:50-82)."""
        out = {k: getattr(self, k) for k in self._DUMP_FIELDS}
        for k in ("upstream_token", "engine_token"):
            out[k] = "<redacted>" if getattr(self, k) else None
        return out


@dataclass
class CompletedConfig:
    options: Options
    engine: Engine
    workflow: WorkflowEngine
    deps: AuthzDeps
    server: Server
    slo_monitor: Optional[object] = None
    autoscale_controller: Optional[object] = None

    async def run(self) -> None:
        """Start serving: resume pending dual-writes, listen, serve
        (reference Server.Run errgroup, server.go:164-202)."""
        await self.workflow.resume_pending()
        await self.server.start()


def add_flags(parser: argparse.ArgumentParser) -> None:
    """CLI flags (reference AddFlags, options.go:196-207)."""
    parser.add_argument("--engine-endpoint", default=TPU_ENDPOINT,
                        help="embedded:// | tpu:// (in-process TPU engine) "
                             "| tcp://host:port (remote engine host) | "
                             "tcp://h1:p1,h2:p2,... (a replicated engine "
                             "set: requests follow the leader, with "
                             "automatic client-side failover when it "
                             "dies — see docs/operations.md)")
    parser.add_argument("--engine-token",
                        help="bearer token for tcp:// engine endpoints")
    parser.add_argument("--engine-insecure", action="store_true",
                        help="PLAINTEXT TCP to the engine host (token and "
                             "relationships in the clear); TLS with full "
                             "verification is the default")
    parser.add_argument("--engine-ca-file",
                        help="CA bundle for verifying the engine host's "
                             "certificate (default: system trust store)")
    parser.add_argument("--engine-skip-verify-ca", action="store_true",
                        help="TLS to the engine host without certificate "
                             "verification")
    parser.add_argument("--engine-client-cert-file",
                        help="client certificate for mutual TLS to the "
                             "engine host")
    parser.add_argument("--engine-client-key-file",
                        help="client key for mutual TLS to the engine host")
    parser.add_argument("--engine-server-name",
                        help="expected certificate name when dialing an "
                             "address that is not the cert's name")
    parser.add_argument("--bootstrap", action="append", default=[],
                        help="schema/relationships bootstrap YAML (repeatable)")
    parser.add_argument("--rule-file", action="append", default=[],
                        help="ProxyRule YAML file (repeatable)")
    parser.add_argument("--upstream-url", help="upstream kube-apiserver URL")
    parser.add_argument("--kubeconfig",
                        help="kubeconfig file for the upstream connection "
                             "(alternative to --upstream-url; in-cluster "
                             "config is used when neither is given)")
    parser.add_argument("--kubeconfig-context",
                        help="kubeconfig context (default: current-context)")
    parser.add_argument("--upstream-token", help="bearer token for upstream")
    parser.add_argument("--upstream-ca-file")
    parser.add_argument("--upstream-client-cert")
    parser.add_argument("--upstream-client-key")
    parser.add_argument("--upstream-insecure", action="store_true")
    parser.add_argument("--bind-host", default="127.0.0.1")
    parser.add_argument("--bind-port", type=int, default=8443)
    parser.add_argument("--tls-cert-file",
                        help="serving certificate (enables HTTPS)")
    parser.add_argument("--tls-key-file",
                        help="serving certificate private key")
    parser.add_argument("--tls-client-ca-file",
                        help="CA bundle for client-certificate "
                             "authentication (CN -> user, O -> groups)")
    parser.add_argument("--tls-requestheader-allowed-name",
                        action="append", default=[],
                        dest="tls_requestheader_allowed_names",
                        help="cert CN allowed to assert user identity via "
                             "X-Remote-* headers (repeatable; front "
                             "proxies)")
    parser.add_argument("--token-auth-file",
                        help="kube static token file "
                             "(token,user,uid[,\"g1,g2\"]) for Bearer "
                             "authentication")
    parser.add_argument("--oidc-issuer-url",
                        help="OIDC issuer URL; enables bearer-JWT "
                             "authentication against its JWKS")
    parser.add_argument("--oidc-client-id",
                        help="audience the token must be issued for")
    parser.add_argument("--oidc-username-claim", default="sub")
    parser.add_argument("--oidc-username-prefix",
                        help="prefix for OIDC usernames; '-' disables; "
                             "default '<issuer>#' for non-email claims")
    parser.add_argument("--oidc-groups-claim",
                        help="claim carrying the user's groups")
    parser.add_argument("--oidc-groups-prefix", default="")
    parser.add_argument("--oidc-ca-file",
                        help="CA bundle for the issuer's HTTPS endpoints")
    parser.add_argument("--oidc-signing-algs", default="RS256",
                        help="comma-separated accepted JWS algorithms")
    parser.add_argument("--oidc-required-claim", action="append",
                        default=[], dest="oidc_required_claims",
                        help="key=value a token must carry verbatim "
                             "(repeatable)")
    parser.add_argument("--workflow-database-path", default=None,
                        help="dual-write workflow DB (sqlite). Default: "
                             "<data-dir>/dtx.sqlite when --data-dir is "
                             f"set, else {DEFAULT_WORKFLOW_DB}")
    parser.add_argument("--snapshot-path",
                        help="relationship-store snapshot file: loaded at "
                             "boot if present, saved on graceful shutdown "
                             "(superseded by --data-dir, which also "
                             "survives SIGKILL)")
    parser.add_argument("--data-dir",
                        help="durable persistence directory: write-ahead "
                             "log + snapshot checkpoints; crash recovery "
                             "replays the WAL tail at boot. Unset = "
                             "in-memory store. In-process engines only")
    parser.add_argument("--wal-fsync", default="interval:100",
                        help="WAL fsync policy: always | interval:<ms> | "
                             "off (default interval:100)")
    parser.add_argument("--checkpoint-wal-bytes", type=int,
                        default=64 << 20,
                        help="snapshot-checkpoint the store once this "
                             "many WAL bytes accumulate since the last "
                             "checkpoint")
    parser.add_argument("--checkpoint-wal-records", type=int,
                        default=50_000,
                        help="...or this many WAL records, whichever "
                             "comes first")
    parser.add_argument("--checkpoint-keep", type=int, default=2,
                        help="snapshot generations to retain (the WAL is "
                             "pruned only up to the oldest kept one)")
    parser.add_argument("--lookup-batch-window", type=float, default=0.0,
                        help="seconds to hold a list prefilter for fusing "
                             "concurrent lookups into one device dispatch "
                             "(0 disables)")
    parser.add_argument("--authz-cache", type=parse_bool_flag,
                        nargs="?", const=True, default=True,
                        metavar="BOOL",
                        help="revision-keyed decision cache + "
                             "singleflight on the authorization hot "
                             "path: identical checks/lookups at an "
                             "unchanged store revision serve host-side "
                             "with zero device dispatches (default on; "
                             "--authz-cache=false disables; in-process "
                             "engines only — pass the same flags to a "
                             "tcp:// engine host)")
    parser.add_argument("--authz-cache-size", type=int, default=65536,
                        help="max cached decisions (LRU entries, check "
                             "verdicts and lookup masks combined)")
    parser.add_argument("--authz-cache-mask-bytes", type=int,
                        default=256 << 20,
                        help="resident lookup-mask byte budget; the "
                             "cold end evicts past it")
    parser.add_argument("--caveat-context", type=parse_bool_flag,
                        nargs="?", const=True, default=True,
                        help="forward request caveat context (client IP "
                             "from --caveat-ip-header, user, verb, "
                             "resource) to the engine so conditional "
                             "grants resolve per request; =false makes "
                             "request-dependent caveats fail closed "
                             "(default: true)")
    parser.add_argument("--caveat-ip-header", default="x-forwarded-for",
                        help="trusted header carrying the client IP for "
                             "IP-allowlist caveats (LAST hop of a "
                             "comma-separated chain — the one the "
                             "trusted LB appended; default: "
                             "x-forwarded-for)")
    parser.add_argument("--delta-capacity", type=int, default=4096,
                        help="device-resident delta-overlay slots per "
                             "compiled graph (fixed — part of the jit "
                             "signature, so writes never re-specialize); "
                             "size to the write burst one compaction "
                             "interval must absorb (in-process engines "
                             "only; pass the same flag to a tcp:// "
                             "engine host)")
    parser.add_argument("--compact-threshold", type=float, default=0.75,
                        help="overlay-occupancy fraction that wakes the "
                             "background compactor folding the delta "
                             "tail into a fresh base off the write path; "
                             "a full overlay then SHEDS writes with a "
                             "bounded Retry-After instead of stalling a "
                             "read on a synchronous recompile (0 "
                             "disables compaction and restores the "
                             "synchronous fallback)")
    parser.add_argument("--device-graph-budget-bytes", type=int,
                        default=0,
                        help="tiered graph storage: device byte budget "
                             "for resident dense graph blocks. Hot "
                             "blocks stay on device under this cap; "
                             "cold blocks live in host arenas and "
                             "stream into dispatches on demand "
                             "(engine_tier_* metrics). 0 keeps the "
                             "classic all-resident placement "
                             "(in-process engines only)")
    parser.add_argument("--shard-map",
                        help="scale-out: explicit versioned shard map "
                             "(JSON file path or inline JSON: "
                             '{"version":1,"groups":[["h:p","h:p"],'
                             '["h:p"]]}). Each group is its own engine '
                             "failover set; tuples partition by "
                             "(namespace, resource-type) consistent "
                             "hashing, cluster-scoped tuples replicate "
                             "to every group. Mutually exclusive with a "
                             "tcp:// --engine-endpoint (see "
                             "docs/operations.md 'Scale-out sharding')")
    parser.add_argument("--shard-journal-path",
                        help="durable cross-shard split-write journal "
                             "(sqlite); default: scaleout-journal.sqlite "
                             "beside the workflow DB. A mid-split crash "
                             "replays to completion at the next boot")
    parser.add_argument("--shard-cache", type=parse_bool_flag,
                        nargs="?", const=True, default=False,
                        metavar="BOOL",
                        help="vector-keyed client-side decision cache: "
                             "entries key by the full per-shard revision "
                             "vector (never serving after ANY component "
                             "shard advances) plus a short TTL, and — "
                             "lacking the hosts' compiled-caveat "
                             "knowledge — by the FULL request caveat "
                             "context, so hit rates need stable caller "
                             "attributes (default off; per-group "
                             "host-side caches stay exact and context-"
                             "digested regardless)")
    parser.add_argument("--rebalance-to",
                        help="online shard rebalance: a TARGET shard "
                             "map (same grammar as --shard-map, HIGHER "
                             "version). Boot starts the live tuple "
                             "mover — copy / catch-up / dual-write / "
                             "per-slice cutover / GC — migrating to "
                             "the new placement with no drain; "
                             "progress on /readyz as 'rebalance: "
                             "moving=K copied=J lag=...' (see "
                             "docs/operations.md 'Rebalancing')")
    parser.add_argument("--migrate-schema",
                        help="live schema migration: a schema-DSL file "
                             "to migrate the serving engine(s) to at "
                             "boot with no downtime — classify / "
                             "dual-compile / journaled backfill / "
                             "atomic cut at a revision (incompatible "
                             "changes refuse with typed reasons before "
                             "any state change); progress on /readyz "
                             "as 'migration: phase=... lag=...' (see "
                             "docs/operations.md 'Live schema "
                             "migration')")
    parser.add_argument("--lock-mode", default=LOCK_MODE_PESSIMISTIC,
                        choices=[LOCK_MODE_PESSIMISTIC, LOCK_MODE_OPTIMISTIC])
    parser.add_argument("--enable-debug-config", action="store_true",
                        help="serve the sanitized options dump on "
                             "/debug/config (off by default)")
    parser.add_argument("--engine-probe-timeout", type=float, default=120.0,
                        help="probe the device backend in a subprocess "
                             "with this timeout before serving (a hung "
                             "TPU attachment fails boot with a clear "
                             "error instead of freezing the first "
                             "request); 0 skips the probe")
    parser.add_argument("--engine-mesh",
                        help="multi-chip device mesh for the in-process "
                             "engine: 'auto' or 'data=D,graph=G'")
    parser.add_argument("--feature-gates",
                        help="comma-separated Name=true|false overrides "
                             "(see utils/features.py for known gates)")
    parser.add_argument("--discovery-cache-ttl", type=float, default=600.0,
                        help="API discovery cache TTL seconds (0 disables)")
    parser.add_argument("--discovery-cache-dir",
                        help="persist the discovery cache here so it "
                             "survives restarts")
    parser.add_argument("--upstream-connect-timeout", type=float,
                        default=5.0,
                        help="per-attempt connect budget to the upstream "
                             "kube-apiserver (seconds)")
    parser.add_argument("--upstream-request-deadline", type=float,
                        default=30.0,
                        help="total per-request deadline to the upstream, "
                             "shared across retries; covers watch "
                             "establishment only, not the stream "
                             "(0 = unlimited)")
    parser.add_argument("--upstream-retries", type=int, default=1,
                        help="transport retries for idempotent upstream "
                             "requests (GET/HEAD) that failed before a "
                             "status line; writes are never retried")
    parser.add_argument("--engine-connect-timeout", type=float,
                        default=10.0,
                        help="per-attempt connect budget to a tcp:// "
                             "engine host (seconds)")
    parser.add_argument("--engine-read-timeout", type=float, default=300.0,
                        help="TOTAL per-call response budget to a tcp:// "
                             "engine host, shared across retries "
                             "(generous: the first query after a "
                             "snapshot refresh pays an XLA compile)")
    parser.add_argument("--engine-retries", type=int, default=2,
                        help="transport retries for engine READ ops "
                             "(check/lookup/revision); relationship "
                             "writes are never retried")
    parser.add_argument("--breaker-failure-threshold", type=int, default=5,
                        help="consecutive transport failures that open a "
                             "dependency's circuit breaker (fail-fast "
                             "503s + /readyz unready until it half-opens)")
    parser.add_argument("--breaker-reset-seconds", type=float, default=10.0,
                        help="how long an open circuit waits before "
                             "admitting a half-open probe")
    parser.add_argument("--retry-budget-ratio", type=float, default=0.1,
                        help="layered retry budget: tokens deposited per "
                             "first attempt (each retry anywhere in the "
                             "dependency stack — transport retry, "
                             "failover re-aim, scatter re-issue — "
                             "withdraws one), bounding steady-state "
                             "retry amplification")
    parser.add_argument("--retry-budget-burst", type=float, default=20.0,
                        help="layered retry budget: bucket capacity (the "
                             "transient-blip allowance before retries "
                             "are rationed to the ratio)")
    parser.add_argument("--admission", type=parse_bool_flag, nargs="?",
                        const=True, default=False, metavar="BOOL",
                        help="admission control: cost-classed, per-tenant "
                             "(= authenticated user) fair queueing with "
                             "an adaptive concurrency limit and priority "
                             "load shedding in front of every "
                             "engine-bound request; overload sheds as "
                             "fail-closed 503 + Retry-After instead of "
                             "queueing unboundedly (default off; see "
                             "docs/operations.md 'Admission control & "
                             "overload')")
    parser.add_argument("--admission-initial-concurrency", type=float,
                        default=32.0,
                        help="adaptive limiter's starting weighted-cost "
                             "limit (check=1, bulk-check/write=2, "
                             "lookup/watch-recompute=4 units)")
    parser.add_argument("--admission-min-concurrency", type=float,
                        default=4.0,
                        help="floor the limiter never drops below")
    parser.add_argument("--admission-max-concurrency", type=float,
                        default=512.0,
                        help="ceiling the limiter never probes past")
    parser.add_argument("--admission-tenant-rate", type=float,
                        default=50.0,
                        help="per-tenant fair-share refill (cost "
                             "units/s): how fast a tenant's consumed "
                             "device time is forgiven")
    parser.add_argument("--admission-tenant-burst", type=float,
                        default=100.0,
                        help="per-tenant debt cap (cost units a storm "
                             "is remembered for)")
    parser.add_argument("--admission-tenant-queue-depth", type=int,
                        default=32,
                        help="max queued requests per tenant")
    parser.add_argument("--admission-queue-depth", type=int, default=256,
                        help="global queued-request bound; past it the "
                             "lowest-priority class sheds first (watch "
                             "ticks, then lists, then checks; writes "
                             "last)")
    parser.add_argument("--admission-queue-timeout", type=float,
                        default=1.0,
                        help="max seconds a request may queue before it "
                             "is shed (503 + Retry-After, never a hang)")
    parser.add_argument("--trace-sample", type=float, default=0.1,
                        help="request-trace tail-sampling keep "
                             "probability (error/shed/slow traces are "
                             "always kept; 0 disables tracing and "
                             "/debug/traces entirely)")
    parser.add_argument("--trace-slow-ms", type=float, default=250.0,
                        help="requests at or above this duration are "
                             "always kept by tail sampling and logged "
                             "as slow, with their trace id")
    parser.add_argument("--trace-ring", type=int, default=256,
                        help="recent-trace ring capacity served by "
                             "/debug/traces")
    parser.add_argument("--enable-debug-traces", action="store_true",
                        help="serve the recent-trace ring on "
                             "/debug/traces (authenticated; off by "
                             "default — traces name other subjects' "
                             "request paths and timings)")
    parser.add_argument("--audit-log", default=None,
                        metavar="PATH|stderr",
                        help="decision audit log destination: one JSON "
                             "line per authorization decision (denies "
                             "always, allows rate-capped; see "
                             "docs/operations.md for the line schema). "
                             "Unset = no audit log")
    parser.add_argument("--slo-objectives", default=None,
                        help="declared SLOs as class=latency_ms:target_pct "
                             "(comma list, e.g. "
                             "'check=25:99.9,lookup=100:99'); enables the "
                             "live burn-rate monitor and the slo_* metric "
                             "family. Unset + no --enable-debug-slo = "
                             "monitor off")
    parser.add_argument("--slo-windows", default="60,300,3600",
                        help="burn-rate windows in seconds (comma list)")
    parser.add_argument("--slo-tick-seconds", type=float, default=5.0,
                        help="SLO monitor sampling cadence")
    parser.add_argument("--enable-debug-slo", action="store_true",
                        help="serve the (authenticated) /debug/slo "
                             "objective/burn-rate report; implies the "
                             "monitor with default objectives when "
                             "--slo-objectives is unset")
    parser.add_argument("--audit-allow-rps", type=float, default=10.0,
                        help="rate cap for ALLOW audit lines per second "
                             "(denies are never capped)")
    parser.add_argument("--autoscale", default="off",
                        choices=["off", "dry-run", "apply"],
                        help="SLO-driven autoscaler: dry-run counts and "
                             "surfaces grow/shrink proposals on /readyz; "
                             "apply drives real shard-map transitions "
                             "through the rebalance coordinator. "
                             "Requires --shard-map")
    parser.add_argument("--autoscale-policy", default=None,
                        help="policy knobs as key=value CSV, e.g. "
                             "'max_groups=6,grow_occupancy=0.7' "
                             "(autoscale/policy.py; unset = defaults)")
    parser.add_argument("--autoscale-tick-seconds", type=float,
                        default=15.0,
                        help="autoscaler observe/decide cadence")
    parser.add_argument("--frontier-exchange", action="store_true",
                        help="enable cross-shard frontier-exchange joins "
                             "(scaleout/frontier.py): cross-namespace "
                             "reference types resolve by iterating "
                             "boundary frontiers instead of requiring "
                             "cluster-scoped replication. Requires "
                             "--shard-map; monotone schemas only")
    parser.add_argument("--frontier-max-rounds", type=int, default=8,
                        help="fail-closed round budget per frontier "
                             "exchange (exhaustion under-approximates "
                             "the closure: deny/under-list, never "
                             "over-grant)")


def options_from_args(args: argparse.Namespace) -> Options:
    return Options(
        engine_endpoint=args.engine_endpoint,
        engine_token=args.engine_token,
        engine_insecure=args.engine_insecure,
        engine_ca_file=args.engine_ca_file,
        engine_skip_verify_ca=args.engine_skip_verify_ca,
        engine_client_cert_file=args.engine_client_cert_file,
        engine_client_key_file=args.engine_client_key_file,
        engine_server_name=args.engine_server_name,
        bootstrap_files=args.bootstrap,
        rule_files=args.rule_file,
        upstream_url=args.upstream_url,
        kubeconfig=args.kubeconfig,
        kubeconfig_context=args.kubeconfig_context,
        upstream_token=args.upstream_token,
        upstream_ca_file=args.upstream_ca_file,
        upstream_client_cert=args.upstream_client_cert,
        upstream_client_key=args.upstream_client_key,
        upstream_insecure=args.upstream_insecure,
        bind_host=args.bind_host,
        bind_port=args.bind_port,
        tls_cert_file=args.tls_cert_file,
        tls_key_file=args.tls_key_file,
        tls_client_ca_file=args.tls_client_ca_file,
        tls_requestheader_allowed_names=args.tls_requestheader_allowed_names,
        token_auth_file=args.token_auth_file,
        oidc_issuer_url=args.oidc_issuer_url,
        oidc_client_id=args.oidc_client_id,
        oidc_username_claim=args.oidc_username_claim,
        oidc_username_prefix=args.oidc_username_prefix,
        oidc_groups_claim=args.oidc_groups_claim,
        oidc_groups_prefix=args.oidc_groups_prefix,
        oidc_ca_file=args.oidc_ca_file,
        oidc_signing_algs=args.oidc_signing_algs,
        oidc_required_claims=args.oidc_required_claims,
        workflow_database_path=args.workflow_database_path,
        lock_mode=args.lock_mode,
        snapshot_path=args.snapshot_path,
        data_dir=args.data_dir,
        wal_fsync=args.wal_fsync,
        checkpoint_wal_bytes=args.checkpoint_wal_bytes,
        checkpoint_wal_records=args.checkpoint_wal_records,
        checkpoint_keep=args.checkpoint_keep,
        lookup_batch_window=args.lookup_batch_window,
        authz_cache=args.authz_cache,
        authz_cache_size=args.authz_cache_size,
        authz_cache_mask_bytes=args.authz_cache_mask_bytes,
        delta_capacity=args.delta_capacity,
        compact_threshold=args.compact_threshold,
        device_graph_budget_bytes=args.device_graph_budget_bytes,
        caveat_context=args.caveat_context,
        caveat_ip_header=args.caveat_ip_header,
        shard_map=args.shard_map,
        shard_journal_path=args.shard_journal_path,
        shard_cache=args.shard_cache,
        rebalance_to=args.rebalance_to,
        migrate_schema=args.migrate_schema,
        engine_probe_timeout=args.engine_probe_timeout,
        enable_debug_config=args.enable_debug_config,
        engine_mesh=args.engine_mesh,
        feature_gates=args.feature_gates,
        discovery_cache_ttl=args.discovery_cache_ttl,
        discovery_cache_dir=args.discovery_cache_dir,
        upstream_connect_timeout=args.upstream_connect_timeout,
        upstream_request_deadline=args.upstream_request_deadline,
        upstream_retries=args.upstream_retries,
        engine_connect_timeout=args.engine_connect_timeout,
        engine_read_timeout=args.engine_read_timeout,
        engine_retries=args.engine_retries,
        breaker_failure_threshold=args.breaker_failure_threshold,
        breaker_reset_seconds=args.breaker_reset_seconds,
        retry_budget_ratio=args.retry_budget_ratio,
        retry_budget_burst=args.retry_budget_burst,
        admission=args.admission,
        admission_initial_concurrency=args.admission_initial_concurrency,
        admission_min_concurrency=args.admission_min_concurrency,
        admission_max_concurrency=args.admission_max_concurrency,
        admission_tenant_rate=args.admission_tenant_rate,
        admission_tenant_burst=args.admission_tenant_burst,
        admission_tenant_queue_depth=args.admission_tenant_queue_depth,
        admission_queue_depth=args.admission_queue_depth,
        admission_queue_timeout=args.admission_queue_timeout,
        trace_sample=args.trace_sample,
        trace_slow_ms=args.trace_slow_ms,
        trace_ring=args.trace_ring,
        enable_debug_traces=args.enable_debug_traces,
        audit_log=args.audit_log,
        audit_allow_rps=args.audit_allow_rps,
        slo_objectives=args.slo_objectives,
        slo_windows=args.slo_windows,
        slo_tick_seconds=args.slo_tick_seconds,
        enable_debug_slo=args.enable_debug_slo,
        autoscale=args.autoscale,
        autoscale_policy=args.autoscale_policy,
        autoscale_tick_seconds=args.autoscale_tick_seconds,
        frontier_exchange=args.frontier_exchange,
        frontier_max_rounds=args.frontier_max_rounds,
    )
