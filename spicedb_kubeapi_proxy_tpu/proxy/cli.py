"""CLI entry point (reference cmd/spicedb-kubeapi-proxy/main.go:20-64).

``python -m spicedb_kubeapi_proxy_tpu.proxy.cli --rule-file rules.yaml
--upstream-url https://kube:6443 ...`` — signal-aware serve loop.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal
import sys

from .options import add_flags, options_from_args


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="spicedb-kubeapi-proxy-tpu",
        description="TPU-native authorizing kube-apiserver proxy",
    )
    add_flags(parser)
    parser.add_argument("-v", "--verbosity", type=int, default=1)
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbosity >= 3 else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    opts = options_from_args(args)
    cfg = opts.complete()

    async def serve():
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await cfg.run()
        logging.info("serving on %s:%d", cfg.server.host, cfg.server.port)
        await stop.wait()
        await cfg.server.stop()
        await cfg.workflow.shutdown()
        if hasattr(cfg.engine, "sharding_status"):
            # sharded planner: parks a live rebalance mover (its
            # persisted transition resumes or aborts at the next boot),
            # drains the scatter pool, closes the split journal
            await asyncio.get_running_loop().run_in_executor(
                None, cfg.engine.close)
        if cfg.slo_monitor is not None:
            cfg.slo_monitor.stop()
        if cfg.deps.audit is not None:
            # drain + close the audit writer queue: the decisions
            # nearest a shutdown (deny storms before a crash-loop) are
            # exactly the ones an auditor needs — never drop them on
            # SIGTERM, never leave a torn half-written tail line
            await asyncio.get_running_loop().run_in_executor(
                None, cfg.deps.audit.close)
        if hasattr(cfg.engine, "close_compaction"):
            # stop the overlay compactor before the final snapshot /
            # checkpoint so no fold races the state capture below
            await asyncio.get_running_loop().run_in_executor(
                None, cfg.engine.close_compaction)
        if opts.snapshot_path and hasattr(cfg.engine, "save_snapshot"):
            cfg.engine.save_snapshot(opts.snapshot_path)
            logging.info("saved snapshot to %s", opts.snapshot_path)
        if opts.data_dir and hasattr(cfg.engine, "close_persistence"):
            # final checkpoint + WAL fsync (persistence/manager.py) so
            # the next boot loads one snapshot and replays nothing
            await asyncio.get_running_loop().run_in_executor(
                None, cfg.engine.close_persistence)
            logging.info("persistence closed (checkpointed %s)",
                         opts.data_dir)

    asyncio.run(serve())
    return 0


if __name__ == "__main__":
    sys.exit(main())
