"""Authentication: embedded-mode header authenticator.

Mirrors the reference's embedded-mode authenticator
(/root/reference/pkg/proxy/authn.go:78-119): the caller's identity arrives
in ``X-Remote-User`` / ``X-Remote-Group`` / ``X-Remote-Extra-*`` headers.
(The reference's other mode wires kube's built-in client-cert/OIDC/token
authenticators; TLS client-cert authn is a proxy-server concern layered on
top of this interface in a later milestone.)
"""

from __future__ import annotations

from ..rules.input import UserInfo

USER_HEADER = "X-Remote-User"
GROUP_HEADER = "X-Remote-Group"
EXTRA_HEADER_PREFIX = "X-Remote-Extra-"


class AuthenticationError(Exception):
    pass


class HeaderAuthenticator:
    def authenticate(self, headers: dict[str, str]) -> UserInfo:
        name = None
        groups: list[str] = []
        extra: dict[str, list[str]] = {}
        for k, v in headers.items():
            lk = k.lower()
            if lk == USER_HEADER.lower():
                name = v
            elif lk == GROUP_HEADER.lower():
                # repeated headers may arrive comma-joined
                groups.extend(g.strip() for g in v.split(",") if g.strip())
            elif lk.startswith(EXTRA_HEADER_PREFIX.lower()):
                key = k[len(EXTRA_HEADER_PREFIX):].lower()
                extra.setdefault(key, []).extend(
                    x.strip() for x in v.split(",") if x.strip())
        if not name:
            raise AuthenticationError(f"no {USER_HEADER} header present")
        return UserInfo(name=name, groups=groups, extra=extra)
