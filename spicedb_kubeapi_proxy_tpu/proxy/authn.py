"""Authentication: header and TLS client-certificate authenticators.

Mirrors the reference's two modes (/root/reference/pkg/proxy/authn.go):

- embedded-mode header authenticator (``authn.go:78-119``): the caller's
  identity arrives in ``X-Remote-User`` / ``X-Remote-Group`` /
  ``X-Remote-Extra-*`` headers;
- built-in client-cert authentication (``authn.go:40-47``, kube's x509
  CommonName user conversion): a TLS peer certificate verified against
  the configured client CA maps CommonName -> user and Organization
  values -> groups — the identity shape the reference's e2e harness
  stamps per user (``e2e/e2e_test.go:215-318``).
"""

from __future__ import annotations

from typing import Optional

from ..rules.input import UserInfo

USER_HEADER = "X-Remote-User"
GROUP_HEADER = "X-Remote-Group"
EXTRA_HEADER_PREFIX = "X-Remote-Extra-"


class AuthenticationError(Exception):
    pass


class HeaderAuthenticator:
    def authenticate(self, headers: dict[str, str]) -> UserInfo:
        name = None
        groups: list[str] = []
        extra: dict[str, list[str]] = {}
        for k, v in headers.items():
            lk = k.lower()
            if lk == USER_HEADER.lower():
                name = v
            elif lk == GROUP_HEADER.lower():
                # repeated headers may arrive comma-joined
                groups.extend(g.strip() for g in v.split(",") if g.strip())
            elif lk.startswith(EXTRA_HEADER_PREFIX.lower()):
                key = k[len(EXTRA_HEADER_PREFIX):].lower()
                extra.setdefault(key, []).extend(
                    x.strip() for x in v.split(",") if x.strip())
        if not name:
            raise AuthenticationError(f"no {USER_HEADER} header present")
        return UserInfo(name=name, groups=groups, extra=extra)


class TokenFileAuthenticator:
    """kube's static token file (--token-auth-file): CSV rows of
    ``token,user,uid[,"group1,group2"]`` (authn.go:40-47 wires the same
    kube authenticator). Comparison is constant-time per row."""

    def __init__(self, path: str):
        import csv
        import hmac as _hmac

        self._hmac = _hmac
        self._rows: list[tuple[str, UserInfo]] = []
        with open(path, newline="") as f:
            for row in csv.reader(f):
                if not row or row[0].lstrip().startswith("#"):
                    continue
                if len(row) < 3:
                    raise ValueError(
                        f"token file {path!r}: rows need token,user,uid")
                groups = [g.strip() for g in row[3].split(",")
                          if g.strip()] if len(row) > 3 else []
                self._rows.append((
                    row[0].encode(),
                    UserInfo(name=row[1], uid=row[2], groups=groups)))

    def authenticate_token(self, token: str) -> Optional[UserInfo]:
        # compare BYTES: str compare_digest raises on non-ASCII input,
        # which an anonymous client could trigger at will (500 not 401)
        presented = token.encode("utf-8", "surrogateescape")
        found = None
        for tok, user in self._rows:  # constant-time, no early exit
            if self._hmac.compare_digest(tok, presented):
                found = user
        return found


class ClientCertAuthenticator:
    """Maps a verified TLS peer certificate to a user identity the way
    kube's x509 authenticator does: CommonName is the user name, each
    Organization value is a group. The ssl module has already verified
    the chain against the configured client CA before this runs."""

    def authenticate_peer(self, peercert: dict) -> UserInfo:
        name = None
        groups: list[str] = []
        for rdn in peercert.get("subject", ()):
            for key, value in rdn:
                if key == "commonName" and name is None:
                    name = value
                elif key == "organizationName":
                    groups.append(value)
        if not name:
            raise AuthenticationError(
                "client certificate has no CommonName")
        return UserInfo(name=name, groups=groups, extra={})
