"""Async HTTP(S) client for the upstream kube-apiserver.

The reverse-proxy transport (reference pkg/proxy/server.go:95-118 uses
httputil.ReverseProxy; activities replay raw URIs with admin credentials,
activity.go:175-231). Built on asyncio streams: per-request connections,
TLS with CA/client-cert options, bearer tokens, and chunked/streaming
response bodies surfaced as async frame iterators (watch).
"""

from __future__ import annotations

import asyncio
import ssl
from typing import AsyncIterator, Optional
from urllib.parse import urlsplit

from .types import ProxyRequest, ProxyResponse

HOP_HEADERS = {"connection", "keep-alive", "transfer-encoding", "upgrade",
               "proxy-connection", "te", "trailer", "content-length", "host"}


class HttpUpstream:
    """Upstream callable: forwards a ProxyRequest to a base URL.

    Auth headers of the incoming request are replaced by the proxy's own
    credentials (the reference proxies with its admin transport and passes
    user identity via rules, not kube impersonation).
    """

    def __init__(self, base_url: str, token: Optional[str] = None,
                 ca_file: Optional[str] = None,
                 client_cert: Optional[str] = None,
                 client_key: Optional[str] = None,
                 insecure_skip_verify: bool = False):
        u = urlsplit(base_url)
        self.scheme = u.scheme or "http"
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or (443 if self.scheme == "https" else 80)
        self.token = token
        self._ssl: Optional[ssl.SSLContext] = None
        if self.scheme == "https":
            ctx = ssl.create_default_context(cafile=ca_file)
            if client_cert:
                ctx.load_cert_chain(client_cert, client_key)
            if insecure_skip_verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            self._ssl = ctx

    async def __call__(self, req: ProxyRequest) -> ProxyResponse:
        reader, writer = await asyncio.open_connection(
            self.host, self.port, ssl=self._ssl)
        try:
            headers = {k: v for k, v in req.headers.items()
                       if k.lower() not in HOP_HEADERS
                       and not k.lower().startswith("x-remote-")
                       and k.lower() not in ("authorization", "accept")}
            headers["Host"] = f"{self.host}:{self.port}"
            accept = next((v for k, v in req.headers.items()
                           if k.lower() == "accept"), "")
            headers["Accept"] = rewrite_accept(accept, _is_watch(req))
            headers["Connection"] = "close"
            if self.token:
                headers["Authorization"] = f"Bearer {self.token}"
            if req.body:
                headers["Content-Length"] = str(len(req.body))
            lines = [f"{req.method} {req.uri} HTTP/1.1\r\n"]
            for k, v in headers.items():
                lines.append(f"{k}: {v}\r\n")
            lines.append("\r\n")
            writer.write("".join(lines).encode("latin-1"))
            if req.body:
                writer.write(req.body)
            await writer.drain()

            status, resp_headers = await _read_head(reader)
            is_stream = _is_watch(req) and status == 200
            if is_stream:
                return ProxyResponse(
                    status=status, headers=resp_headers,
                    stream=_stream_body(reader, writer, resp_headers))
            body = await _read_body(reader, resp_headers)
            writer.close()
            return ProxyResponse(status=status, headers=resp_headers, body=body)
        except BaseException:
            writer.close()
            raise


def rewrite_accept(accept: str, watching: bool) -> str:
    """Accept rewriting for upstream requests: the filterer parses JSON
    (incl. Table) and kube protobuf objects/lists/Tables
    (authz/filterer.py, proxy/kubeproto.py) but NOT protobuf watch
    frames — so protobuf ranges pass through except on watches, which
    stay JSON-only (the watch join decodes frames as JSON). Anything
    else is stripped; an emptied Accept falls back to JSON."""

    from ..utils.features import features

    proto_ok = features.enabled("ProtobufNegotiation")

    def keep(r: str) -> bool:
        low = r.lower()
        if "json" in low:
            return True
        return proto_ok and "protobuf" in low and not watching

    return ",".join(r for r in accept.split(",")
                    if keep(r)) or "application/json"


def _is_watch(req: ProxyRequest) -> bool:
    v = req.query.get("watch")
    return bool(v) and v[0] in ("", "1", "true", "True")


async def _read_head(reader) -> tuple[int, dict]:
    status_line = await reader.readline()
    parts = status_line.decode("latin-1").split(" ", 2)
    status = int(parts[1])
    headers: dict[str, str] = {}
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        k, _, v = line.decode("latin-1").partition(":")
        headers[k.strip()] = v.strip()
    return status, headers


def _header(headers: dict, name: str) -> Optional[str]:
    for k, v in headers.items():
        if k.lower() == name:
            return v
    return None


async def _read_body(reader, headers: dict) -> bytes:
    te = _header(headers, "transfer-encoding") or ""
    if "chunked" in te.lower():
        chunks = []
        while True:
            size_line = await reader.readline()
            if not size_line:
                break
            size = int(size_line.strip().split(b";")[0] or b"0", 16)
            if size == 0:
                await reader.readline()
                break
            chunks.append(await reader.readexactly(size))
            await reader.readline()
        return b"".join(chunks)
    cl = _header(headers, "content-length")
    if cl is not None:
        return await reader.readexactly(int(cl))
    return await reader.read()


async def _stream_body(reader, writer, headers: dict) -> AsyncIterator[bytes]:
    """Yield newline-delimited watch frames, preserving raw bytes."""
    te = _header(headers, "transfer-encoding") or ""
    buf = b""
    try:
        if "chunked" in te.lower():
            while True:
                size_line = await reader.readline()
                if not size_line:
                    break
                size = int(size_line.strip().split(b";")[0] or b"0", 16)
                if size == 0:
                    break
                data = await reader.readexactly(size)
                await reader.readline()
                buf += data
                while b"\n" in buf:
                    frame, buf = buf.split(b"\n", 1)
                    yield frame + b"\n"
        else:
            while True:
                line = await reader.readline()
                if not line:
                    break
                yield line
        if buf:
            yield buf
    finally:
        writer.close()
