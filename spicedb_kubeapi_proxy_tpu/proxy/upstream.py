"""Async HTTP(S) client for the upstream kube-apiserver.

The reverse-proxy transport (reference pkg/proxy/server.go:95-118 uses
httputil.ReverseProxy; activities replay raw URIs with admin credentials,
activity.go:175-231). Built on asyncio streams: per-request connections,
TLS with CA/client-cert options, bearer tokens, and chunked/streaming
response bodies surfaced as async frame iterators (watch).
"""

from __future__ import annotations

import asyncio
import ssl
import time
from typing import AsyncIterator, Optional
from urllib.parse import urlsplit

from ..utils.failpoints import FailPointError, failpoints
from ..utils.metrics import metrics
from ..utils.resilience import (
    CircuitBreaker,
    Deadline,
    RetryBudget,
    RetryPolicy,
)
from .types import ProxyRequest, ProxyResponse

HOP_HEADERS = {"connection", "keep-alive", "transfer-encoding", "upgrade",
               "proxy-connection", "te", "trailer", "content-length", "host"}

# "the transport failed": the connection could not be established, died,
# or timed out. These feed the circuit breaker and — pre-response, on
# idempotent requests only — the retry path. FailPointError is included
# so the upstream.connect/upstream.read failpoints drive the exact same
# classification chaos tests need to exercise.
TRANSPORT_ERRORS = (OSError, asyncio.TimeoutError, TimeoutError,
                    asyncio.IncompleteReadError, FailPointError)


class HttpUpstream:
    """Upstream callable: forwards a ProxyRequest to a base URL.

    Auth headers of the incoming request are replaced by the proxy's own
    credentials (the reference proxies with its admin transport and passes
    user identity via rules, not kube impersonation).
    """

    def __init__(self, base_url: str, token: Optional[str] = None,
                 ca_file: Optional[str] = None,
                 client_cert: Optional[str] = None,
                 client_key: Optional[str] = None,
                 insecure_skip_verify: bool = False,
                 connect_timeout: float = 5.0,
                 request_deadline: float = 30.0,
                 retries: int = 1,
                 retry_policy: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 breaker_failure_threshold: int = 5,
                 breaker_reset_seconds: float = 10.0,
                 retry_budget: Optional[RetryBudget] = None):
        u = urlsplit(base_url)
        self.scheme = u.scheme or "http"
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or (443 if self.scheme == "https" else 80)
        self.token = token
        # per-attempt connect budget and per-request total deadline (0 =
        # unlimited); a watch's deadline covers establishment only — the
        # long-lived frame stream is exempt by design
        self.connect_timeout = connect_timeout
        self.request_deadline = request_deadline
        # retries apply ONLY to idempotent requests (GET/HEAD: get, list,
        # watch establishment) that failed BEFORE a status line arrived —
        # a write may have been applied even if the response never came
        self.retries = retries
        self.retry_policy = retry_policy or RetryPolicy(base=0.05, cap=1.0)
        # shared token-bucket retry allowance (utils/resilience.py
        # RetryBudget): bounds total upstream retries under sustained
        # failure so a wedged kube-apiserver never sees a retry storm
        # on top of its outage. None = unbudgeted.
        self.retry_budget = retry_budget
        self.breaker = breaker or CircuitBreaker(
            "upstream", failure_threshold=breaker_failure_threshold,
            reset_timeout=breaker_reset_seconds)
        self._ssl: Optional[ssl.SSLContext] = None
        if self.scheme == "https":
            ctx = ssl.create_default_context(cafile=ca_file)
            if client_cert:
                ctx.load_cert_chain(client_cert, client_key)
            if insecure_skip_verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            self._ssl = ctx

    async def __call__(self, req: ProxyRequest) -> ProxyResponse:
        deadline = Deadline.after(self.request_deadline)
        attempts = (self.retries + 1
                    if req.method.upper() in ("GET", "HEAD") else 1)
        delays = self.retry_policy.delays()
        if self.retry_budget is not None:
            self.retry_budget.on_attempt()
        while True:
            attempts -= 1
            self.breaker.allow()
            head_seen = [False]
            start = time.monotonic()
            try:
                resp = await self._attempt(req, deadline, head_seen)
            except TRANSPORT_ERRORS:
                self.breaker.record_failure()
                # an exhausted deadline is terminal even for idempotent
                # requests: surface it as the 503-mapped family
                deadline.check("upstream")
                if attempts <= 0 or head_seen[0]:
                    raise
                if self.retry_budget is not None \
                        and not self.retry_budget.allow():
                    # budget dry: surface the failure (counted) rather
                    # than pile a retry storm onto a wedged upstream
                    raise
                metrics.counter("proxy_dependency_retries_total",
                                dependency="upstream").inc()
                await asyncio.sleep(min(next(delays), deadline.remaining()))
                continue
            except BaseException:
                # non-transport outcome (e.g. the handler task was
                # cancelled mid-attempt): no verdict on the dependency,
                # but the admitted half-open probe slot must not leak
                self.breaker.release()
                raise
            self.breaker.record_success()
            metrics.histogram("proxy_dependency_seconds",
                              dependency="upstream").observe(
                time.monotonic() - start)
            return resp

    async def _attempt(self, req: ProxyRequest, deadline: Deadline,
                       head_seen: list) -> ProxyResponse:
        failpoints.hit("upstream.connect")
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(self.host, self.port, ssl=self._ssl),
                deadline.budget(self.connect_timeout))
        except (asyncio.TimeoutError, TimeoutError):
            raise ConnectionError(
                f"connect to upstream {self.host}:{self.port} "
                "timed out") from None
        try:
            headers = {k: v for k, v in req.headers.items()
                       if k.lower() not in HOP_HEADERS
                       and not k.lower().startswith("x-remote-")
                       and k.lower() not in ("authorization", "accept")}
            headers["Host"] = f"{self.host}:{self.port}"
            accept = next((v for k, v in req.headers.items()
                           if k.lower() == "accept"), "")
            headers["Accept"] = rewrite_accept(accept, _is_watch(req))
            headers["Connection"] = "close"
            if self.token:
                headers["Authorization"] = f"Bearer {self.token}"
            if req.body:
                headers["Content-Length"] = str(len(req.body))
            lines = [f"{req.method} {req.uri} HTTP/1.1\r\n"]
            for k, v in headers.items():
                lines.append(f"{k}: {v}\r\n")
            lines.append("\r\n")
            writer.write("".join(lines).encode("latin-1"))
            if req.body:
                writer.write(req.body)
            await writer.drain()

            failpoints.hit("upstream.read")
            status, resp_headers = await asyncio.wait_for(
                _read_head(reader), deadline.budget())
            head_seen[0] = True
            is_stream = _is_watch(req) and status == 200
            if is_stream:
                return ProxyResponse(
                    status=status, headers=resp_headers,
                    stream=_stream_body(reader, writer, resp_headers))
            body = await asyncio.wait_for(
                _read_body(reader, resp_headers), deadline.budget())
            writer.close()
            return ProxyResponse(status=status, headers=resp_headers, body=body)
        except BaseException:
            writer.close()
            raise


def rewrite_accept(accept: str, watching: bool,
                   json_only: bool = False) -> str:
    """Accept rewriting for upstream requests: the filterer parses JSON
    (incl. Table) and kube protobuf objects/lists/Tables/watch frames
    (authz/filterer.py, authz/watch.py, proxy/kubeproto.py), so protobuf
    ranges pass through — on watches only while the ``ProtobufWatch``
    gate is on (off = the legacy JSON downgrade, counted in /metrics so
    a fleet of proto watchers re-encoded as JSON is visible to the
    operator). ``json_only`` strips protobuf unconditionally (the
    postfilter path resolves rule expressions over item JSON). Anything
    else is stripped; an emptied Accept falls back to JSON."""

    from ..utils.features import features
    from ..utils.metrics import metrics

    proto_ok = not json_only and features.enabled("ProtobufNegotiation")
    proto_watch_ok = proto_ok and (
        not watching or features.enabled("ProtobufWatch"))
    downgraded = False

    def keep(r: str) -> bool:
        nonlocal downgraded
        low = r.lower()
        if "json" in low:
            return True
        if "protobuf" not in low:
            return False
        if proto_watch_ok:
            return True
        if watching and not json_only:
            downgraded = True
        return False

    out = ",".join(r for r in accept.split(",")
                   if keep(r)) or "application/json"
    if downgraded:
        # one count per watch request whose proto preference we rewrote
        metrics.counter("proxy_proto_watch_downgrades_total").inc()
    return out


def _is_watch(req: ProxyRequest) -> bool:
    v = req.query.get("watch")
    return bool(v) and v[0] in ("", "1", "true", "True")


async def _read_head(reader) -> tuple[int, dict]:
    status_line = await reader.readline()
    parts = status_line.decode("latin-1").split(" ", 2)
    try:
        status = int(parts[1].strip())
    except (IndexError, ValueError):
        # upstream closed (or garbled) before a status line: surface a
        # connection error — the retry/error paths handle those — not a
        # bare IndexError/ValueError from the parse (str.isdigit would
        # still admit non-ASCII digits that int() rejects)
        raise ConnectionResetError(
            "upstream closed the connection before sending a response "
            f"status line ({status_line[:60]!r})") from None
    headers: dict[str, str] = {}
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        k, _, v = line.decode("latin-1").partition(":")
        headers[k.strip()] = v.strip()
    return status, headers


def _header(headers: dict, name: str) -> Optional[str]:
    for k, v in headers.items():
        if k.lower() == name:
            return v
    return None


def _chunk_size(size_line: bytes) -> int:
    """Chunked-transfer size line -> int. Garbage surfaces as a
    connection error the way _read_head does for a garbled status line —
    the retry/error paths classify it as a transport failure instead of
    a bare ValueError escaping to the panic handler."""
    try:
        size = int(size_line.strip().split(b";")[0] or b"0", 16)
    except ValueError:
        size = -1  # int() admits a leading '-'; treat both the same
    if size < 0:
        raise ConnectionResetError(
            "upstream sent a garbled chunk-size line "
            f"({size_line[:40]!r})")
    return size


async def _read_body(reader, headers: dict) -> bytes:
    te = _header(headers, "transfer-encoding") or ""
    if "chunked" in te.lower():
        chunks = []
        while True:
            size_line = await reader.readline()
            if not size_line:
                break
            size = _chunk_size(size_line)
            if size == 0:
                await reader.readline()
                break
            chunks.append(await reader.readexactly(size))
            await reader.readline()
        return b"".join(chunks)
    cl = _header(headers, "content-length")
    if cl is not None:
        return await reader.readexactly(int(cl))
    return await reader.read()


# largest single proto watch frame we will buffer; a corrupt/desynced
# length prefix must abort the stream, not grow the buffer toward 4 GiB
MAX_WATCH_FRAME = 64 * 1024 * 1024


def _split_frames(buf: bytes, proto: bool) -> tuple[list[bytes], bytes]:
    """Complete frames + remainder. JSON watch streams are
    newline-delimited; protobuf streams are 4-byte big-endian
    length-prefixed (kube LengthDelimitedFramer) — frames keep their
    length prefix so downstream passthrough is byte-identical. Raises
    ValueError on an absurd length prefix (ends the watch; the client
    re-lists and re-watches)."""
    frames = []
    if proto:
        while len(buf) >= 4:
            n = int.from_bytes(buf[:4], "big")
            if n > MAX_WATCH_FRAME:
                raise ValueError(
                    f"proto watch frame of {n} bytes exceeds limit "
                    "(corrupt or desynchronized stream)")
            if len(buf) < 4 + n:
                break
            frames.append(buf[:4 + n])
            buf = buf[4 + n:]
    else:
        while b"\n" in buf:
            frame, buf = buf.split(b"\n", 1)
            frames.append(frame + b"\n")
    return frames, buf


def _is_proto_stream(headers: dict) -> bool:
    ct = (_header(headers, "content-type") or "").lower()
    return "protobuf" in ct


async def _stream_body(reader, writer, headers: dict) -> AsyncIterator[bytes]:
    """Yield watch frames, preserving raw bytes (newline-delimited JSON
    or length-prefixed kube protobuf, by response Content-Type)."""
    te = _header(headers, "transfer-encoding") or ""
    proto = _is_proto_stream(headers)
    buf = b""
    try:
        if "chunked" in te.lower():
            while True:
                size_line = await reader.readline()
                if not size_line:
                    break
                size = _chunk_size(size_line)
                if size == 0:
                    break
                data = await reader.readexactly(size)
                await reader.readline()
                buf += data
                frames, buf = _split_frames(buf, proto)
                for frame in frames:
                    yield frame
        elif proto:
            while True:
                data = await reader.read(65536)
                if not data:
                    break
                buf += data
                frames, buf = _split_frames(buf, proto)
                for frame in frames:
                    yield frame
        else:
            while True:
                line = await reader.readline()
                if not line:
                    break
                yield line
        if buf and not proto:
            # proto: a partial frame at EOF is a dead connection's torso —
            # drop it (the filter would fail closed on it anyway); JSON:
            # surface the partial line, the join refuses to judge it
            yield buf
    finally:
        writer.close()
