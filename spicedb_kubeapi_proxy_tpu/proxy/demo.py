"""Self-contained demo: the proxy, an in-memory kube upstream, and the
sample rule set — zero external dependencies.

``python -m spicedb_kubeapi_proxy_tpu.proxy.demo`` (or ``make demo``)
serves on 127.0.0.1:8080 with header authentication. This is the
reference's ``mage dev:up`` + ``dev:run`` developer flow
(magefiles/dev.go:43-101) with the kind cluster replaced by an
in-process upstream, so the authorize/filter/dual-write loop can be
exercised with nothing but curl:

    curl -s -H 'X-Remote-User: alice' \\
        http://127.0.0.1:8080/api/v1/namespaces        # sees: dev
    curl -s -H 'X-Remote-User: carol' \\
        http://127.0.0.1:8080/api/v1/namespaces        # sees: prod
    curl -s -X POST -H 'X-Remote-User: alice' \\
        -H 'Content-Type: application/json' \\
        -d '{"metadata": {"name": "mine"}}' \\
        http://127.0.0.1:8080/api/v1/namespaces        # dual-write
"""

from __future__ import annotations

import asyncio

from .inmemkube import InMemoryKube


def build(port: int = 8080):
    """Wire the demo stack: engine + rules + upstream + seeded state.
    Returns the completed config (``await cfg.run()`` to serve)."""
    import os

    from ..engine import CheckItem, WriteOp
    from ..models.tuples import parse_relationship
    from .options import Options

    root = os.path.join(os.path.dirname(__file__), "..", "..")
    upstream = InMemoryKube()
    opts = Options(
        rule_files=[os.path.join(root, "deploy", "rules.yaml")],
        bootstrap_files=[os.path.join(root, "deploy", "bootstrap.yaml")],
        upstream=upstream,
        bind_host="127.0.0.1",
        bind_port=port,
        workflow_database_path=":memory:",
    )
    cfg = opts.complete()

    # seed: two users with disjoint worlds, as if dual-written earlier
    for ns, user in (("dev", "alice"), ("prod", "carol")):
        upstream.put("namespaces", ns)
        upstream.put("pods", "api", ns=ns)
        cfg.engine.write_relationships([
            WriteOp("touch", parse_relationship(
                f"namespace:{ns}#creator@user:{user}")),
            WriteOp("touch", parse_relationship(
                f"pod:{ns}/api#namespace@namespace:{ns}")),
            WriteOp("touch", parse_relationship(
                f"pod:{ns}/api#creator@user:{user}")),
        ])
    # warm the jitted fixpoint for the list shapes before serving: the
    # first XLA compile can exceed the 10s prefilter window, which would
    # greet the very first curl with a timeout
    for rtype in ("namespace", "pod"):
        cfg.engine.lookup_resources_mask(rtype, "view", "user", "alice")
    cfg.engine.check_bulk([CheckItem("namespace", "dev", "view",
                                     "user", "alice")])
    return cfg


def main(argv=None) -> int:
    import argparse
    import logging
    import signal

    ap = argparse.ArgumentParser(
        prog="spicedb-kubeapi-proxy-tpu-demo", description=__doc__)
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--tpu", action="store_true",
                    help="run the engine on the TPU backend (default: "
                         "CPU — the demo is a laptop flow, and a slow or "
                         "absent TPU plugin would stall the boot warmup)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(levelname)s %(message)s")
    if not args.tpu:
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # already initialized: keep whatever it picked
            pass
    cfg = build(args.port)

    async def serve():
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await cfg.run()
        print(__doc__.split("curl", 1)[0].strip())
        print(f"\nserving on http://127.0.0.1:{args.port} — try:\n")
        for user, what in (("alice", "sees dev"), ("carol", "sees prod")):
            print(f"  curl -s -H 'X-Remote-User: {user}' "
                  f"http://127.0.0.1:{args.port}/api/v1/namespaces"
                  f"   # {what}")
        print(f"  curl -s -X POST -H 'X-Remote-User: alice' "
              f"-H 'Content-Type: application/json' "
              f"-d '{{\"metadata\": {{\"name\": \"mine\"}}}}' "
              f"http://127.0.0.1:{args.port}/api/v1/namespaces"
              f"   # dual-write")
        await stop.wait()
        await cfg.server.stop()
        await cfg.workflow.shutdown()

    asyncio.run(serve())
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
