"""Upstream kube-apiserver connection config: kubeconfig and in-cluster.

Mirrors the reference's RestConfigFunc resolution
(/root/reference/pkg/proxy/options.go:223-263,429-449): an explicit
kubeconfig file (cluster server/CA, user token or client cert, selected by
context) or, inside a pod, the in-cluster service-account environment
(KUBERNETES_SERVICE_HOST/PORT + /var/run/secrets/.../{token,ca.crt}).

Inline ``*-data`` fields (base64) are materialized to private temp files
because ``ssl.SSLContext.load_cert_chain`` only takes paths; the files
live for the process lifetime.
"""

from __future__ import annotations

import base64
import os
import tempfile
from dataclasses import dataclass
from typing import Optional

import yaml

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeconfigError(ValueError):
    pass


@dataclass
class UpstreamConfig:
    """Everything HttpUpstream needs to dial the apiserver."""

    url: str
    token: Optional[str] = None
    ca_file: Optional[str] = None
    client_cert: Optional[str] = None
    client_key: Optional[str] = None
    insecure_skip_verify: bool = False


def _materialize(data_b64: str, suffix: str) -> str:
    """base64 inline data -> private temp file path (0600)."""
    try:
        raw = base64.b64decode(data_b64)
    except (ValueError, TypeError) as e:
        raise KubeconfigError(f"invalid base64 in kubeconfig: {e}") from None
    fd, path = tempfile.mkstemp(prefix="sdbkp-kubeconfig-", suffix=suffix)
    with os.fdopen(fd, "wb") as f:
        f.write(raw)
    return path


def _by_name(entries, name: str, what: str) -> dict:
    for e in entries or []:
        if e.get("name") == name:
            return e.get(what) or {}
    raise KubeconfigError(f"kubeconfig has no {what} named {name!r}")


def load_kubeconfig(path: str,
                    context: Optional[str] = None) -> UpstreamConfig:
    """Resolve a kubeconfig file to an UpstreamConfig, honoring
    current-context (or an explicit context name). Relative file
    references resolve against the kubeconfig's own directory, as
    kubectl/client-go do."""
    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    base_dir = os.path.dirname(os.path.abspath(path))

    def resolve(p: Optional[str]) -> Optional[str]:
        if not p:
            return p
        return p if os.path.isabs(p) else os.path.join(base_dir, p)
    ctx_name = context or doc.get("current-context")
    if not ctx_name:
        raise KubeconfigError(
            f"kubeconfig {path!r} has no current-context "
            "(pass an explicit context)")
    ctx = _by_name(doc.get("contexts"), ctx_name, "context")
    cluster = _by_name(doc.get("clusters"), ctx.get("cluster"), "cluster")
    user = _by_name(doc.get("users"), ctx.get("user"), "user") \
        if ctx.get("user") else {}

    server = cluster.get("server")
    if not server:
        raise KubeconfigError(
            f"kubeconfig cluster {ctx.get('cluster')!r} has no server")

    ca_file = resolve(cluster.get("certificate-authority"))
    if cluster.get("certificate-authority-data"):
        ca_file = _materialize(cluster["certificate-authority-data"],
                               ".ca.pem")
    cert = resolve(user.get("client-certificate"))
    if user.get("client-certificate-data"):
        cert = _materialize(user["client-certificate-data"], ".crt.pem")
    key = resolve(user.get("client-key"))
    if user.get("client-key-data"):
        key = _materialize(user["client-key-data"], ".key.pem")
    token = user.get("token")
    if not token and user.get("tokenFile"):
        token = open(resolve(user["tokenFile"])).read().strip()

    return UpstreamConfig(
        url=server,
        token=token,
        ca_file=ca_file,
        client_cert=cert,
        client_key=key,
        insecure_skip_verify=bool(cluster.get("insecure-skip-tls-verify")),
    )


def in_cluster_available(env=os.environ,
                         sa_dir: str = SERVICE_ACCOUNT_DIR) -> bool:
    return ("KUBERNETES_SERVICE_HOST" in env
            and "KUBERNETES_SERVICE_PORT" in env
            and os.path.exists(os.path.join(sa_dir, "token")))


def in_cluster_config(env=os.environ,
                      sa_dir: str = SERVICE_ACCOUNT_DIR) -> UpstreamConfig:
    """The pod service-account config (reference options.go:258-263 uses
    rest.InClusterConfig)."""
    host = env.get("KUBERNETES_SERVICE_HOST")
    port = env.get("KUBERNETES_SERVICE_PORT")
    if not host or not port:
        raise KubeconfigError(
            "not running in-cluster (KUBERNETES_SERVICE_HOST/PORT unset)")
    token_path = os.path.join(sa_dir, "token")
    if not os.path.exists(token_path):
        raise KubeconfigError(f"service account token missing: {token_path}")
    ca_path = os.path.join(sa_dir, "ca.crt")
    if ":" in host and not host.startswith("["):
        host = f"[{host}]"  # IPv6 service host
    return UpstreamConfig(
        url=f"https://{host}:{port}",
        token=open(token_path).read().strip(),
        ca_file=ca_path if os.path.exists(ca_path) else None,
    )
