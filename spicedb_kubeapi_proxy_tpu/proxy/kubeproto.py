"""Kubernetes protobuf envelope handling: schema-light wire surgery.

The kube protobuf wire format is a 4-byte magic prefix (``k8s\\x00``)
followed by a ``runtime.Unknown`` message whose ``raw`` field holds the
serialized object (reference negotiates this alongside JSON,
/root/reference/pkg/authz/responsefilterer.go:242-313).

Filtering a *List or Table response only needs a handful of API-stable
protobuf field numbers — no generated schemas:

- ``runtime.Unknown``: typeMeta=1 (apiVersion=1, kind=2), raw=2,
  contentEncoding=3, contentType=4
- every ``XList`` message: metadata(ListMeta)=1, repeated items=2
- every item's ``metadata(ObjectMeta)``=1, within it name=1, namespace=3
- ``meta.k8s.io/v1 Table``: metadata=1, columnDefinitions=2, rows=3;
  ``TableRow``: cells=1, conditions=2, object(RawExtension)=3;
  ``runtime.RawExtension``: raw=1. A row's object bytes are either a
  nested magic-prefixed ``runtime.Unknown`` (how kube encodes nested
  RawExtensions under proto negotiation) or a bare
  ``PartialObjectMetadata`` — both resolve through the same
  ObjectMeta-at-field-1 shape (reference filters Table rows after full
  decode, pkg/authz/responsefilterer.go:349-374; here the kept rows stay
  byte-identical)

These numbers are frozen by the kube API compatibility contract (all
generated.proto files), so splitting the repeated ``items`` field and
peeking each item's ObjectMeta is exact, and every byte we keep is
byte-identical to what the apiserver sent — the same passthrough property
the JSON/watch paths maintain (pkg/authz/frames.go:13-68).

WATCH streams (reference negotiates the streaming serializer per content
type, responsefilterer.go:557-626) add one more frozen layer: each frame
is a 4-byte big-endian length followed by a RAW-serialized (no magic, no
Unknown envelope) ``meta.k8s.io/v1 WatchEvent`` — type=1 (string),
object(RawExtension)=2 — whose ``object.raw`` bytes hold the event's
object with the FULL magic-prefixed Unknown envelope. The watch join
needs only (event type, namespace, name) per frame; kept frames pass
through byte-identically, length prefix and all.
"""

from __future__ import annotations

from typing import Iterator, Optional

MAGIC = b"k8s\x00"
CONTENT_TYPE = "application/vnd.kubernetes.protobuf"
# the streaming variant the apiserver stamps on proto watch responses
WATCH_CONTENT_TYPE = CONTENT_TYPE + ";stream=watch"


class ProtoError(ValueError):
    pass


def _read_varint(b: bytes, i: int) -> tuple[int, int]:
    out = 0
    shift = 0
    while True:
        if i >= len(b):
            raise ProtoError("truncated varint")
        byte = b[i]
        i += 1
        out |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return out, i
        shift += 7
        if shift > 63:
            raise ProtoError("varint too long")


def _encode_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        byte = v & 0x7F
        v >>= 7
        if v:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return bytes(out)


def fields(b: bytes) -> Iterator[tuple[int, int, bytes, bytes]]:
    """Yield (field_no, wire_type, full_chunk, payload) over a message.
    ``full_chunk`` is the exact byte span including the tag, so callers
    can copy or drop whole fields byte-identically."""
    i = 0
    n = len(b)
    while i < n:
        start = i
        tag, i = _read_varint(b, i)
        field_no, wire_type = tag >> 3, tag & 7
        if wire_type == 0:  # varint
            _, i = _read_varint(b, i)
            payload = b[start:i]
        elif wire_type == 1:  # fixed64
            i += 8
            payload = b[start:i]
        elif wire_type == 2:  # length-delimited
            ln, j = _read_varint(b, i)
            if j + ln > n:
                raise ProtoError("truncated length-delimited field")
            payload = b[j:j + ln]
            i = j + ln
        elif wire_type == 5:  # fixed32
            i += 4
            payload = b[start:i]
        else:
            raise ProtoError(f"unsupported wire type {wire_type}")
        if i > n:
            raise ProtoError("truncated field")
        yield field_no, wire_type, b[start:i], payload


def _field(b: bytes, field_no: int) -> Optional[bytes]:
    """Payload of the first length-delimited occurrence of a field."""
    for fno, wt, _, payload in fields(b):
        if fno == field_no and wt == 2:
            return payload
    return None


def _ld_field(field_no: int, payload: bytes) -> bytes:
    return _encode_varint((field_no << 3) | 2) \
        + _encode_varint(len(payload)) + payload


def decode_unknown(body: bytes) -> tuple[str, str, bytes]:
    """-> (apiVersion, kind, raw) from a magic-prefixed runtime.Unknown."""
    if not body.startswith(MAGIC):
        raise ProtoError("missing k8s protobuf magic prefix")
    msg = body[len(MAGIC):]
    api_version, kind, raw = "", "", b""
    for fno, wt, _, payload in fields(msg):
        if fno == 1 and wt == 2:  # typeMeta
            tm_api = _field(payload, 1)
            tm_kind = _field(payload, 2)
            api_version = (tm_api or b"").decode("utf-8", "replace")
            kind = (tm_kind or b"").decode("utf-8", "replace")
        elif fno == 2 and wt == 2:  # raw
            raw = payload
    return api_version, kind, raw


def replace_unknown_raw(body: bytes, new_raw: bytes) -> bytes:
    """Re-emit the envelope with ``raw`` replaced; every other field is
    copied byte-identically in its original position."""
    msg = body[len(MAGIC):]
    out = bytearray(MAGIC)
    replaced = False
    for fno, wt, chunk, _ in fields(msg):
        if fno == 2 and wt == 2 and not replaced:
            out += _ld_field(2, new_raw)
            replaced = True
        elif fno == 2 and wt == 2:
            continue  # drop duplicate raw fields
        else:
            out += chunk
    if not replaced:
        out += _ld_field(2, new_raw)
    return bytes(out)


def item_meta(item: bytes) -> tuple[str, str]:
    """(namespace, name) from an item's ObjectMeta (field 1; name=1,
    namespace=3)."""
    meta = _field(item, 1)
    if meta is None:
        return "", ""
    name = _field(meta, 1)
    namespace = _field(meta, 3)
    return ((namespace or b"").decode("utf-8", "replace"),
            (name or b"").decode("utf-8", "replace"))


def table_row_meta(row: bytes) -> tuple[str, str]:
    """(namespace, name) for a TableRow via its ``object`` RawExtension.
    Raises ProtoError when the row carries no keyable object (e.g. the
    client sent ``includeObject=None``) — the filterer turns that into a
    clean 4xx rather than passing unjudgeable rows through."""
    ext = _field(row, 3)  # optional RawExtension object
    if ext is None:
        raise ProtoError(
            "table row has no object to authorize against (request "
            "includeObject=Metadata, the kube default)")
    raw_obj = _field(ext, 1)  # RawExtension.raw
    if raw_obj is None:
        raise ProtoError("table row object has no raw bytes")
    if raw_obj.startswith(MAGIC):
        _, _, raw_obj = decode_unknown(raw_obj)
    ns, name = item_meta(raw_obj)
    if not name:
        raise ProtoError("table row object has no metadata.name")
    return ns, name


# -- encoders (in-memory upstream fidelity + tests) --------------------------


def encode_unknown(api_version: str, kind: str, raw: bytes) -> bytes:
    """Magic-prefixed ``runtime.Unknown`` envelope — the inverse of
    :func:`decode_unknown` (the in-memory upstream uses it to serve
    protobuf the way a real apiserver would)."""
    tm = _ld_field(1, api_version.encode()) + _ld_field(2, kind.encode())
    return MAGIC + _ld_field(1, tm) + _ld_field(2, raw)


def encode_object_meta_only(name: str, namespace: str = "") -> bytes:
    """A message whose field 1 is an ObjectMeta carrying name/namespace —
    the minimal shape every keying path here reads."""
    meta = b""
    if name:
        meta += _ld_field(1, name.encode())
    if namespace:
        meta += _ld_field(3, namespace.encode())
    return _ld_field(1, meta)


def encode_watch_frame(event_type: str, object_bytes: bytes) -> bytes:
    """One length-prefixed raw-serialized WatchEvent frame (the shape
    :func:`watch_frame_key` reads): type=1, object RawExtension=2 whose
    raw=1 holds ``object_bytes`` (normally an :func:`encode_unknown`
    envelope)."""
    we = _ld_field(1, event_type.encode()) \
        + _ld_field(2, _ld_field(1, object_bytes))
    return len(we).to_bytes(4, "big") + we


def decode_watch_event(body: bytes) -> tuple[str, bytes]:
    """(event type, object bytes) from a raw-serialized WatchEvent (the
    frame body AFTER the 4-byte length prefix). ``object bytes`` are the
    RawExtension's raw field — normally a magic-prefixed Unknown."""
    typ = ""
    raw = b""
    for fno, wt, _, payload in fields(body):
        if fno == 1 and wt == 2:
            typ = payload.decode("utf-8", "replace")
        elif fno == 2 and wt == 2:
            raw = _field(payload, 1) or b""
    return typ, raw


def watch_frame_key(frame: bytes) -> Optional[tuple[str, str]]:
    """(namespace, name) of the object a length-prefixed proto watch frame
    carries, or None for frames every consumer may see (BOOKMARKs). The
    frame bytes are never altered — the caller passes kept frames through
    verbatim (reference frame-capturing reader, pkg/authz/frames.go).

    Raises ProtoError for frames carrying no keyable object (an ERROR
    frame's Status, a Table row without an object) — the watch join must
    not silently pass unjudgeable objects."""
    if len(frame) < 4:
        raise ProtoError("proto watch frame shorter than its length prefix")
    body = frame[4:]
    typ, raw = decode_watch_event(body)
    if typ == "BOOKMARK":
        return None  # progress marker, carries only a resourceVersion
    kind = ""
    if raw.startswith(MAGIC):
        _, kind, raw = decode_unknown(raw)
    if typ == "ERROR" or kind == "Status":
        # a terminal Status (watch expiry etc.): no object to judge,
        # every consumer is entitled to see it
        return None
    if kind == "Table":
        for fno, wt, _, payload in fields(raw):
            if fno == 3 and wt == 2:  # first row keys the event
                return table_row_meta(payload)
        raise ProtoError("Table watch event has no rows")
    ns, name = item_meta(raw)
    if not name:
        raise ProtoError("watch event object has no metadata.name")
    return ns, name


def filter_table_raw(raw: bytes, allows) -> bytes:
    """Drop Table ``rows`` (repeated field 3) whose row object fails
    ``allows(namespace, name)``; metadata, columnDefinitions, and kept
    rows are copied byte-identically in order."""
    out = bytearray()
    for fno, wt, chunk, payload in fields(raw):
        if fno == 3 and wt == 2:
            ns, name = table_row_meta(payload)
            if not allows(ns, name):
                continue
        out += chunk
    return bytes(out)


def filter_list_raw(raw: bytes, allows) -> bytes:
    """Drop ``items`` (repeated field 2) whose ObjectMeta fails
    ``allows(namespace, name)``; all other fields and kept items are
    copied byte-identically in order."""
    out = bytearray()
    for fno, wt, chunk, payload in fields(raw):
        if fno == 2 and wt == 2:
            ns, name = item_meta(payload)
            if not allows(ns, name):
                continue
        out += chunk
    return bytes(out)
