"""Proxy layer: HTTP types, request-info parsing, authn, server, transports.

Mirrors the reference's pkg/proxy (server/options/authn) plus the
kube-apiserver request plumbing it borrows (WithRequestInfo) and
pkg/inmemory's zero-network transport.
"""

from .types import ProxyRequest, ProxyResponse, Upstream  # noqa: F401
from .requestinfo import parse_request_info  # noqa: F401
from .authn import HeaderAuthenticator, AuthenticationError  # noqa: F401
