"""The proxy server: asyncio HTTP/1.1 serving the authorization middleware.

Mirrors /root/reference/pkg/proxy/server.go: a handler chain (panic
recovery → logging → request-info → authentication → authorization →
reverse proxy) mounted alongside /readyz and /livez
(server.go:85-94,147-155). Built on stdlib asyncio streams — no external
HTTP framework — with chunked transfer for watch streams.

The handler core operates on ProxyRequest/ProxyResponse, so the exact same
chain serves the socket listener, the in-memory transport
(pkg/inmemory role, inmemory.py), and tests.
"""

from __future__ import annotations

import asyncio
import logging
import time
import traceback
from dataclasses import dataclass, field
from typing import Optional
from urllib.parse import parse_qs, unquote, urlsplit

from ..authz import AuthzDeps, authorize
from ..obs.trace import tracer
from ..proxy.authn import (
    AuthenticationError,
    ClientCertAuthenticator,
    HeaderAuthenticator,
)
from ..proxy.requestinfo import parse_request_info
from ..proxy.types import ProxyRequest, ProxyResponse, kube_status
from ..utils.metrics import metrics
from ..utils.net import drain_server

log = logging.getLogger("sdbkp.proxy")

MAX_BODY = 64 * 1024 * 1024

# fixed infra endpoints that never open a trace: probe/scrape cadence
# would otherwise cycle real request traces out of the bounded ring
_UNTRACED_PATHS = frozenset({
    "/livez", "/readyz", "/metrics", "/debug/traces", "/debug/config",
    "/debug/slo"})


class Server:
    """Serves the handler chain over TCP; also exposes `handle` for
    in-memory clients (reference GetEmbeddedClient, server.go:303-350)."""

    def __init__(self, deps: AuthzDeps,
                 authenticator: Optional[HeaderAuthenticator] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 config_dump: Optional[dict] = None,
                 ssl_context=None,
                 client_ca_configured: bool = False,
                 requestheader_allowed_names: tuple = (),
                 token_authenticator=None,
                 enable_debug_traces: bool = False,
                 slo_monitor=None,
                 enable_debug_slo: bool = False,
                 autoscale_controller=None):
        self.deps = deps
        self.authenticator = authenticator or HeaderAuthenticator()
        self.cert_authenticator = ClientCertAuthenticator()
        # kube static-token-file authn (authn.go:40-47); None = disabled
        self.token_authenticator = token_authenticator
        self.host = host
        self.port = port
        # sanitized options for /debug/config (the reference's debugmap
        # struct tags produce the same kind of secret-free dump)
        self.config_dump = config_dump
        # TLS serving (reference serves TLS with kube's secure-serving
        # stack, server.go:164-202). With a client CA configured, a peer's
        # verified cert IS its identity (CN -> user, O -> groups) — except
        # peers whose CN is in requestheader_allowed_names, which are
        # trusted FRONT PROXIES allowed to assert end-user identity via
        # X-Remote-* headers (kube's --requestheader-allowed-names
        # contract, authn.go:40-47). Cert-less connections never get
        # header identity when a client CA is configured.
        self.ssl_context = ssl_context
        self.client_ca_configured = client_ca_configured
        self.requestheader_allowed_names = set(requestheader_allowed_names)
        # /debug/traces posture mirrors /debug/config: traces name other
        # subjects' request paths and timings, so the endpoint is opt-in
        # (--enable-debug-traces) on top of authentication
        self.enable_debug_traces = enable_debug_traces
        # live SLO monitor (obs/slo.py); /debug/slo posture mirrors
        # /debug/traces — flag-gated on top of authentication
        self.slo_monitor = slo_monitor
        self.enable_debug_slo = enable_debug_slo
        # autoscale controller (autoscale/controller.py); surfaced on
        # /readyz so operators see dry-run proposals before trusting
        # --autoscale=apply
        self.autoscale_controller = autoscale_controller
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()  # live connection-handler tasks

    # -- handler chain -------------------------------------------------------

    async def handle(self, req: ProxyRequest) -> ProxyResponse:
        """Panic recovery → logging → tracing → request info → authn →
        authz. The root span adopts an incoming W3C ``traceparent`` (or
        mints a fresh trace); every response carries ``X-Trace-Id`` while
        tracing is on, so a shed/failed request is followable from the
        client's error body straight into ``/debug/traces``. Fixed infra
        endpoints (health probes, scrapes, the introspection endpoints
        themselves) never trace: at kubelet/Prometheus cadence their
        sampled zero-span traces would cycle real request traces out of
        the fixed ring on a low-traffic replica."""
        start = time.monotonic()
        trace_id = None
        if req.path in _UNTRACED_PATHS:
            resp = await self._recovered_inner(req)
        else:
            tp = next((v for k, v in req.headers.items()
                       if k.lower() == "traceparent"), None)
            with tracer.start("request", traceparent=tp,
                              method=req.method, path=req.path) as root:
                resp = await self._recovered_inner(req)
                root.set("status", resp.status)
                if resp.status >= 500 and not tracer.flagged("shed"):
                    # breaker-open / dependency-down responses are traces
                    # worth keeping: flag so tail sampling never drops
                    # them. Shed 503s stay "shed"-only — a load shed is
                    # the admission design WORKING, and it must not
                    # pollute an operator's error-trace filter
                    tracer.flag("error")
                trace_id = root.trace_id
                if trace_id is not None:
                    resp.headers.setdefault("X-Trace-Id", trace_id)
        dur = time.monotonic() - start
        metrics.counter("proxy_requests_total",
                        verb=(req.request_info.verb if req.request_info
                              else req.method),
                        code=resp.status).inc()
        metrics.histogram("proxy_request_seconds").observe(dur)
        if trace_id is not None and dur >= tracer.slow_s:
            log.warning("slow request: %s %s -> %d (%.1fms, trace %s)",
                        req.method, req.path, resp.status, dur * 1e3,
                        trace_id)
        log.info("%s %s -> %d (%.1fms)", req.method, req.path, resp.status,
                 dur * 1e3)
        return resp

    async def _recovered_inner(self, req: ProxyRequest) -> ProxyResponse:
        try:
            return await self._handle_inner(req)
        except Exception as e:  # panic recovery (server.go:149)
            log.error("panic serving %s %s: %s\n%s", req.method, req.path,
                      e, traceback.format_exc())
            metrics.counter("proxy_panics").inc()
            return kube_status(500, "internal error")

    async def _handle_inner(self, req: ProxyRequest) -> ProxyResponse:
        if req.path == "/livez":
            return ProxyResponse(status=200, body=b"ok")
        if req.path == "/readyz":
            # readiness is per-dependency: an open circuit breaker on the
            # upstream kube-apiserver or an engine endpoint makes the
            # replica unready, with the dependency NAMED in the body
            # (kube readyz check style) so the operator sees which leg is
            # degraded — instead of the unconditional 200 that would keep
            # routing traffic into guaranteed 503s
            reasons = [(b.dependency, r)
                       for b in getattr(self.deps, "breakers", ())
                       if (r := b.open_reason()) is not None]
            # replicated engine set: report role|term|lag so an
            # orchestrator can gate traffic THROUGH the failover window
            # (role != leader means requests would only 503 fail-closed)
            repl_line = None
            repl_fn = getattr(self.deps.engine, "replication_status", None)
            if repl_fn is not None:
                try:
                    # to_thread: the status probe is one blocking socket
                    # round trip — it must not park the event loop
                    st = await asyncio.to_thread(repl_fn)
                except Exception:  # noqa: BLE001 - readyz must answer
                    st = {"role": "electing", "term": None, "lag": None}
                detail = (f"role={st.get('role')} term={st.get('term')} "
                          f"lag={st.get('lag')}")
                if st.get("role") == "leader":
                    repl_line = f"replication: {detail}"
                else:
                    reasons.append(("replication", detail))
            info_lines = [] if repl_line is None else [repl_line]
            # sharded deployments (scaleout/): shard count, per-group
            # role/lag, map version — INFORMATIONAL like admission (a
            # degraded group degrades a slice of the keyspace; pulling
            # the whole replica would turn a partial outage into a full
            # one), but visible here BEFORE that group starts shedding
            shard_fn = getattr(self.deps.engine, "sharding_status", None)
            if shard_fn is not None:
                try:
                    st = await asyncio.to_thread(shard_fn)
                    per_group = " ".join(
                        f"g{g['group']}={g['role']}/"
                        f"{'?' if g['lag'] is None else g['lag']}"
                        for g in st["groups"])
                    info_lines.append(
                        f"sharding: groups={len(st['groups'])} "
                        f"map_version={st['version']} {per_group} "
                        f"pending_splits={st['pending_splits']}")
                    reb = st.get("rebalance")
                    if reb:
                        # a live tuple move in flight: informational
                        # like the sharding line (migration is the
                        # system working, not unreadiness)
                        info_lines.append(
                            f"rebalance: to_version="
                            f"{reb['to_version']} "
                            f"moving={reb['moving']} "
                            f"copied={reb['copied']} "
                            f"cut={reb['cut']} lag={reb['lag']}")
                except Exception:  # noqa: BLE001 - readyz must answer
                    info_lines.append("sharding: status unavailable")
            # live schema migration (migration/migrator.py): phase/lag
            # — INFORMATIONAL like rebalance (a migration in flight is
            # the system changing schemas without downtime, not
            # unreadiness); covers the sharded planner's aggregate and
            # the single-engine (in-proc or remote) status alike
            mig_fn = (getattr(self.deps.engine, "migration_status", None)
                      or getattr(self.deps.engine, "migrate_status",
                                 None))
            if mig_fn is not None:
                try:
                    mig = await asyncio.to_thread(mig_fn)
                except Exception:  # noqa: BLE001 - readyz must answer
                    mig = None
                if mig:
                    info_lines.append(
                        f"migration: phase={mig.get('phase')} "
                        f"classification={mig.get('classification')} "
                        f"lag={mig.get('lag')} "
                        f"backfilled={mig.get('backfilled')}")
            # autoscaler posture: INFORMATIONAL like migration — a
            # proposal (or a transition it started) is the elasticity
            # design working, not unreadiness
            if self.autoscale_controller is not None:
                try:
                    st = self.autoscale_controller.status()
                    last = st.get("last_proposal")
                    last_s = ("none" if not last else
                              f"{last['action']}->"
                              f"{last['target_groups']}")
                    info_lines.append(
                        f"autoscale: mode={st['mode']} "
                        f"groups={st['groups']} "
                        f"transitions={st['transitions']} "
                        f"last={last_s}")
                except Exception:  # noqa: BLE001 - readyz must answer
                    info_lines.append("autoscale: status unavailable")
            # admission shed/queue state is INFORMATIONAL: shedding is
            # the overload design working, not unreadiness — pulling a
            # shedding replica from rotation would dump its share of the
            # load onto the rest and cascade
            adm = getattr(self.deps, "admission", None)
            if adm is not None:
                st = adm.status()
                info_lines.append(
                    f"admission: limit={st['limit']} "
                    f"inflight={st['inflight']} queued={st['queued']} "
                    f"shed={st['shed_total']}")
            if reasons:
                body = "".join(f"[-]{dep}: {reason}\n"
                               for dep, reason in reasons)
                return ProxyResponse(
                    status=503, headers={"Content-Type": "text/plain"},
                    body=body.encode())
            body = b"ok" if not info_lines else (
                "".join(f"[+]{line}\n" for line in info_lines) + "ok"
            ).encode()
            return ProxyResponse(status=200, body=body)
        if req.path == "/metrics":
            return ProxyResponse(
                status=200, headers={"Content-Type": "text/plain"},
                body=metrics.render().encode())
        if req.request_info is None:
            req.request_info = parse_request_info(req.method, req.path,
                                                  req.query)
        if req.user is None and self.token_authenticator is not None:
            auth = next((v for k, v in req.headers.items()
                         if k.lower() == "authorization"), "")
            if auth.lower().startswith("bearer "):
                # to_thread: OIDC verification can do a blocking JWKS
                # fetch (plus modular-exponentiation work) — neither
                # belongs on the event loop
                with tracer.span("authn"):
                    user = await asyncio.to_thread(
                        self.token_authenticator.authenticate_token,
                        auth[7:].strip())
                if user is None:
                    # credentials were presented and are wrong: reject
                    # rather than falling through to weaker identities
                    return kube_status(401, "invalid bearer token",
                                       "Unauthorized")
                req.user = user
        if req.user is None:
            try:
                req.user = self.authenticator.authenticate(req.headers)
            except AuthenticationError as e:
                return kube_status(401, str(e), "Unauthorized")
        if req.path == "/debug/traces":
            # flag-gated AND authenticated (traces name other subjects'
            # request paths and timings); the ring is the recent
            # TAIL-KEPT set — error/shed/slow always, the rest sampled
            if not self.enable_debug_traces or not tracer.enabled:
                return kube_status(
                    404, "trace endpoint disabled "
                         "(--enable-debug-traces, --trace-sample>0)",
                    "NotFound")
            import json as _json

            try:
                limit = int(req.query_get("limit", "64"))
            except ValueError:
                limit = 64
            traces = tracer.recent(limit)
            # cross-process engine hosts keep their span fragments in
            # their OWN ring: fetch and stitch them in by trace_id so an
            # operator reads one complete trace here. In-process engines
            # (and tcp:// hosts sharing this interpreter) stitched live,
            # so only EXTERNAL fragments merge — never duplicates.
            fetch = getattr(self.deps.engine, "fetch_traces", None)
            if fetch is not None:
                try:
                    frags = await asyncio.to_thread(fetch, limit)
                except Exception:  # noqa: BLE001 - diagnostics only
                    frags = []
                # shallow-copy before stitching: recent() hands back the
                # ring's own dicts, and mutating them would re-append
                # fragments on every later fetch
                traces = [dict(t) for t in traces]
                by_id = {t["trace_id"]: t for t in traces}
                for f in frags:
                    if not f.get("external"):
                        continue
                    local = by_id.get(f["trace_id"])
                    if local is not None:
                        local["spans"] = local["spans"] + f["spans"]
                    else:
                        # a later fragment of the same trace (a re-aimed
                        # request leaves spans on several hosts) must
                        # merge into THIS entry, not append another
                        traces.append(f)
                        by_id[f["trace_id"]] = f
            return ProxyResponse(
                status=200, headers={"Content-Type": "application/json"},
                body=_json.dumps({"traces": traces}).encode())
        if req.path == "/debug/slo":
            # flag-gated AND authenticated: declared objectives +
            # multi-window burn rates, fresh-sampled so an operator
            # debugging an alert reads NOW, not the last tick
            if not self.enable_debug_slo or self.slo_monitor is None:
                return kube_status(
                    404, "SLO endpoint disabled "
                         "(--enable-debug-slo, --slo-objectives)",
                    "NotFound")
            import json as _json

            mon = self.slo_monitor
            await asyncio.to_thread(mon.tick)
            return ProxyResponse(
                status=200, headers={"Content-Type": "application/json"},
                body=_json.dumps(mon.status()).encode())
        if req.path == "/debug/config":
            # flag-gated (Options.enable_debug_config) AND authenticated:
            # the dump is allowlisted, but config topology still doesn't
            # belong on an endpoint that exists by default
            if self.config_dump is None:
                return kube_status(404, "not found", "NotFound")
            import json as _json

            return ProxyResponse(
                status=200, headers={"Content-Type": "application/json"},
                body=_json.dumps(self.config_dump, indent=2).encode())
        return await authorize(req, self.deps)

    # -- TCP serving ---------------------------------------------------------

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._serve_connection, self.host, self.port,
            ssl=self.ssl_context)
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("proxy listening on %s:%d (%s)", self.host, self.port,
                 "https" if self.ssl_context else "http")
        return self.port

    async def stop(self, grace: float = 2.0) -> None:
        """Stop listening and drain connections (utils/net.py: idle
        streaming handlers never write, so without the drain
        ``wait_closed()`` blocks forever on any idle watch)."""
        if self._server is None:
            return
        await drain_server(self._server, self._conns, grace)
        self._server = None

    async def _serve_connection(self, reader: asyncio.StreamReader,
                                writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conns.add(task)
        try:
            await self._serve_connection_inner(reader, writer)
        finally:
            self._conns.discard(task)

    async def _serve_connection_inner(self, reader: asyncio.StreamReader,
                                      writer: asyncio.StreamWriter) -> None:
        # cert identity is per-connection: resolve once, stamp each request
        peer_user = None
        peer_error: Optional[str] = None
        if self.ssl_context is not None:
            peercert = writer.get_extra_info("peercert")
            if peercert:
                try:
                    peer_user = self.cert_authenticator.authenticate_peer(
                        peercert)
                except AuthenticationError as e:
                    peer_error = str(e)
        try:
            while True:
                req = await _read_request(reader)
                if req is None:
                    return
                if peer_user is not None and \
                        peer_user.name in self.requestheader_allowed_names:
                    # trusted front proxy: its X-Remote-* headers carry the
                    # end-user identity (header authn path runs as usual)
                    pass
                elif peer_user is not None:
                    # verified client cert IS the identity; headers from
                    # ordinary cert users must not escalate
                    req.user = peer_user
                elif peer_error is not None or (
                        self.ssl_context is not None
                        and self.client_ca_configured):
                    # a client CA is configured: identity headers are only
                    # trusted from allowed cert-bearing front proxies
                    # (anyone can send headers; only proxies hold certs)
                    req.headers = {
                        k: v for k, v in req.headers.items()
                        if not k.lower().startswith("x-remote-")}
                resp = await self.handle(req)
                conn_hdr = next((v for k, v in req.headers.items()
                                 if k.lower() == "connection"), "")
                keep_alive = conn_hdr.lower() != "close"
                await _write_response(writer, resp)
                if resp.stream is not None or not keep_alive:
                    return
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except Exception:
            log.exception("connection handler error")
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass


async def _read_request(reader: asyncio.StreamReader) -> Optional[ProxyRequest]:
    try:
        request_line = await reader.readline()
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    if not request_line:
        return None
    parts = request_line.decode("latin-1").strip().split(" ")
    if len(parts) != 3:
        return None
    method, target, _version = parts
    headers: dict[str, str] = {}
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        k, _, v = line.decode("latin-1").partition(":")
        k, v = k.strip(), v.strip()
        if k.lower() in ("x-remote-group",) and k in headers:
            headers[k] = headers[k] + "," + v  # repeated group headers
        else:
            headers[k] = v
    body = b""
    if "Content-Length" in {k.title(): None for k in headers}:
        n = int(next(v for k, v in headers.items()
                     if k.lower() == "content-length"))
        if n > MAX_BODY:
            return None
        body = await reader.readexactly(n)
    elif any(k.lower() == "transfer-encoding"
             and "chunked" in v.lower() for k, v in headers.items()):
        chunks = []
        total = 0
        while True:
            size_line = await reader.readline()
            size = int(size_line.strip().split(b";")[0], 16)
            if size == 0:
                await reader.readline()
                break
            total += size
            if total > MAX_BODY:  # same cap as Content-Length bodies
                return None
            chunks.append(await reader.readexactly(size))
            await reader.readline()
        body = b"".join(chunks)
    u = urlsplit(target)
    query = parse_qs(u.query, keep_blank_values=True)
    return ProxyRequest(method=method, path=unquote(u.path), query=query,
                        headers=headers, body=body)


async def _write_response(writer: asyncio.StreamWriter,
                          resp: ProxyResponse) -> None:
    headers = dict(resp.headers)
    if resp.stream is not None:
        headers.pop("Content-Length", None)
        headers["Transfer-Encoding"] = "chunked"
    else:
        headers["Content-Length"] = str(len(resp.body))
    headers.setdefault("Content-Type", "application/json")
    lines = [f"HTTP/1.1 {resp.status} {_reason(resp.status)}\r\n"]
    for k, v in headers.items():
        lines.append(f"{k}: {v}\r\n")
    lines.append("\r\n")
    writer.write("".join(lines).encode("latin-1"))
    await writer.drain()
    if resp.stream is None:
        writer.write(resp.body)
        await writer.drain()
        return
    try:
        async for frame in resp.stream:
            writer.write(f"{len(frame):x}\r\n".encode())
            writer.write(frame)
            writer.write(b"\r\n")
            await writer.drain()
    finally:
        try:
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass


def _reason(status: int) -> str:
    return {
        200: "OK", 201: "Created", 202: "Accepted", 204: "No Content",
        400: "Bad Request", 401: "Unauthorized", 403: "Forbidden",
        404: "Not Found", 405: "Method Not Allowed", 409: "Conflict",
        422: "Unprocessable Entity", 500: "Internal Server Error",
        502: "Bad Gateway", 504: "Gateway Timeout",
    }.get(status, "Status")
