"""HTTP request/response abstractions shared by the authz middleware, the
asyncio server, the in-memory transport, and the fake upstream.

The reference plumbs net/http types end-to-end; here the middleware operates
on these small dataclasses so the same authorization/filtering logic runs
identically under the socket server, the in-memory embedded transport
(reference pkg/inmemory), and tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import AsyncIterator, Awaitable, Callable, Optional

from ..rules.input import RequestInfo, UserInfo


@dataclass
class ProxyRequest:
    method: str
    path: str  # path only, no query
    query: dict[str, list[str]] = field(default_factory=dict)
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""
    user: Optional[UserInfo] = None
    request_info: Optional[RequestInfo] = None

    def query_get(self, key: str, default: str = "") -> str:
        v = self.query.get(key)
        return v[0] if v else default

    @property
    def uri(self) -> str:
        if not self.query:
            return self.path
        parts = []
        for k, vs in self.query.items():
            for v in vs:
                parts.append(f"{k}={v}" if v != "" else k)
        return self.path + "?" + "&".join(parts)


@dataclass
class ProxyResponse:
    status: int = 200
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""
    # streaming responses (watch): async iterator of raw frame bytes; when
    # set, `body` is ignored and frames are written as they arrive
    stream: Optional[AsyncIterator[bytes]] = None

    @property
    def content_type(self) -> str:
        for k, v in self.headers.items():
            if k.lower() == "content-type":
                return v
        return ""


# An upstream is anything that can serve a ProxyRequest: the real
# kube-apiserver via the HTTP client, or the in-process fake used by tests
# (the envtest role in the reference e2e suite).
Upstream = Callable[[ProxyRequest], Awaitable[ProxyResponse]]


def json_response(status: int, obj) -> ProxyResponse:
    import json

    return ProxyResponse(
        status=status,
        headers={"Content-Type": "application/json"},
        body=json.dumps(obj).encode(),
    )


def kube_status(status: int, message: str, reason: str = "") -> ProxyResponse:
    """A kubernetes Status object response."""
    return json_response(status, {
        "kind": "Status",
        "apiVersion": "v1",
        "metadata": {},
        "status": "Failure" if status >= 400 else "Success",
        "message": message,
        "reason": reason,
        "code": status,
    })
