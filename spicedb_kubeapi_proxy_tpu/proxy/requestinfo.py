"""Kube request-info parsing: URL path + method -> RequestInfo.

The reference mounts k8s.io/apiserver's WithRequestInfo filter
(/root/reference/pkg/proxy/server.go:151); this is the same resolution
logic: api prefixes (/api core, /apis named groups), namespace scoping,
resource/name/subresource segments, and verb derivation from the HTTP
method (list vs get vs watch, deletecollection vs delete).
"""

from __future__ import annotations

from typing import Optional

from ..rules.input import RequestInfo

_METHOD_VERBS = {
    "GET": "get",
    "HEAD": "get",
    "POST": "create",
    "PUT": "update",
    "PATCH": "patch",
    "DELETE": "delete",
}

# paths that are never resource requests (discovery etc.)
NON_RESOURCE_PREFIXES = ("/openapi", "/version", "/healthz", "/livez",
                         "/readyz", "/metrics")


def parse_request_info(method: str, path: str,
                       query: Optional[dict] = None) -> RequestInfo:
    query = query or {}
    verb = _METHOD_VERBS.get(method.upper(), method.lower())
    info = RequestInfo(verb=verb, path=path, is_resource_request=False)
    info.label_selector = (query.get("labelSelector") or [""])[0]
    info.field_selector = (query.get("fieldSelector") or [""])[0]

    parts = [p for p in path.split("/") if p]
    if not parts:
        return info
    if path.startswith(NON_RESOURCE_PREFIXES):
        return info

    # /api/v1/... or /apis/<group>/<version>/...
    if parts[0] == "api":
        if len(parts) < 2:
            return info
        info.api_group = ""
        info.api_version = parts[1]
        rest = parts[2:]
    elif parts[0] == "apis":
        if len(parts) < 3:
            return info
        info.api_group = parts[1]
        info.api_version = parts[2]
        rest = parts[3:]
    else:
        return info

    if not rest:
        return info  # bare discovery (/api/v1)
    info.is_resource_request = True

    # namespaces/<ns>/<resource>/... except when namespaces IS the resource;
    # /namespaces/<name>/{status,finalize} are subresources OF a namespace
    # (k8s RequestInfo special case), not namespaced resources
    if rest[0] == "namespaces" and len(rest) == 3 and \
            rest[2] in ("status", "finalize"):
        info.resource = "namespaces"
        info.name = rest[1]
        info.subresource = rest[2]
        _finish_verb(info, query)
        return info
    if rest[0] == "namespaces" and len(rest) >= 3:
        info.namespace = rest[1]
        rest = rest[2:]
    elif rest[0] == "namespaces":
        # /api/v1/namespaces or /api/v1/namespaces/<name>
        info.resource = "namespaces"
        if len(rest) >= 2:
            info.name = rest[1]
        rest = rest[2:] if len(rest) >= 2 else []
        if rest:
            info.subresource = rest[0]
        _finish_verb(info, query)
        return info

    info.resource = rest[0]
    if len(rest) >= 2:
        info.name = rest[1]
    if len(rest) >= 3:
        info.subresource = rest[2]
    _finish_verb(info, query)
    return info


def _truthy_param(query: dict, key: str) -> bool:
    vals = query.get(key)
    if not vals:
        return False
    v = vals[0]
    return v in ("", "1", "true", "True")


def _finish_verb(info: RequestInfo, query: dict) -> None:
    if info.verb == "get" and not info.name:
        info.verb = "watch" if _truthy_param(query, "watch") else "list"
    elif info.verb == "get" and _truthy_param(query, "watch"):
        info.verb = "watch"
    elif info.verb == "delete" and not info.name:
        info.verb = "deletecollection"
