"""OIDC bearer-token authentication.

The last of the reference's four built-in authenticators
(/root/reference/pkg/proxy/authn.go:40-47 wires kube's client-cert, OIDC,
token-file, and request-header stack): an IDP-issued JWT arrives as
``Authorization: Bearer``, is verified against the issuer's JWKS, and its
claims map to a kube user identity. Flags mirror the kube-apiserver OIDC
option names (--oidc-issuer-url, --oidc-client-id, --oidc-username-claim,
--oidc-username-prefix, --oidc-groups-claim, --oidc-groups-prefix,
--oidc-ca-file, --oidc-signing-algs), and the claim-mapping rules follow
kube's documented semantics:

- ``iss`` must equal the configured issuer exactly;
- ``aud`` must contain the client id (string or array form);
- ``exp``/``nbf`` enforced with a small clock skew;
- username = the username claim, prefixed with ``<issuer>#`` by default
  when the claim is not ``email`` (``-`` disables prefixing, any other
  value IS the prefix);
- with ``email`` as the username claim, a present-but-false
  ``email_verified`` rejects the token;
- groups claim may be a string or an array of strings, each prefixed
  with the groups prefix.

JWKS keys are fetched from the issuer's discovery document (or an
explicit ``jwks_uri``), cached, and refreshed on unknown ``kid`` with a
rate limit so an attacker cannot hammer the IDP through us.
"""

from __future__ import annotations

import json
import logging
import ssl
import threading
import time
import urllib.request
from typing import Callable, Optional

from ..rules.input import UserInfo
from . import jose

log = logging.getLogger("sdbkp.oidc")

DISCOVERY_PATH = "/.well-known/openid-configuration"
DEFAULT_ALGS = ("RS256",)  # kube's default --oidc-signing-algs
ALL_ALGS = ("RS256", "RS384", "RS512", "ES256", "ES384")
# minimum seconds between JWKS refetches triggered by unknown kids
REFRESH_COOLDOWN = 10.0


class OIDCError(Exception):
    pass


def parse_signing_algs(spec: str) -> tuple:
    """Comma-separated alg spec -> validated tuple (shared by options
    validation and the authenticator constructor so they cannot drift)."""
    algs = tuple(a.strip() for a in spec.split(",") if a.strip())
    bad = [a for a in algs if a not in ALL_ALGS]
    if not algs or bad:
        raise OIDCError(
            f"invalid signing algs {spec!r} "
            f"(supported: {', '.join(ALL_ALGS)})")
    return algs


def _default_fetch(url: str, ca_file: Optional[str],
                   timeout: float) -> bytes:
    ctx = None
    if url.startswith("https://"):
        ctx = ssl.create_default_context(cafile=ca_file)
    with urllib.request.urlopen(url, timeout=timeout, context=ctx) as r:
        return r.read()


class OIDCAuthenticator:
    """Verifies bearer JWTs; ``authenticate_token`` returns the mapped
    :class:`UserInfo` or ``None`` (the serving layer turns a presented-
    but-rejected credential into a 401). Thread-safe."""

    def __init__(self, issuer_url: str, client_id: str,
                 username_claim: str = "sub",
                 username_prefix: Optional[str] = None,
                 groups_claim: Optional[str] = None,
                 groups_prefix: str = "",
                 ca_file: Optional[str] = None,
                 required_claims: Optional[dict] = None,
                 signing_algs: tuple = DEFAULT_ALGS,
                 jwks_uri: Optional[str] = None,
                 skew: float = 10.0,
                 fetch: Optional[Callable[[str], bytes]] = None,
                 http_timeout: float = 10.0):
        if not issuer_url or not client_id:
            raise OIDCError("issuer_url and client_id are required")
        signing_algs = parse_signing_algs(",".join(signing_algs))
        # kube compares the token's iss claim to the configured issuer URL
        # EXACTLY (a trailing-slash difference rejects); only the discovery
        # URL construction normalizes the slash.
        self.issuer = issuer_url
        self._issuer_base = issuer_url.rstrip("/")
        self.client_id = client_id
        self.username_claim = username_claim
        self.username_prefix = username_prefix
        self.groups_claim = groups_claim
        self.groups_prefix = groups_prefix
        # kube --oidc-required-claim key=value pairs: every pair must be
        # present with exactly that string value
        self.required_claims = dict(required_claims or {})
        self.signing_algs = tuple(signing_algs)
        self.skew = skew
        self._jwks_uri = jwks_uri
        # bounds how long a refresh-needing validation may wait behind an
        # in-flight fetch (waiters use ~2x: discovery + JWKS)
        self.http_timeout = http_timeout
        self._fetch = fetch or (
            lambda url: _default_fetch(url, ca_file, http_timeout))
        # _lock guards the key map + refresh stamp only; the network fetch
        # runs OUTSIDE it, serialized by _refresh_lock (single-flight), so
        # a hung IDP socket never blocks validations whose kid is cached.
        self._lock = threading.Lock()
        self._refresh_lock = threading.Lock()
        self._keys: Optional[dict[str, dict]] = None  # kid -> JWK
        self._keys_unnamed: list[dict] = []  # JWKs without a kid
        self._last_refresh = 0.0

    # -- JWKS ----------------------------------------------------------------

    def _discover_jwks_uri(self) -> str:
        url = self._issuer_base + DISCOVERY_PATH
        doc = json.loads(self._fetch(url))
        if doc.get("issuer", "").rstrip("/") != self._issuer_base:
            raise OIDCError(
                f"discovery document issuer {doc.get('issuer')!r} does not "
                f"match configured issuer {self.issuer!r}")
        uri = doc.get("jwks_uri")
        if not uri:
            raise OIDCError("discovery document has no jwks_uri")
        return uri

    def _refresh(self) -> None:
        """Network half of a JWKS refresh. Caller holds _refresh_lock (so
        fetches are single-flight) but NOT _lock — concurrent validations
        against the cached map proceed while this blocks on the IDP."""
        if self._jwks_uri is None:
            self._jwks_uri = self._discover_jwks_uri()
        doc = json.loads(self._fetch(self._jwks_uri))
        keys: dict[str, dict] = {}
        unnamed: list[dict] = []
        for k in doc.get("keys", []):
            if k.get("use") not in (None, "sig"):
                continue
            if k.get("kid"):
                keys[k["kid"]] = k
            else:
                unnamed.append(k)
        with self._lock:
            self._keys = keys
            self._keys_unnamed = unnamed

    def _stamp_attempt(self) -> None:
        # stamp the ATTEMPT, not just success: with the IDP down, a storm
        # of forged-kid tokens must not translate into a fetch per token
        with self._lock:
            self._last_refresh = time.monotonic()

    def _candidate_keys(self, kid: Optional[str]) -> list[dict]:
        """JWKs to try for a token, refreshing on an unknown kid (key
        rotation) no more than once per cooldown window.

        Stale-while-revalidate: a validation whose kid is in the cached
        map never touches the network or waits on a fetch in flight; only
        requests that actually need a refresh (cold start, unknown kid)
        serialize on the single-flight lock — the winner fetches once,
        waiters then read the refreshed cache instead of 401ing, and the
        wait is bounded by the fetch's http_timeout. The cooldown stamp
        still caps fetch frequency under forged-kid storms or a down
        IDP."""
        with self._lock:
            keys = self._keys
            last = self._last_refresh
        if keys is not None:
            if kid is None:
                with self._lock:
                    return list(self._keys.values()) + \
                        list(self._keys_unnamed)
            k = keys.get(kid)
            if k is not None:
                return [k]
            # unknown kid — plausible key rotation; at most one refetch
            # per cooldown window. All needers serialize on the lock: the
            # winner fetches, waiters re-read the refreshed map when it
            # releases (a rotation fetch window must not 401 the very
            # tokens the rotation is for)
            if time.monotonic() - last > REFRESH_COOLDOWN:
                # wait bounded by what a healthy fetch can take: a hung
                # IDP must not stall rotation-window requests longer than
                # the fetch's own timeout would
                if not self._refresh_lock.acquire(
                        timeout=self.http_timeout * 2):
                    return []
                try:
                    # re-check under the lock: the fetch may have just
                    # finished — back-to-back fetches would defeat the
                    # cooldown's forged-kid-storm defense
                    with self._lock:
                        last = self._last_refresh
                        k = (self._keys or {}).get(kid)
                    if k is None and \
                            time.monotonic() - last > REFRESH_COOLDOWN:
                        self._stamp_attempt()
                        self._refresh()
                finally:
                    self._refresh_lock.release()
                with self._lock:
                    k = (self._keys or {}).get(kid)
                return [k] if k is not None else []
            return []
        # no key map yet (first token, or every earlier fetch failed):
        # retry only past the cooldown. One fetcher at a time; the others
        # WAIT on the lock (bounded by the fetch's http_timeout) and then
        # validate against the freshly-cached keys — a proxy restart
        # under a fleet reconnect storm must not convert one fetch's
        # latency into a burst of spurious 401s
        # the cooldown decision happens UNDER the lock: the attempt stamp
        # is written before the fetch starts, so a pre-lock check cannot
        # tell "a fetch is in flight right now" (wait for it) from "the
        # last fetch just failed" (cool down)
        if not self._refresh_lock.acquire(timeout=self.http_timeout * 2):
            raise OIDCError("JWKS fetch timed out behind an in-flight "
                            "refresh")
        try:
            # re-check under the lock (see the rotation branch above): a
            # just-finished fetch that still yielded no keys means the
            # IDP is down — cool down instead of immediately refetching
            with self._lock:
                last = self._last_refresh
                have_keys = self._keys is not None
            if not have_keys and last and \
                    time.monotonic() - last <= REFRESH_COOLDOWN:
                raise OIDCError("JWKS unavailable (cooling down)")
            if not have_keys:
                self._stamp_attempt()
                self._refresh()
        finally:
            self._refresh_lock.release()
        with self._lock:
            assert self._keys is not None  # _refresh raises on failure
            if kid is not None:
                k = self._keys.get(kid)
                return [k] if k is not None else []
            return list(self._keys.values()) + list(self._keys_unnamed)

    # -- token validation ----------------------------------------------------

    def authenticate_token(self, token: str) -> Optional[UserInfo]:
        try:
            return self._authenticate(token)
        except (jose.JoseError, OIDCError) as e:
            log.info("oidc: rejecting token: %s", e)
            return None
        except Exception as e:  # JWKS fetch failures etc.
            log.warning("oidc: verification unavailable: %s", e)
            return None

    def _authenticate(self, token: str) -> Optional[UserInfo]:
        header, claims, signing_input, sig = jose.parse_compact(token)
        alg = header.get("alg")
        if alg not in self.signing_algs:
            raise OIDCError(f"alg {alg!r} not in accepted set "
                            f"{self.signing_algs}")
        # exact comparison, matching kube: a trailing-slash difference
        # between the token's iss and the configured issuer REJECTS
        if claims.get("iss") != self.issuer:
            raise OIDCError(f"issuer {claims.get('iss')!r} does not match "
                            f"{self.issuer!r}")
        keys = self._candidate_keys(header.get("kid"))
        if not keys:
            raise OIDCError(f"no JWKS key for kid {header.get('kid')!r}")
        verified = False
        for k in keys:
            try:
                if jose.verify_jws(header, signing_input, sig, k):
                    verified = True
                    break
            except jose.JoseError:
                # a mismatched key TYPE among kid-less candidates (EC key
                # tried against an RS token) must not abort the scan —
                # later keys may still legitimately verify
                continue
        if not verified:
            raise OIDCError("signature verification failed")
        self._validate_time(claims)
        self._validate_audience(claims)
        for k, v in self.required_claims.items():
            if claims.get(k) != v:
                raise OIDCError(
                    f"required claim {k}={v!r} not satisfied "
                    f"(got {claims.get(k)!r})")
        return self._map_identity(claims)

    def _validate_time(self, claims: dict) -> None:
        now = time.time()
        exp = claims.get("exp")
        if not isinstance(exp, (int, float)):
            raise OIDCError("token has no exp claim")
        if now > exp + self.skew:
            raise OIDCError("token is expired")
        nbf = claims.get("nbf")
        if isinstance(nbf, (int, float)) and now < nbf - self.skew:
            raise OIDCError("token not yet valid (nbf)")

    def _validate_audience(self, claims: dict) -> None:
        aud = claims.get("aud")
        if isinstance(aud, str):
            ok = aud == self.client_id
        elif isinstance(aud, list):
            ok = self.client_id in aud
        else:
            ok = False
        if not ok:
            raise OIDCError(
                f"audience {aud!r} does not include {self.client_id!r}")

    def _map_identity(self, claims: dict) -> UserInfo:
        raw = claims.get(self.username_claim)
        if not isinstance(raw, str) or not raw:
            raise OIDCError(
                f"username claim {self.username_claim!r} missing or not a "
                "string")
        if self.username_claim == "email":
            verified = claims.get("email_verified")
            # kube parses bool-ish strings via strconv.ParseBool; IDPs do
            # emit "true" as a string in the wild
            if isinstance(verified, str):
                verified = verified.strip().lower() in ("1", "t", "true")
            if verified is not None and verified is not True:
                raise OIDCError("email_verified is not true")
        prefix = self.username_prefix
        if prefix is None:
            # kube default: non-email claims are prefixed with `issuer#`
            # so `system:` names cannot be minted by the IDP
            prefix = "" if self.username_claim == "email" \
                else self.issuer + "#"
        elif prefix == "-":
            prefix = ""
        name = prefix + raw
        groups: list[str] = []
        if self.groups_claim:
            g = claims.get(self.groups_claim)
            if isinstance(g, str):
                g = [g]
            if g is not None:
                if not isinstance(g, list) or \
                        not all(isinstance(x, str) for x in g):
                    raise OIDCError(
                        f"groups claim {self.groups_claim!r} must be a "
                        "string or array of strings")
                groups = [self.groups_prefix + x for x in g]
        return UserInfo(name=name, groups=groups, extra={})


class ChainTokenAuthenticator:
    """Tries bearer authenticators in order; first mapped identity wins
    (kube's union token authenticator shape). Returns None when every
    member rejects — the serving layer then answers 401."""

    def __init__(self, members: list):
        self.members = list(members)

    def authenticate_token(self, token: str) -> Optional[UserInfo]:
        for m in self.members:
            user = m.authenticate_token(token)
            if user is not None:
                return user
        return None
