"""Admission control: cost-classed, per-tenant fair queueing with
adaptive concurrency and priority load shedding.

The traffic-handling half of the overload story (PR 1-4 built the
failure-handling half): every engine-bound request passes through an
:class:`AdmissionController` before it may occupy the dispatch pool.

- ``classes.py`` — the cost classifier: each operation maps to one of
  five classes (check / bulk-check / lookup-prefilter / watch-recompute
  / write-dtx) carrying a concurrency **weight** (how much of the device
  budget one admitted op occupies) and a shed **priority** (watch ticks
  shed first, then lists, then checks; writes last).
- ``limiter.py`` — the adaptive concurrency limiter: AIMD on the
  gradient of observed engine latency against a decayed-minimum
  baseline, so the admitted-cost ceiling tracks what the device can
  actually absorb instead of a static guess.
- ``controller.py`` — the per-tenant weighted fair queue (token-bucket
  debt decay, bounded per-tenant and global depth), priority load
  shedding, and the sync/async acquire surface. Rejections raise
  :class:`AdmissionRejected`, a
  :class:`~..utils.resilience.DependencyUnavailable` subclass — the
  authz middleware's existing fail-closed path turns it into a bounded
  kube 503 with a ``Retry-After`` header, and
  ``admission_shed_total{class=...}`` accounts for every one.

Wired in two places: the authz middleware (per authenticated user — no
subject can monopolize a proxy replica's engine time) and the engine
host server (per proxy-replica peer — a shared ``tcp://`` engine is
protected from the aggregate of many replicas).
"""

from .classes import (  # noqa: F401
    BULK_CHECK,
    CHECK,
    CLASSES,
    LOOKUP_PREFILTER,
    REBALANCE,
    WATCH_RECOMPUTE,
    WRITE_DTX,
    CostClass,
    classify_op,
    classify_request,
)
from .controller import (  # noqa: F401
    AdmissionController,
    AdmissionRejected,
    Ticket,
    validate_config,
)
from .limiter import AdaptiveLimiter  # noqa: F401
