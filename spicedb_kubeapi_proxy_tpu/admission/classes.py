"""The cost classifier: operation -> cost class.

Per-dispatch cost on the compiled graph is predictable enough to budget
against (the TpuGraphs premise, PAPERS.md): a single check reads one
slot, a bulk check shares one fixpoint across its items, a list
prefilter reads a whole type's slot range, and a watch-hub recompute is
a prefilter re-run triggered by write traffic rather than a waiting
client. Each class carries:

- ``weight`` — concurrency units one admitted op occupies against the
  adaptive limit (a lookup occupies 4x what a check does, so 8 admitted
  lists and 32 admitted checks exert the same device pressure);
- ``priority`` — shed order under saturation, LOWEST first: watch
  recomputes (an overloaded hub degrades to staler allowed-sets, not
  dropped requests), then list prefilters, then checks; writes last
  (dual-writes are the requests users retry by hand).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..rules.proxyrule import WRITE_VERBS  # noqa: F401 - one owner


@dataclass(frozen=True)
class CostClass:
    name: str
    weight: float  # concurrency units occupied while admitted
    priority: int  # shed order: lower sheds first

    def __str__(self) -> str:
        return self.name

    def scaled(self, units: int) -> "CostClass":
        """This class charged ``units`` times — the scale-out rule: a
        scatter op is charged once per touched shard (a 4-group
        LookupResources occupies 4x one group's lookup budget), while
        the NAME (and so the shed/metric label) stays the class's own.
        ``units <= 1`` returns self unchanged."""
        if units <= 1:
            return self
        return CostClass(self.name, self.weight * units, self.priority)


CHECK = CostClass("check", 1.0, 2)
BULK_CHECK = CostClass("bulk-check", 2.0, 2)
LOOKUP_PREFILTER = CostClass("lookup-prefilter", 4.0, 1)
WATCH_RECOMPUTE = CostClass("watch-recompute", 4.0, 0)
WRITE_DTX = CostClass("write-dtx", 2.0, 3)
# shard-rebalance mover traffic (scaleout/rebalance.py slice ops):
# cost-accounted like any tenant's bulk work, and the FIRST class shed
# under saturation — a live migration yields to serving traffic by
# design (the mover backs off by the shed's Retry-After and resumes)
REBALANCE = CostClass("rebalance", 2.0, -1)

CLASSES = {c.name: c for c in (CHECK, BULK_CHECK, LOOKUP_PREFILTER,
                               WATCH_RECOMPUTE, WRITE_DTX, REBALANCE)}

# engine-host wire ops that pass through admission (engine/remote.py
# EngineServer._dispatch); everything else — auth, failover_state,
# revision, watch/mirror subscriptions, id-table syncs — is either
# control-plane or too cheap to queue
_OP_CLASSES = {
    "check_bulk": CHECK,  # promoted to BULK_CHECK by item count
    "lookup_resources": LOOKUP_PREFILTER,
    "lookup_mask": LOOKUP_PREFILTER,
    "lookup_subjects": LOOKUP_PREFILTER,  # chunked bulk checks inside
    # one frontier-exchange leg is a batch of lookup_resources against
    # the group's local tuples — same cost shape, same shed class (the
    # planner's scatter fails closed if any leg sheds); frontier_pairs
    # is a pure schema walk and stays ungated control-plane
    "frontier_expand": LOOKUP_PREFILTER,
    "read_relationships": CHECK,
    "watch_since": WATCH_RECOMPUTE,
    "write_relationships": WRITE_DTX,
    "delete_relationships": WRITE_DTX,
    # the live tuple mover's data plane: slice export, idempotent
    # import, catch-up replay, and GC — all sheddable migration traffic
    "slice_read": REBALANCE,
    "slice_load": REBALANCE,
    "slice_apply": REBALANCE,
    "slice_drop": REBALANCE,
    "slice_watch": REBALANCE,
    # live schema migration control plane (migration/migrator.py):
    # operator-driven bulk work, cost-accounted and sheddable beneath
    # tenant traffic exactly like the tuple mover's slice ops
    "migrate_begin": REBALANCE,
    "migrate_status": REBALANCE,
    "migrate_cut": REBALANCE,
    "migrate_abort": REBALANCE,
}


def classify_op(op: str, n_items: int = 1) -> "CostClass | None":
    """Cost class for an engine-host wire op, or None for ungated ops."""
    cls = _OP_CLASSES.get(op)
    if cls is CHECK and op == "check_bulk" and n_items > 1:
        return BULK_CHECK
    return cls


def classify_request(verb: str, rules) -> CostClass:
    """Cost class for one proxy request, from its verb and the matched
    rule set — the class of the request's most expensive engine-bound
    phase. Exception-free by construction (multi-prefilter/multi-update
    misconfigurations surface later on their own paths)."""
    if verb in WRITE_VERBS:
        return WRITE_DTX
    has_prefilter = any(r.pre_filters for r in rules)
    if verb == "watch":
        # a prefiltered watch drives hub recomputes for its lifetime; a
        # plain watch only pays its admission checks
        return WATCH_RECOMPUTE if has_prefilter else CHECK
    if has_prefilter or (verb == "list"
                         and any(r.post_filters for r in rules)):
        return LOOKUP_PREFILTER
    n_checks = sum(len(r.checks) for r in rules)
    return BULK_CHECK if n_checks > 1 else CHECK
