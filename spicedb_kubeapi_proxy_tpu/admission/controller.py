"""The admission controller: fair queueing + adaptive limit + shedding.

One controller guards one dispatch pool (a proxy replica's in-process
engine, or the engine host's worker executor). The flow per request:

1. **classify** (admission/classes.py) — done by the caller, which knows
   the operation.
2. **admit or queue** — if nothing is queued and the weighted in-flight
   cost fits under the adaptive limit, the request is admitted
   immediately. Otherwise it queues behind its tenant's FIFO, bounded
   per-tenant and globally.
3. **fair dequeue** — each release drains the queue by weighted fair
   share: every tenant carries a *debt* of recently-consumed cost units
   that decays at ``tenant_rate`` units/second (the token-bucket refill)
   and is capped at ``tenant_burst`` (so a finished storm is forgiven in
   bounded time); the tenant with the LEAST debt goes next. A tenant
   issuing expensive LookupResources storms accumulates debt 4x faster
   than one issuing checks and is scheduled behind everyone else —
   weighted fairness over device time, not request count.
4. **shed** — when a queue bound is hit, the LOWEST-priority queued
   request makes room for a higher-priority arrival (watch ticks first,
   then lists, then checks; writes last); an arrival that outranks
   nothing is shed itself. Queued requests also shed when their wait
   exceeds ``queue_timeout`` — a queued request NEVER hangs. Every
   rejection raises :class:`AdmissionRejected` (the middleware's
   fail-closed 503 + Retry-After family) and lands in
   ``admission_shed_total{class=...}``.

Thread-safe, loop-friendly: the sync surface (``acquire``) parks on an
event, the async surface (``acquire_async``) on a future resolved via
``call_soon_threadsafe`` — both share one accounting core, so the authz
middleware (event loop) and bench/worker threads see the same queue.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import deque
from typing import Optional

from ..utils.metrics import metrics
from ..utils.resilience import DependencyUnavailable
from .classes import CostClass
from .limiter import AdaptiveLimiter


class AdmissionRejected(DependencyUnavailable):
    """Load shed: the request was refused BEFORE any engine dispatch (or
    durable side effect), so retrying is always safe. Subclasses
    :class:`DependencyUnavailable` so the authz middleware maps it to
    the existing fail-closed kube 503 + ``Retry-After`` path, counted
    under its own ``dependency`` label (distinguishable from breaker
    opens and ``NotLeaderError`` in
    ``proxy_dependency_unavailable_total``)."""

    def __init__(self, op_class: str, reason: str,
                 retry_after: float = 1.0, dependency: str = "admission"):
        super().__init__(dependency, f"{op_class}: {reason}",
                         retry_after=retry_after)
        self.op_class = op_class
        self.reason = reason


def validate_config(initial_concurrency: float, min_concurrency: float,
                    max_concurrency: float, tenant_rate: float,
                    tenant_burst: float, tenant_depth: int,
                    global_depth: int, queue_timeout: float) -> None:
    """The ONE owner of admission flag bounds; proxy options and the
    engine-host CLI both call it so their accepted configs can never
    drift. Raises ValueError with an operator-facing message."""
    if not 0 < min_concurrency <= initial_concurrency <= max_concurrency:
        raise ValueError(
            "need 0 < admission-min-concurrency <= "
            "admission-initial-concurrency <= admission-max-concurrency")
    if tenant_rate <= 0 or tenant_burst <= 0:
        raise ValueError("admission-tenant-rate/-burst must be > 0")
    if tenant_depth < 1 or global_depth < 1:
        raise ValueError("admission queue depths must be >= 1")
    if queue_timeout <= 0:
        raise ValueError("admission-queue-timeout must be > 0")


class Ticket:
    """One admitted request's grant; release EXACTLY once (idempotent —
    double releases are ignored, not double-credited)."""

    __slots__ = ("_ctrl", "tenant", "cls", "granted_at", "_released")

    def __init__(self, ctrl: "AdmissionController", tenant: str,
                 cls: CostClass, granted_at: float):
        self._ctrl = ctrl
        self.tenant = tenant
        self.cls = cls
        self.granted_at = granted_at
        self._released = False

    def release(self, observe: bool = True) -> None:
        """Hand the capacity back. ``observe=False`` returns the slot
        WITHOUT feeding the limiter — for operations whose duration is
        dominated by a deliberate non-engine wait (e.g. an engine-host
        write blocking on synchronous replication), which would
        otherwise read as engine congestion and collapse the limit.
        Idempotence is decided under the controller lock (_release), so
        concurrent releases from a worker thread and the event loop can
        never double-credit; this unlocked read is only a fast path."""
        if self._released:
            return
        self._ctrl._release(self, observe=observe)

    def __enter__(self) -> "Ticket":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


# waiter states
_QUEUED, _GRANTED, _SHED = 0, 1, 2


class _Waiter:
    __slots__ = ("tenant", "cls", "deliver", "enqueued_at", "granted_at",
                 "seq", "state")

    def __init__(self, tenant: str, cls: CostClass, deliver,
                 enqueued_at: float, seq: int):
        self.tenant = tenant
        self.cls = cls
        self.deliver = deliver  # deliver(exc_or_None), called OFF-lock
        self.enqueued_at = enqueued_at
        self.granted_at = 0.0
        self.seq = seq
        self.state = _QUEUED


class _Tenant:
    __slots__ = ("name", "debt", "last", "queue")

    def __init__(self, name: str, now: float):
        self.name = name
        self.debt = 0.0  # outstanding cost units; decays at tenant_rate
        self.last = now
        self.queue: deque = deque()  # FIFO of _Waiter


class AdmissionController:
    """See module docstring. ``dependency`` labels this controller's
    metrics and rejections ("admission" on the proxy,
    "engine-admission" on the engine host)."""

    def __init__(self, initial_concurrency: float = 32.0,
                 min_concurrency: float = 4.0,
                 max_concurrency: float = 512.0,
                 tenant_rate: float = 50.0, tenant_burst: float = 100.0,
                 tenant_depth: int = 32, global_depth: int = 256,
                 queue_timeout: float = 1.0,
                 dependency: str = "admission",
                 limiter: Optional[AdaptiveLimiter] = None,
                 clock=time.monotonic):
        # flag-level bounds (including tenant_rate/burst > 0) are owned
        # by validate_config at the options/CLI layer; the constructor
        # deliberately permits tenant_rate=0 — deterministic tests and
        # benches freeze debt decay with it — and only rejects values
        # that would break the controller's own invariants
        if tenant_depth < 1 or global_depth < 1:
            raise ValueError("queue depths must be >= 1")
        if queue_timeout <= 0:
            raise ValueError("queue-timeout must be > 0")
        self.limiter = limiter or AdaptiveLimiter(
            initial_concurrency, min_concurrency, max_concurrency,
            dependency=dependency)
        self.tenant_rate = float(tenant_rate)
        self.tenant_burst = float(tenant_burst)
        self.tenant_depth = tenant_depth
        self.global_depth = global_depth
        self.queue_timeout = queue_timeout
        self.dependency = dependency
        self._clock = clock
        self._lock = threading.Lock()
        self._tenants: dict[str, _Tenant] = {}
        # tenants with a non-empty queue: the ONLY set the drain, the
        # shed-victim scan, and the retry-after estimate iterate — with
        # per-user tenancy the full dict holds every subject ever seen,
        # and an O(all-tenants) sweep per grant inside the global lock
        # would make admission itself the contention point under load
        self._backlogged: set = set()
        self._prune_above = 4096  # amortized idle-tenant sweep threshold
        self._queued = 0
        self._queued_cost = 0.0  # running sum: O(1) Retry-After estimate
        self._inflight = 0
        self._inflight_cost = 0.0
        self._shed_total = 0
        self._seq = 0

    # -- accounting core (everything below the public surface holds
    # -- self._lock; deliver callbacks always run OFF-lock) ------------------

    def _tenant(self, name: str, now: float) -> _Tenant:
        t = self._tenants.get(name)
        if t is None:
            if len(self._tenants) >= self._prune_above:
                # prune decayed-idle tenants so per-user tenancy cannot
                # grow the dict without bound — AMORTIZED: the next
                # sweep waits for substantial growth past what survived,
                # so a high-cardinality steady state (everything still
                # in its decay window) cannot pay this O(tenants) scan
                # on every new-tenant creation
                for k in [k for k, v in self._tenants.items()
                          if not v.queue
                          and v.debt <= (now - v.last) * self.tenant_rate]:
                    del self._tenants[k]
                self._prune_above = max(4096, 2 * len(self._tenants))
            t = self._tenants[name] = _Tenant(name, now)
        return t

    def _decay(self, t: _Tenant, now: float) -> None:
        if now > t.last:
            t.debt = max(0.0, t.debt - (now - t.last) * self.tenant_rate)
            t.last = now

    def _charge(self, t: _Tenant, cls: CostClass) -> None:
        t.debt = min(self.tenant_burst, t.debt + cls.weight)

    def _fits(self, cls: CostClass) -> bool:
        return (self._inflight_cost + cls.weight <= self.limiter.limit
                or self._inflight == 0)  # one op always fits: no wedging

    def _admit_locked(self, t: _Tenant, cls: CostClass) -> None:
        self._inflight += 1
        self._inflight_cost += cls.weight
        self._charge(t, cls)
        metrics.counter("admission_admitted_total",
                        **{"class": cls.name}).inc()
        metrics.gauge("admission_inflight_cost",
                      dependency=self.dependency).set(self._inflight_cost)

    def _drain_locked(self, now: float) -> list[_Waiter]:
        """Grant queued waiters in weighted-fair order while capacity
        lasts; returns them for OFF-lock delivery."""
        granted: list[_Waiter] = []
        while self._queued:
            best: Optional[_Tenant] = None
            best_key = None
            for t in self._backlogged:
                self._decay(t, now)
                key = (t.debt, t.queue[0].seq)
                if best is None or key < best_key:
                    best, best_key = t, key
            if best is None:  # stale count; repaired defensively
                self._queued = 0
                break
            w = best.queue[0]
            if not self._fits(w.cls):
                break
            best.queue.popleft()
            if not best.queue:
                self._backlogged.discard(best)
            self._queued -= 1
            self._queued_cost = max(0.0, self._queued_cost - w.cls.weight)
            w.state = _GRANTED
            w.granted_at = now
            self._admit_locked(best, w.cls)
            metrics.histogram("admission_queue_seconds",
                              dependency=self.dependency).observe(
                max(0.0, now - w.enqueued_at))
            granted.append(w)
        metrics.gauge("admission_queue_depth",
                      dependency=self.dependency).set(self._queued)
        return granted

    def _lowest_priority_locked(self, pool) -> Optional[_Waiter]:
        """The shed candidate: lowest priority, newest arrival among it
        (LIFO within a class preserves the oldest waiters' progress)."""
        victim: Optional[_Waiter] = None
        for w in pool:
            if victim is None or (w.cls.priority, -w.seq) < \
                    (victim.cls.priority, -victim.seq):
                victim = w
        return victim

    def _count_shed(self, cls: CostClass) -> None:
        self._shed_total += 1
        metrics.counter("admission_shed_total",
                        **{"class": cls.name}).inc()

    def _retry_after_locked(self) -> float:
        # estimated queue DRAIN TIME: (queued cost / concurrency limit)
        # is how many limit-fulls are ahead, and each turns over in
        # roughly one baseline op latency — a depth alone would be a
        # unitless ratio misread as seconds, telling polite clients to
        # back off ~1000x too long on sub-ms workloads. The running
        # counter keeps the shed path O(1): walking every queued waiter
        # under the global lock would make each rejection pay O(depth)
        # exactly when rejections are the common case
        drain = (self._queued_cost / max(self.limiter.limit, 1.0)) \
            * self.limiter.baseline_latency
        return max(1.0, min(10.0, drain))

    def _submit(self, tenant: str, cls: CostClass, deliver):
        """Admit now (returns None), queue (returns the waiter), or shed
        (raises). May also evict a lower-priority queued waiter — its
        rejection is delivered off-lock before returning."""
        evicted: Optional[_Waiter] = None
        granted: list[_Waiter] = []
        try:
            with self._lock:
                now = self._clock()
                t = self._tenant(tenant, now)
                self._decay(t, now)
                if self._queued == 0 and self._fits(cls):
                    self._admit_locked(t, cls)
                    # zero-wait admits observe too: the queue-wait
                    # distribution must cover EVERY admitted request or
                    # its p50 reads as "everyone queued" the moment one
                    # request does (bench per-stage admission-wait)
                    metrics.histogram(
                        "admission_queue_seconds",
                        dependency=self.dependency).observe(0.0)
                    return None
                if len(t.queue) >= self.tenant_depth \
                        or self._queued >= self.global_depth:
                    # per-tenant overflow sheds within the tenant (the
                    # bound exists to contain exactly that tenant);
                    # global overflow sheds across everyone
                    pool = (t.queue if len(t.queue) >= self.tenant_depth
                            else (w for tt in self._backlogged
                                  for w in tt.queue))
                    victim = self._lowest_priority_locked(pool)
                    if victim is not None \
                            and victim.cls.priority < cls.priority:
                        vt = self._tenants[victim.tenant]
                        vt.queue.remove(victim)
                        if not vt.queue:
                            self._backlogged.discard(vt)
                        victim.state = _SHED
                        self._queued -= 1
                        self._queued_cost = max(
                            0.0, self._queued_cost - victim.cls.weight)
                        self._count_shed(victim.cls)
                        evicted = victim
                    else:
                        self._count_shed(cls)
                        raise AdmissionRejected(
                            cls.name,
                            f"queue full ({self._queued} queued, "
                            f"limit {self.limiter.limit:.0f})",
                            retry_after=self._retry_after_locked(),
                            dependency=self.dependency)
                self._seq += 1
                w = _Waiter(tenant, cls, deliver, now, self._seq)
                t.queue.append(w)
                self._backlogged.add(t)
                self._queued += 1
                self._queued_cost += cls.weight
                metrics.gauge("admission_queue_depth",
                              dependency=self.dependency).set(self._queued)
                if evicted is not None:
                    # the eviction may have replaced a too-heavy queue
                    # head: anything that now fits goes immediately
                    granted = self._drain_locked(now)
                return w
        finally:
            if evicted is not None:
                evicted.deliver(AdmissionRejected(
                    evicted.cls.name,
                    "shed for a higher-priority request",
                    retry_after=1.0, dependency=self.dependency))
            for g in granted:
                g.deliver(None)

    def _cancel(self, w: _Waiter, count_shed: bool = True) -> bool:
        """Timeout/cancellation path: True iff the waiter was still
        queued (and is now removed); False means a grant/shed already
        won the race — its terminal state is visible in ``w.state``.
        ``count_shed=False`` for caller-abandoned waits (a cancelled
        handler is not an overload rejection)."""
        granted: list[_Waiter] = []
        try:
            with self._lock:
                if w.state != _QUEUED:
                    return False
                t = self._tenants[w.tenant]
                t.queue.remove(w)
                if not t.queue:
                    self._backlogged.discard(t)
                w.state = _SHED
                self._queued -= 1
                self._queued_cost = max(
                    0.0, self._queued_cost - w.cls.weight)
                if count_shed:
                    self._count_shed(w.cls)
                metrics.gauge("admission_queue_depth",
                              dependency=self.dependency).set(self._queued)
                # the removed waiter may have been the heavy HEAD that
                # blocked lighter requests behind it: drain NOW — a
                # fitting waiter must not sit until an unrelated release
                # (or shed spuriously at its own timeout meanwhile)
                granted = self._drain_locked(self._clock())
                return True
        finally:
            for g in granted:
                g.deliver(None)

    def _retry_after(self) -> float:
        with self._lock:
            return self._retry_after_locked()

    def _release(self, ticket: Ticket, observe: bool = True) -> None:
        now = self._clock()
        with self._lock:
            if ticket._released:  # definitive idempotence check
                return
            ticket._released = True
            # utilization is sampled BEFORE handing the weight back: the
            # released op was part of the in-flight set whose latency it
            # reports, and a post-decrement sample could never reach the
            # limiter's saturation threshold for heavy-weight classes
            # (releasing a weight-4 lookup always leaves <= limit - 4)
            cost_at_release = self._inflight_cost
            self._inflight -= 1
            self._inflight_cost = max(
                0.0, self._inflight_cost - ticket.cls.weight)
            if observe:
                self.limiter.observe(max(0.0, now - ticket.granted_at),
                                     cost_at_release)
            metrics.gauge("admission_inflight_cost",
                          dependency=self.dependency).set(
                self._inflight_cost)
            granted = self._drain_locked(now)
        for w in granted:
            w.deliver(None)

    # -- public surface ------------------------------------------------------

    def acquire(self, tenant: str, cls: CostClass) -> Ticket:
        """Blocking admission from a worker thread. Returns a
        :class:`Ticket` or raises :class:`AdmissionRejected` — never
        later than ``queue_timeout`` (plus delivery jitter)."""
        ev = threading.Event()
        box: dict = {}

        def deliver(exc):
            box["exc"] = exc
            ev.set()

        w = self._submit(tenant, cls, deliver)
        if w is None:
            return Ticket(self, tenant, cls, self._clock())
        if not ev.wait(self.queue_timeout):
            if self._cancel(w):
                raise AdmissionRejected(
                    cls.name,
                    f"queued longer than {self.queue_timeout:.2f}s",
                    retry_after=self._retry_after(),
                    dependency=self.dependency)
            ev.wait()  # outcome landed concurrently with the timeout
        exc = box.get("exc")
        if exc is not None:
            raise exc
        return Ticket(self, tenant, cls, w.granted_at)

    async def acquire_async(self, tenant: str, cls: CostClass) -> Ticket:
        """Event-loop admission: queued waits park a future, not a
        thread (the engine host may hold hundreds of queued ops)."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()

        def deliver(exc):
            def _set():
                if fut.done():
                    return
                if exc is None:
                    fut.set_result(None)
                else:
                    fut.set_exception(exc)

            loop.call_soon_threadsafe(_set)

        w = self._submit(tenant, cls, deliver)
        if w is None:
            return Ticket(self, tenant, cls, self._clock())

        def on_timeout():
            if self._cancel(w):
                deliver(AdmissionRejected(
                    cls.name,
                    f"queued longer than {self.queue_timeout:.2f}s",
                    retry_after=self._retry_after(),
                    dependency=self.dependency))

        handle = loop.call_later(self.queue_timeout, on_timeout)
        try:
            await fut
        except asyncio.CancelledError:
            # the awaiting handler died (client disconnect, task
            # teardown): hand back the queue slot — or, if a grant
            # already raced in, the admitted CAPACITY — so an abandoned
            # waiter can never leak inflight cost and wedge the
            # controller shut. _cancel's terminal states make this
            # race-free: False + _GRANTED means the cost was charged and
            # nobody will ever release it but us.
            if not self._cancel(w, count_shed=False) \
                    and w.state == _GRANTED:
                # observe=False: the op never dispatched, so the
                # grant-to-cancel span (~0, floor-clamped) is a phantom
                # sample that would pin the limiter baseline at the
                # floor exactly when disconnect churn peaks
                Ticket(self, tenant, cls, w.granted_at).release(
                    observe=False)
            raise
        finally:
            handle.cancel()
        return Ticket(self, tenant, cls, w.granted_at)

    def status(self) -> dict:
        """Shed/queue state for /readyz and tests."""
        with self._lock:
            return {
                "limit": round(self.limiter.limit, 1),
                "inflight": self._inflight,
                "inflight_cost": round(self._inflight_cost, 1),
                "queued": self._queued,
                "tenants": sum(1 for t in self._tenants.values()
                               if t.queue or t.debt > 0),
                "shed_total": self._shed_total,
            }
