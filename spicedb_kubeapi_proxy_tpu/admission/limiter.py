"""Adaptive concurrency limiter: AIMD on the latency gradient.

A static concurrency cap is wrong twice — too low it idles the chip,
too high it lets queueing build inside the dispatch pool where nothing
can shed it. This limiter moves the admitted-cost ceiling against what
the engine's latency actually says (the Netflix/gradient-limiter shape,
TCP-Vegas flavored):

- **baseline** — a decayed minimum of observed latency: new lows adopt
  immediately; otherwise it drifts upward slowly, so a permanent regime
  change (bigger graph after a bulk load) re-anchors instead of pinning
  the limiter shut forever.
- **short** — an EWMA of recent latency.
- when ``short > baseline * tolerance`` the engine is queueing:
  multiplicative decrease. When latency is healthy AND the limit is
  actually saturated: additive increase (probing unused headroom when
  half the limit is idle would just be noise).

Adjustments are cooled down (one per ``cooldown`` samples) so a single
bulk check's worth of observations moves the limit once, not per item.
Thread-safe; the clock is injectable only for symmetry with the rest of
the resilience stack — the limiter itself is sample-driven, so tests
drive it deterministically with plain ``observe`` calls.
"""

from __future__ import annotations

import threading

from ..utils.metrics import metrics


class AdaptiveLimiter:
    def __init__(self, initial: float = 32.0, min_limit: float = 4.0,
                 max_limit: float = 512.0, tolerance: float = 1.5,
                 decrease: float = 0.85, increase: float = 1.0,
                 warmup: int = 10, cooldown: int = 8,
                 floor: float = 0.001,
                 dependency: str = "admission"):
        if not min_limit <= initial <= max_limit:
            raise ValueError(
                f"need min <= initial <= max, got {min_limit}/{initial}/"
                f"{max_limit}")
        self.limit = float(initial)
        self.min_limit = float(min_limit)
        self.max_limit = float(max_limit)
        self.tolerance = tolerance
        self.decrease = decrease
        self.increase = increase
        self.warmup = warmup
        self.cooldown = cooldown
        # observations clamp UP to this (seconds): at micro-op scale the
        # short EWMA's jitter trivially exceeds tolerance times a
        # microsecond baseline, and the limiter would ratchet down on
        # pure scheduling noise — nothing meaningful queues behind
        # sub-floor operations anyway
        self.floor = floor
        self.dependency = dependency
        self._baseline: float | None = None
        self._short: float | None = None
        self._n = 0
        self._since_adjust = 0
        self._lock = threading.Lock()
        self._gauge().set(self.limit)

    def _gauge(self):
        return metrics.gauge("admission_concurrency_limit",
                             dependency=self.dependency)

    def observe(self, latency: float, inflight_cost: float) -> None:
        """One completed operation: ``latency`` seconds from admission
        grant to release, ``inflight_cost`` the weighted in-flight cost
        AT release — including the released op itself, so a saturated
        system reports ~limit and the grow probe can actually fire for
        heavy-weight classes (utilization signal)."""
        latency = max(latency, self.floor)
        with self._lock:
            self._n += 1
            if self._baseline is None or self._short is None:
                self._baseline = self._short = latency
                return
            self._short += (latency - self._short) * 0.3
            if latency < self._baseline:
                self._baseline = latency
            else:
                self._baseline += (latency - self._baseline) * 0.02
            self._since_adjust += 1
            if self._n < self.warmup or self._since_adjust < self.cooldown:
                return
            if self._short > self._baseline * self.tolerance:
                # latency detached from its floor: the engine is
                # queueing behind us — back off multiplicatively
                self.limit = max(self.min_limit,
                                 self.limit * self.decrease)
            elif inflight_cost >= self.limit - 1.0:
                # healthy and saturated: probe one unit of headroom
                self.limit = min(self.max_limit, self.limit + self.increase)
            else:
                return  # healthy but unsaturated: nothing to learn
            self._since_adjust = 0
            self._gauge().set(self.limit)

    @property
    def baseline_latency(self) -> float:
        """The decayed-minimum per-op latency (seconds); the floor until
        a first observation lands. Used to turn queue depth into a
        drain-time estimate for Retry-After hints."""
        with self._lock:
            return self._baseline if self._baseline is not None \
                else self.floor

    def snapshot(self) -> dict:
        with self._lock:
            return {"limit": self.limit, "baseline": self._baseline,
                    "short": self._short, "samples": self._n}
