"""Feature gates: named on/off switches for optional behaviors.

Mirrors the reference's feature-gate plumbing
(/root/reference/pkg/proxy/features.go:10-27, kube component-base style):
gates are registered with a default, overridable from the CLI
(``--feature-gates Name=true,Other=false``). Unlike a bare settings dict,
unknown gate names are rejected at parse time so typos fail boot, not
silently.

Registered gates (all real behavior switches):

- ``IncrementalGraphUpdates`` (default on): O(delta) compiled-graph
  updates on write; off forces a full recompile per revision change.
- ``BitKernel`` (default on): the bit-packed Pallas propagation kernel on
  TPU for small query batches; off keeps every block on the MXU matmul.
- ``SemiringDenseKernel`` (default on): the MXU-tile-shaped Pallas dense
  kernel for the semiring pull path (ops/semiring.py); off keeps the
  dense phase on the plain XLA dot_general.
- ``ProtobufNegotiation`` (default on): forward kube-protobuf Accept
  ranges upstream and wire-filter protobuf responses; off rewrites every
  Accept to JSON.
- ``ProtobufWatch`` (default on): let WATCH requests negotiate protobuf
  too — frames pass through filtered and byte-identical
  (proxy/kubeproto.py WatchEvent surgery); off restores the legacy
  JSON-downgrade rewrite, counted in ``/metrics``
  (``proxy_proto_watch_downgrades_total``) so the re-encoding cost is
  visible to operators.
"""

from __future__ import annotations

import threading


class FeatureGateError(ValueError):
    pass


class FeatureGates:
    def __init__(self):
        self._lock = threading.Lock()
        self._defaults: dict[str, bool] = {}
        self._overrides: dict[str, bool] = {}

    def register(self, name: str, default: bool) -> None:
        with self._lock:
            self._defaults[name] = default

    def enabled(self, name: str) -> bool:
        with self._lock:
            if name not in self._defaults:
                raise FeatureGateError(f"unknown feature gate {name!r}")
            return self._overrides.get(name, self._defaults[name])

    def set(self, name: str, value: bool) -> None:
        with self._lock:
            if name not in self._defaults:
                raise FeatureGateError(
                    f"unknown feature gate {name!r} "
                    f"(known: {', '.join(sorted(self._defaults))})")
            self._overrides[name] = value

    def validate_spec(self, spec: str) -> list[tuple[str, bool]]:
        """Parse ``Name=true,Other=false`` (CLI form) without applying;
        raises FeatureGateError on syntax errors or unknown names."""
        out = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, sep, value = part.partition("=")
            if not sep or value.lower() not in ("true", "false"):
                raise FeatureGateError(
                    f"invalid feature gate setting {part!r} "
                    "(expected Name=true|false)")
            name = name.strip()
            with self._lock:
                if name not in self._defaults:
                    raise FeatureGateError(
                        f"unknown feature gate {name!r} "
                        f"(known: {', '.join(sorted(self._defaults))})")
            out.append((name, value.lower() == "true"))
        return out

    def apply_spec(self, spec: str) -> None:
        for name, value in self.validate_spec(spec):
            self.set(name, value)

    def reset(self) -> None:
        with self._lock:
            self._overrides.clear()

    def known(self) -> dict[str, bool]:
        with self._lock:
            return {n: self._overrides.get(n, d)
                    for n, d in sorted(self._defaults.items())}


features = FeatureGates()
features.register("IncrementalGraphUpdates", True)
features.register("BitKernel", True)
features.register("SemiringDenseKernel", True)
features.register("ProtobufNegotiation", True)
features.register("ProtobufWatch", True)
