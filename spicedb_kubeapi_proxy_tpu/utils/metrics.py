"""Minimal in-process metrics: counters + gauges + histograms, Prometheus
text format.

The reference advertises metrics support but wires no exporter of its own
(SURVEY.md §5 — embedded SpiceDB metrics are explicitly disabled); the TPU
build adds real ones: request counts/latency, engine checks/sec, fixpoint
iterations, compile counts. Rendered at /metrics by the proxy server.
"""

from __future__ import annotations

import threading
from typing import Optional


class Counter:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """A value that can go up and down (breaker state, pool occupancy)."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                       0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

    def __init__(self, buckets=None):
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.n = 0
        self.max = 0.0  # largest observation: bounds the overflow bucket
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self.n += 1
            self.total += v
            if v > self.max:
                self.max = v
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def quantile(self, q: float) -> Optional[float]:
        """Approximate quantile from bucket counts (upper bound) — any
        ``q`` including deep tails (p99.9 = ``quantile(0.999)``). A target
        landing in the overflow bucket clamps to the LARGEST OBSERVED
        value, never infinity — bench p99/p99.9 fields must stay finite
        JSON. An EMPTY histogram returns ``None``: a window that saw no
        observations has no percentile, and 0.0 would read as "infinitely
        fast" in a latency curve."""
        with self._lock:
            if self.n == 0:
                return None
            target = q * self.n
            acc = 0
            for i, b in enumerate(self.buckets):
                acc += self.counts[i]
                if acc >= target:
                    return b
            return self.max

    def snapshot(self) -> dict:
        """A consistent copy of the histogram state, for delta-quantile
        computation across a measurement window (bench.py per-phase stage
        breakdowns)."""
        with self._lock:
            return {"buckets": self.buckets, "counts": list(self.counts),
                    "n": self.n, "total": self.total, "max": self.max}


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple, Counter] = {}
        self._gauges: dict[tuple, Gauge] = {}
        self._hists: dict[tuple, Histogram] = {}

    def counter(self, name: str, **labels) -> Counter:
        key = (name,) + tuple(sorted(labels.items()))
        with self._lock:
            c = self._counters.get(key)
            if c is None:
                c = self._counters[key] = Counter()
            return c

    def gauge(self, name: str, **labels) -> Gauge:
        key = (name,) + tuple(sorted(labels.items()))
        with self._lock:
            g = self._gauges.get(key)
            if g is None:
                g = self._gauges[key] = Gauge()
            return g

    def histogram(self, name: str, buckets=None, **labels) -> Histogram:
        key = (name,) + tuple(sorted(labels.items()))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Histogram(buckets)
            return h

    def hist_snapshot(self, name: str, **labels) -> Optional[dict]:
        """One merged :meth:`Histogram.snapshot` across every label set
        registered under ``name`` (or ``None`` when nothing is). Bench
        stage breakdowns aggregate over labels (e.g. per-dependency
        latency series) — label sets with differing bucket layouts keep
        the first layout and drop the rest, which cannot happen for
        same-name histograms registered through this module's defaults.
        ``labels`` restricts the merge to label sets CONTAINING every
        given pair (how the SLO monitor reads one op class out of a
        shared family, e.g. ``hist_snapshot("loadgen_op_seconds",
        op="check")``)."""
        want = set(labels.items())
        with self._lock:
            hs = [h for key, h in self._hists.items()
                  if key[0] == name and want <= set(key[1:])]
        merged: Optional[dict] = None
        for h in hs:
            s = h.snapshot()
            if merged is None:
                merged = s
            elif s["buckets"] == merged["buckets"]:
                merged["counts"] = [a + b for a, b in
                                    zip(merged["counts"], s["counts"])]
                merged["n"] += s["n"]
                merged["total"] += s["total"]
                merged["max"] = max(merged["max"], s["max"])
        return merged

    def render(self) -> str:
        """Prometheus text exposition. Histograms render the full
        contract — ``# TYPE`` metadata plus cumulative
        ``_bucket{le="..."}`` series ending at ``+Inf`` — so a real
        scraper can compute quantiles; the historical ``_count``/``_sum``
        lines are unchanged."""
        out = []
        typed: set = set()

        def type_line(name: str, kind: str) -> None:
            if name not in typed:
                typed.add(name)
                out.append(f"# TYPE {name} {kind}")

        with self._lock:
            for key, c in sorted(self._counters.items()):
                type_line(key[0], "counter")
                out.append(f"{_fmt(key)} {c.value}")
            for key, g in sorted(self._gauges.items()):
                type_line(key[0], "gauge")
                out.append(f"{_fmt(key)} {g.value}")
            for key, h in sorted(self._hists.items()):
                name = key[0]
                labels = key[1:]
                type_line(name, "histogram")
                s = h.snapshot()
                acc = 0
                for b, c in zip(s["buckets"], s["counts"]):
                    acc += c
                    out.append(_fmt((name + "_bucket",) + labels
                                    + (("le", _fmt_le(b)),)) + f" {acc}")
                out.append(_fmt((name + "_bucket",) + labels
                                + (("le", "+Inf"),)) + f" {s['n']}")
                out.append(f"{_fmt((name + '_count',) + labels)} {s['n']}")
                out.append(
                    f"{_fmt((name + '_sum',) + labels)} {s['total']}")
        return "\n".join(out) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


def _fmt(key: tuple) -> str:
    name = key[0]
    labels = key[1:]
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


def _fmt_le(bound) -> str:
    # repr keeps the bound EXACT (shortest round-trip float repr): %g's
    # 6 significant digits would misstate large bounds (2**21 renders as
    # 2.09715e+06 = 2097150) and could collapse nearby bounds into
    # duplicate le labels — an invalid exposition
    return repr(bound)


def snapshot_delta_quantile(before: Optional[dict], after: Optional[dict],
                            q: float) -> Optional[float]:
    """Approximate quantile (upper bucket bound) of the observations that
    landed BETWEEN two :meth:`Histogram.snapshot`/:meth:`Registry.
    hist_snapshot` calls — how bench.py attributes a phase's stage
    latency without resetting shared histograms. ``None`` when the window
    saw no observations; the overflow bucket clamps to the window's
    largest observed value (``after``'s max — an upper bound when earlier
    phases observed larger, never infinity)."""
    if after is None:
        return None
    if before is None:
        before = {"buckets": after["buckets"],
                  "counts": [0] * len(after["counts"]), "n": 0}
    if before["buckets"] != after["buckets"]:
        return None
    d = [a - b for a, b in zip(after["counts"], before["counts"])]
    n = after["n"] - before["n"]
    if n <= 0:
        return None
    target = q * n
    acc = 0
    for i, b in enumerate(after["buckets"]):
        acc += d[i]
        if acc >= target:
            return b
    return after["max"]


metrics = Registry()
