"""Minimal in-process metrics: counters + gauges + histograms, Prometheus
text format.

The reference advertises metrics support but wires no exporter of its own
(SURVEY.md §5 — embedded SpiceDB metrics are explicitly disabled); the TPU
build adds real ones: request counts/latency, engine checks/sec, fixpoint
iterations, compile counts. Rendered at /metrics by the proxy server.
"""

from __future__ import annotations

import threading
from typing import Optional


class Counter:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """A value that can go up and down (breaker state, pool occupancy)."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                       0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

    def __init__(self, buckets=None):
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.n = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self.n += 1
            self.total += v
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket counts (upper bound)."""
        with self._lock:
            if self.n == 0:
                return 0.0
            target = q * self.n
            acc = 0
            for i, b in enumerate(self.buckets):
                acc += self.counts[i]
                if acc >= target:
                    return b
            return float("inf")


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple, Counter] = {}
        self._gauges: dict[tuple, Gauge] = {}
        self._hists: dict[tuple, Histogram] = {}

    def counter(self, name: str, **labels) -> Counter:
        key = (name,) + tuple(sorted(labels.items()))
        with self._lock:
            c = self._counters.get(key)
            if c is None:
                c = self._counters[key] = Counter()
            return c

    def gauge(self, name: str, **labels) -> Gauge:
        key = (name,) + tuple(sorted(labels.items()))
        with self._lock:
            g = self._gauges.get(key)
            if g is None:
                g = self._gauges[key] = Gauge()
            return g

    def histogram(self, name: str, buckets=None, **labels) -> Histogram:
        key = (name,) + tuple(sorted(labels.items()))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Histogram(buckets)
            return h

    def render(self) -> str:
        out = []
        with self._lock:
            for key, c in sorted(self._counters.items()):
                out.append(f"{_fmt(key)} {c.value}")
            for key, g in sorted(self._gauges.items()):
                out.append(f"{_fmt(key)} {g.value}")
            for key, h in sorted(self._hists.items()):
                name = key[0]
                labels = key[1:]
                out.append(f"{_fmt((name + '_count',) + labels)} {h.n}")
                out.append(f"{_fmt((name + '_sum',) + labels)} {h.total}")
        return "\n".join(out) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


def _fmt(key: tuple) -> str:
    name = key[0]
    labels = key[1:]
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return f"{name}{{{inner}}}"


metrics = Registry()
