"""Opt-in runtime concurrency sanitizer (``PROXY_SANITIZE=1``).

The static passes in ``tools/analysis/`` see one function at a time;
this module watches the *composition* at runtime, lockdep-style, so the
whole test suite and the chaos campaign double as race detectors:

- **lock-order graph**: every ``threading.Lock``/``RLock`` created from
  package code is keyed by its creation site (its "lock class"). Each
  blocking acquire while other classes are held adds held→acquiring
  edges; an edge that closes a cycle is a deadlock-in-waiting
  (``lock-order-cycle``) even if this run never interleaved badly.
- **hold-time ceiling**: a release (or Condition wait) after holding a
  lock longer than ``PROXY_SANITIZE_HOLD_MS`` (default 2000) records
  ``hold-time`` — the static lock-discipline pass's runtime twin.
- **loop-thread blocking**: ``time.sleep`` called from *package code*
  on a thread with a running asyncio event loop records
  ``loop-blocking-call`` (the PR 12 class: a loop-side sleep stalls
  every in-flight request and heartbeat). A blocking lock acquire that
  actually contends on a loop thread records ``loop-lock-contention``
  (informational — brief on-loop probes are a design choice, e.g. the
  middleware's decision-cache probe).

Installed by ``tests/conftest.py`` before package modules import (so
every package lock is wrapped); ``report()``/``reset()`` read and clear
the global violation list. Instrumentation is scoped at creation time:
locks created from stdlib/third-party frames get the raw primitive.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_PKG_MARKER = os.sep + "spicedb_kubeapi_proxy_tpu" + os.sep

# raw primitives captured at import, BEFORE install() swaps the
# factories — the sanitizer's own state must never instrument itself
_real_lock = threading.Lock
_real_rlock = threading.RLock
_real_sleep = time.sleep

HOLD_MS_ENV = "PROXY_SANITIZE_HOLD_MS"
ENABLE_ENV = "PROXY_SANITIZE"


@dataclass(frozen=True)
class Violation:
    kind: str       # lock-order-cycle | hold-time | loop-blocking-call
    #                 | loop-lock-contention
    detail: str
    site: str       # creation/call site "file:line"

    def render(self) -> str:
        return f"{self.kind}: {self.site} {self.detail}"


class _State:
    def __init__(self):
        self.mu = _real_lock()
        self.violations: List[Violation] = []
        self.edges: Dict[str, Set[str]] = {}       # class -> classes
        self.edge_seen: Set[Tuple[str, str]] = set()
        self.cycle_seen: Set[Tuple[str, str]] = set()
        self.hold_ms = float(os.environ.get(HOLD_MS_ENV, "2000"))
        self.tls = threading.local()
        self.record_all = False  # tests: attribute non-package frames too

    def held(self) -> list:
        st = getattr(self.tls, "stack", None)
        if st is None:
            st = self.tls.stack = []
        return st

    def record(self, kind: str, detail: str, site: str) -> None:
        with self.mu:
            self.violations.append(Violation(kind, detail, site))


_state = _State()
_installed = False


def _caller_site(depth: int = 2) -> Optional[str]:
    try:
        f = sys._getframe(depth)
    except ValueError:
        return None
    fn = f.f_code.co_filename
    if _PKG_MARKER not in fn and not _state.record_all:
        return None
    short = fn.split(_PKG_MARKER)[-1] if _PKG_MARKER in fn else fn
    return f"{short}:{f.f_lineno}"


def _on_loop_thread() -> bool:
    try:
        import asyncio
        return asyncio._get_running_loop() is not None
    except Exception:  # noqa: BLE001 - detection is best-effort
        return False


def _path_exists(graph: Dict[str, Set[str]], src: str, dst: str) -> bool:
    seen = set()
    stack = [src]
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(graph.get(n, ()))
    return False


class SanitizedLock:
    """Wrapper around a real Lock/RLock carrying a creation-site lock
    class. Exposes the full lock protocol; Condition integration
    (``_release_save``/``_acquire_restore``/``_is_owned``) is forwarded
    only when the inner primitive has it (RLock), with held-stack
    bookkeeping so a waiting Condition doesn't read as a held lock."""

    __slots__ = ("_inner", "_site", "_reentrant")

    def __init__(self, inner, site: str, reentrant: bool):
        self._inner = inner
        self._site = site
        self._reentrant = reentrant

    # -- core protocol ---------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        st = _state
        stack = st.held()
        me = id(self)
        already_held = any(e[0] == me for e in stack)
        ok = None
        if blocking and not already_held:
            self._note_edges(stack)
            if _on_loop_thread():
                if self._inner.acquire(False):
                    ok = True  # uncontended fast path: done
                else:
                    st.record(
                        "loop-lock-contention",
                        "blocking acquire contended on an event-loop "
                        "thread", self._site)
        if ok is None:
            ok = self._inner.acquire(blocking, timeout)
        if ok:
            stack.append((me, self._site, time.monotonic()))
        return ok

    def release(self):
        st = _state
        stack = st.held()
        me = id(self)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == me:
                _, site, t0 = stack.pop(i)
                if not any(e[0] == me for e in stack):
                    held_ms = (time.monotonic() - t0) * 1000.0
                    if held_ms > st.hold_ms:
                        st.record(
                            "hold-time",
                            f"held {held_ms:.0f}ms "
                            f"(ceiling {st.hold_ms:.0f}ms)", site)
                break
        return self._inner.release()

    def _note_edges(self, stack) -> None:
        st = _state
        mine = self._site
        for _, held_site, _t in stack:
            if held_site == mine:
                continue
            key = (held_site, mine)
            cycle = False
            with st.mu:
                if key in st.edge_seen:
                    continue
                st.edge_seen.add(key)
                # closing edge held->mine: a path mine->...->held means
                # somewhere else the opposite order exists
                if _path_exists(st.edges, mine, held_site) \
                        and (mine, held_site) not in st.cycle_seen:
                    st.cycle_seen.add((mine, held_site))
                    cycle = True
                st.edges.setdefault(held_site, set()).add(mine)
            if cycle:  # record() retakes st.mu — must be outside it
                st.record(
                    "lock-order-cycle",
                    f"acquiring while holding {held_site} closes an "
                    f"order cycle (reverse path exists)", mine)

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<SanitizedLock {self._site} of {self._inner!r}>"

    # -- Condition (RLock) protocol — present only when inner has it ----

    def _pop_all(self):
        stack = _state.held()
        me = id(self)
        t0 = None
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == me:
                t0 = stack.pop(i)[2]
        return t0

    def __getattr__(self, name):
        # Condition probes _release_save/_acquire_restore/_is_owned via
        # getattr at __init__; forward them (with bookkeeping) only when
        # the inner lock really has them, so a plain Lock keeps raising
        # AttributeError and Condition uses its portable fallback
        if name == "_release_save":
            inner_rs = self._inner._release_save  # may raise

            def _release_save():
                t0 = self._pop_all()
                if t0 is not None:
                    held_ms = (time.monotonic() - t0) * 1000.0
                    if held_ms > _state.hold_ms:
                        _state.record(
                            "hold-time",
                            f"held {held_ms:.0f}ms at Condition.wait "
                            f"(ceiling {_state.hold_ms:.0f}ms)",
                            self._site)
                return inner_rs()
            return _release_save
        if name == "_acquire_restore":
            inner_ar = self._inner._acquire_restore  # may raise

            def _acquire_restore(state):
                out = inner_ar(state)
                _state.held().append(
                    (id(self), self._site, time.monotonic()))
                return out
            return _acquire_restore
        if name == "_is_owned":
            return self._inner._is_owned  # may raise
        return getattr(self._inner, name)


def _make_factory(real, reentrant: bool):
    def factory():
        site = _caller_site(2)
        inner = real()
        if site is None:
            return inner
        return SanitizedLock(inner, site, reentrant)
    return factory


def _sanitized_sleep(seconds):
    if seconds and seconds > 0.001 and _on_loop_thread():
        site = _caller_site(2)
        if site is not None:
            _state.record(
                "loop-blocking-call",
                f"time.sleep({seconds!r}) on an event-loop thread",
                site)
    return _real_sleep(seconds)


def install() -> None:
    """Swap the lock factories and time.sleep. Idempotent."""
    global _installed
    if _installed:
        return
    threading.Lock = _make_factory(_real_lock, False)
    threading.RLock = _make_factory(_real_rlock, True)
    time.sleep = _sanitized_sleep
    _installed = True


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    time.sleep = _real_sleep
    _installed = False


def installed() -> bool:
    return _installed


def enabled_by_env() -> bool:
    return os.environ.get(ENABLE_ENV, "") == "1"


def report() -> List[Violation]:
    with _state.mu:
        return list(_state.violations)


def reset() -> None:
    """Clear violations AND the order graph (test isolation)."""
    with _state.mu:
        _state.violations.clear()
        _state.edges.clear()
        _state.edge_seen.clear()
        _state.cycle_seen.clear()


ENFORCED_KINDS = ("lock-order-cycle", "loop-blocking-call")


def enforced_violations() -> List[Violation]:
    """The kinds a CI run fails on; hold-time and loop contention are
    reported but advisory (CPU CI machines make wall-clock ceilings
    flaky, and brief on-loop probes are a documented design choice)."""
    return [v for v in report() if v.kind in ENFORCED_KINDS]
