"""TLS context construction for the ``tcp://`` engine protocol.

The reference's remote backend endpoint defaults to TLS (system or custom
CA, CA verification skippable, plaintext only behind an explicit
``--spicedb-insecure``; /root/reference/pkg/proxy/options.go:325-369).
The engine wire mirrors that flag shape: an engine host serves TLS from a
cert/key pair (optionally demanding client certificates), and clients
verify against the system store or a custom CA bundle unless explicitly
told to skip verification or go plaintext. The shared bearer token rides
INSIDE the channel either way — TLS protects the token and every
relationship in transit; the token authenticates the peer.
"""

from __future__ import annotations

import ssl
from typing import Optional


class TLSConfigError(ValueError):
    pass


def server_ssl_context(cert_file: str, key_file: str,
                       client_ca_file: Optional[str] = None
                       ) -> ssl.SSLContext:
    """Serving context for an engine host. A ``client_ca_file``
    additionally REQUIRES client certificates signed by that CA (mutual
    TLS), on top of the bearer token."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    try:
        ctx.load_cert_chain(cert_file, key_file)
    except (OSError, ssl.SSLError) as e:
        raise TLSConfigError(
            f"cannot load serving cert/key ({cert_file}, {key_file}): {e}"
        ) from None
    if client_ca_file:
        try:
            ctx.load_verify_locations(cafile=client_ca_file)
        except (OSError, ssl.SSLError) as e:
            raise TLSConfigError(
                f"cannot load client CA {client_ca_file}: {e}") from None
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_ssl_context(ca_file: Optional[str] = None,
                       skip_verify: bool = False,
                       client_cert_file: Optional[str] = None,
                       client_key_file: Optional[str] = None
                       ) -> ssl.SSLContext:
    """Connecting context for proxies / followers. Default: full
    verification against the system trust store; ``ca_file`` REPLACES the
    trust store with that bundle (pinning — a publicly-trusted MITM cert
    must not pass when the operator named a private CA, matching the
    reference's CAPath mode); ``skip_verify`` keeps TLS (confidentiality)
    but trusts any presented certificate (SkipVerifyCA)."""
    if ca_file:
        try:
            # cafile= at construction loads ONLY this bundle: the system
            # store is never consulted (create_default_context skips
            # load_default_certs when an explicit CA source is given)
            ctx = ssl.create_default_context(cafile=ca_file)
        except (OSError, ssl.SSLError) as e:
            raise TLSConfigError(
                f"cannot load CA bundle {ca_file}: {e}") from None
    else:
        ctx = ssl.create_default_context()
    if skip_verify:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    if client_cert_file:
        try:
            ctx.load_cert_chain(client_cert_file, client_key_file)
        except (OSError, ssl.SSLError) as e:
            raise TLSConfigError(
                f"cannot load client cert/key ({client_cert_file}, "
                f"{client_key_file}): {e}") from None
    return ctx
