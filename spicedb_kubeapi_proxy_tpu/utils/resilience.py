"""Dependency resilience: deadlines, retries, circuit breakers.

The proxy fronts two remote dependencies — the upstream kube-apiserver
(proxy/upstream.py) and, in the engine-host deployment shape, a remote
TPU engine (engine/remote.py tcp://). Either one wedging must degrade
into a bounded, fail-closed error, never an unbounded hang and never a
fail-open authorization. Three cooperating pieces:

- :class:`Deadline` — a per-request wall-clock budget from which
  per-attempt connect/read budgets are derived (``budget(cap)``), so
  retries never extend the caller's total wait.
- :class:`RetryPolicy` — exponential backoff with DECORRELATED jitter
  (each delay drawn from [base, 3*previous], capped), applied by the
  transports ONLY to idempotent operations: upstream GET/watch
  establishment and engine reads. Writes are never retried — once bytes
  are on the wire the server may have applied them (engine/remote.py's
  no-retry-after-send invariant).
- :class:`CircuitBreaker` — per-dependency closed → open → half-open
  state machine. Open fails fast with :class:`BreakerOpen` (carrying a
  Retry-After hint); after ``reset_timeout`` one probe is admitted at a
  time. State is exported as the ``proxy_dependency_breaker_state``
  gauge and surfaced on ``/readyz`` with a per-dependency reason.
- :class:`RetryBudget` — a token-bucket retry allowance SHARED across
  every retrying layer of one dependency stack (transport retries in
  RemoteEngine/HttpUpstream, FailoverEngine re-aim re-issues, planner
  scatter-leg re-issues). Each first attempt deposits ``ratio`` tokens
  (capped at ``burst``); each retry, anywhere in the stack, withdraws
  one — so a browned-out shard sees at most ``burst + ratio × attempts``
  retries TOTAL instead of N_layers × N_retries × attempts (the
  metastable-failure guard: retry amplification is what turns a brief
  brownout into a self-sustaining overload).

Failures that feed the breaker are TRANSPORT failures (connect refused,
reset, timeout, armed failpoint) — an upstream 500 or an engine
precondition error is a healthy dependency saying no.

Everything takes an injectable clock/rng so chaos tests drive the whole
state machine deterministically, without sleeps.
"""

from __future__ import annotations

import math
import random
import threading
import time
from typing import Iterator, Optional

from .metrics import metrics

# breaker states; also the value of the breaker-state gauge
STATE_CLOSED = 0
STATE_HALF_OPEN = 1
STATE_OPEN = 2

_STATE_NAMES = {STATE_CLOSED: "closed", STATE_HALF_OPEN: "half-open",
                STATE_OPEN: "open"}


class DependencyUnavailable(RuntimeError):
    """A dependency is unreachable within policy: the authz middleware
    maps this (and only this) family to a fail-closed kube 503 with a
    ``Retry-After`` header (authz/middleware.py)."""

    def __init__(self, dependency: str, message: str,
                 retry_after: float = 1.0):
        super().__init__(message)
        self.dependency = dependency
        # seconds the caller should wait before trying again (>= 0)
        self.retry_after = retry_after


class BreakerOpen(DependencyUnavailable):
    """Fast failure: the dependency's circuit breaker is open."""


class DeadlineExceeded(DependencyUnavailable):
    """The per-request deadline ran out before the dependency answered."""


class Deadline:
    """A wall-clock budget for ONE request, shared across its attempts.

    ``budget(cap)`` derives a per-attempt timeout: the smaller of the
    attempt cap (e.g. a connect timeout) and the time left, so a retry
    can never push the caller past its total deadline. A ``None`` total
    means unlimited (``budget`` then just returns the cap)."""

    __slots__ = ("_at", "_clock", "total")

    def __init__(self, total: Optional[float], clock=time.monotonic):
        self.total = total
        self._clock = clock
        self._at = None if not total or total <= 0 else clock() + total

    @classmethod
    def after(cls, total: Optional[float],
              clock=time.monotonic) -> "Deadline":
        return cls(total, clock=clock)

    def remaining(self) -> float:
        if self._at is None:
            return math.inf
        return max(0.0, self._at - self._clock())

    @property
    def expired(self) -> bool:
        return self._at is not None and self._clock() >= self._at

    def budget(self, cap: Optional[float] = None) -> Optional[float]:
        """Per-attempt timeout: min(cap, remaining); None = unlimited
        (suitable for ``asyncio.wait_for``/``socket.settimeout``)."""
        rem = self.remaining()
        if rem is math.inf:
            return cap
        return rem if cap is None else min(cap, rem)

    def check(self, dependency: str) -> None:
        if self.expired:
            raise DeadlineExceeded(
                dependency,
                f"deadline of {self.total:.1f}s exhausted waiting for "
                f"{dependency}")


class RetryPolicy:
    """A backoff SCHEDULE (how many attempts a caller makes is the
    caller's ``retries`` knob): exponential with decorrelated jitter,
    each delay drawn uniformly from [base, 3 * previous], capped. A zero
    ``base``/``cap`` gives an all-zero schedule — how chaos tests inject
    a sleepless policy."""

    __slots__ = ("base", "cap", "_rng")

    def __init__(self, base: float = 0.05, cap: float = 2.0,
                 rng: Optional[random.Random] = None):
        self.base = base
        self.cap = cap
        self._rng = rng or random.Random()

    def delays(self) -> Iterator[float]:
        prev = self.base
        while True:
            delay = min(self.cap, self._rng.uniform(self.base,
                                                    max(self.base, prev * 3)))
            prev = max(delay, self.base)
            yield delay


class RetryBudget:
    """Layered-retry amplification guard (see module docstring).

    The bucket starts FULL (``burst`` tokens): a cold stack can absorb a
    transient blip at full retry aggressiveness; only sustained failure
    drains it, after which retries are rationed to ``ratio`` per fresh
    attempt — the steady-state amplification bound. ``allow()`` answers
    whether ONE retry may proceed and counts every refusal in
    ``resilience_retry_budget_exhausted_total{dependency}``; callers that
    get False surface the underlying failure immediately instead of
    retrying. Thread-safe; a ``ratio`` of 0 with a huge ``burst``
    degenerates to the unbudgeted behavior."""

    __slots__ = ("dependency", "ratio", "burst", "_tokens", "_attempts",
                 "_lock")

    def __init__(self, dependency: str = "engine", ratio: float = 0.1,
                 burst: float = 10.0):
        if ratio < 0:
            raise ValueError("retry-budget ratio must be >= 0")
        if burst < 1:
            raise ValueError("retry-budget burst must be >= 1")
        self.dependency = dependency
        self.ratio = ratio
        self.burst = float(burst)
        self._tokens = float(burst)
        # lifetime deposit count: the EXACT denominator of the
        # amplification bound (burst + ratio × attempts) — verifiers
        # snapshot it instead of guessing deposits from logical-op
        # counts (one scatter op deposits once per leg)
        self._attempts = 0
        self._lock = threading.Lock()
        self._gauge().set(self._tokens)

    def _gauge(self):
        return metrics.gauge("resilience_retry_budget_tokens",
                             dependency=self.dependency)

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    @property
    def attempts(self) -> int:
        with self._lock:
            return self._attempts

    def on_attempt(self) -> None:
        """Credit one FIRST attempt (not a retry): deposits ``ratio``
        tokens, capped at ``burst``. Every logical call through a
        budgeted client calls this exactly once."""
        with self._lock:
            self._attempts += 1
            self._tokens = min(self.burst, self._tokens + self.ratio)
            t = self._tokens
        self._gauge().set(t)

    def allow(self) -> bool:
        """Withdraw one retry's token; False (counted) when the budget
        is dry — the caller must surface its failure, not retry."""
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                t = self._tokens
                ok = True
            else:
                t = self._tokens
                ok = False
        self._gauge().set(t)
        if not ok:
            metrics.counter("resilience_retry_budget_exhausted_total",
                            dependency=self.dependency).inc()
        return ok


class CircuitBreaker:
    """Per-dependency closed → open → half-open breaker.

    ``failure_threshold`` CONSECUTIVE transport failures open the
    circuit; while open, ``allow()`` raises :class:`BreakerOpen`
    immediately (fail fast, never hang). After ``reset_timeout`` the
    next ``allow()`` admits ONE probe (half-open); its success closes
    the circuit, its failure re-opens with a fresh window. Thread-safe —
    the remote-engine client calls it from request-handler worker
    threads, the upstream from the event loop."""

    def __init__(self, dependency: str, failure_threshold: int = 5,
                 reset_timeout: float = 10.0, clock=time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.dependency = dependency
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self._clock = clock
        self._lock = threading.Lock()
        self._state = STATE_CLOSED
        self._failures = 0  # consecutive
        self._opened_at = 0.0
        self._probe_inflight = False
        self._gauge().set(STATE_CLOSED)

    def _gauge(self):
        return metrics.gauge("proxy_dependency_breaker_state",
                             dependency=self.dependency)

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def _set_state(self, state: int) -> None:
        # lock held by caller
        self._state = state
        self._gauge().set(state)

    def allow(self) -> None:
        """Admission check before an attempt; raises BreakerOpen when the
        circuit rejects it. Every admitted attempt MUST be answered with
        record_success() or record_failure()."""
        with self._lock:
            if self._state == STATE_CLOSED:
                return
            elapsed = self._clock() - self._opened_at
            if self._state == STATE_OPEN and elapsed >= self.reset_timeout:
                self._set_state(STATE_HALF_OPEN)
                self._probe_inflight = False
            if self._state == STATE_HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return
            retry_after = max(0.0, self.reset_timeout - elapsed)
            state = _STATE_NAMES[self._state]
            failures = self._failures
        metrics.counter("proxy_dependency_breaker_rejections_total",
                        dependency=self.dependency).inc()
        raise BreakerOpen(
            self.dependency,
            f"circuit breaker for {self.dependency} is {state} "
            f"({failures} consecutive failures)",
            retry_after=retry_after)

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probe_inflight = False
            if self._state != STATE_CLOSED:
                self._set_state(STATE_CLOSED)

    def release(self) -> None:
        """Release an admitted attempt WITHOUT a verdict: the attempt
        ended in a non-transport outcome (handler cancelled, protocol
        error, server-side rejection surfaced as an exception before the
        success path ran). Neither state nor the failure streak moves,
        but a half-open probe slot must not leak — otherwise one such
        exception during the probe would wedge the breaker open
        forever."""
        with self._lock:
            self._probe_inflight = False

    def check_open(self) -> None:
        """Raise BreakerOpen iff the circuit is not passing traffic —
        hard-open inside the reset window, or half-open with the probe
        slot taken — WITHOUT admitting an attempt or consuming the probe
        slot. For callers that want to fail fast before committing side
        effects (e.g. durably enqueueing a dual-write) but must not
        interfere with probe accounting. A probe-eligible circuit passes:
        let a real attempt decide."""
        with self._lock:
            if self._state == STATE_CLOSED:
                return
            if self._state == STATE_HALF_OPEN:
                if not self._probe_inflight:
                    return
                # a probe is in flight and may hang up to a full read
                # timeout against a stalled host; everything else fails
                # fast meanwhile rather than queueing behind it
                retry_after = 1.0
                state = "half-open (probe in flight)"
            else:
                elapsed = self._clock() - self._opened_at
                if elapsed >= self.reset_timeout:
                    return
                retry_after = self.reset_timeout - elapsed
                state = "open"
            failures = self._failures
        raise BreakerOpen(
            self.dependency,
            f"circuit breaker for {self.dependency} is {state} "
            f"({failures} consecutive failures)",
            retry_after=retry_after)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == STATE_HALF_OPEN:
                # the probe failed: re-open with a fresh reset window
                self._probe_inflight = False
                self._opened_at = self._clock()
                self._set_state(STATE_OPEN)
            elif (self._state == STATE_CLOSED
                    and self._failures >= self.failure_threshold):
                self._opened_at = self._clock()
                self._set_state(STATE_OPEN)

    def force_open(self) -> None:
        """Trip the breaker as if the threshold had been crossed (ops/
        test hook; also what a chaos failpoint storm converges to)."""
        with self._lock:
            self._failures = max(self._failures, self.failure_threshold)
            self._opened_at = self._clock()
            self._set_state(STATE_OPEN)

    def open_reason(self) -> Optional[str]:
        """Human-readable unreadiness reason, or None when ready.
        Surfaced per-dependency by /readyz (proxy/server.py).

        A PROBE-ELIGIBLE circuit (open with the reset window elapsed, or
        half-open with no probe in flight) reports READY: unreadiness
        pulls the replica out of rotation, and a replica starved of
        traffic would otherwise never reach allow() — the only place the
        open -> half-open probe happens — leaving it unready forever
        after the dependency recovers."""
        with self._lock:
            if self._state == STATE_CLOSED:
                return None
            if self._state == STATE_HALF_OPEN:
                return ("circuit half-open (probing)"
                        if self._probe_inflight else None)
            left = self.reset_timeout - (self._clock() - self._opened_at)
            if left <= 0:
                return None  # probe-eligible: let traffic return
            return (f"circuit open after {self._failures} consecutive "
                    f"failures; next probe in {left:.1f}s")
