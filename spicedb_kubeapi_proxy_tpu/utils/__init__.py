"""Utilities: failpoints, metrics, resilience (deadlines/retries/breakers)."""

from .failpoints import FailPointError, failpoints  # noqa: F401
from .resilience import (  # noqa: F401
    BreakerOpen,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    DependencyUnavailable,
    RetryPolicy,
)
