"""Utilities: failpoints, metrics, logging."""

from .failpoints import FailPointError, failpoints  # noqa: F401
