"""Discovery response cache: TTL + disk-backed, stale-on-error.

Mirrors the reference's disk-cached discovery RESTMapper
(/root/reference/pkg/proxy/server.go:228-243, client-go's cached
discovery with its 10-minute default TTL): API discovery documents
(/api, /apis, /openapi, /version — the always-allowed metadata paths,
authz.go:205-207) change rarely, are requested constantly by clients, and
must not each cost an upstream round trip. Entries persist to an optional
cache directory so a restarted proxy serves discovery before its first
upstream contact; on upstream failure a stale entry is served rather than
an error (discovery staleness is benign, matching client-go semantics).
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import threading
import time
from typing import Optional

from ..proxy.types import ProxyRequest, ProxyResponse

DEFAULT_TTL = 600.0  # client-go cached discovery default (10 minutes)

# key cardinality is client-controlled (Accept values, query strings), so
# the cache is bounded: beyond this many entries the nearest-to-expiry is
# evicted (memory and disk)
MAX_ENTRIES = 128


class DiscoveryCache:
    def __init__(self, ttl: float = DEFAULT_TTL,
                 cache_dir: Optional[str] = None,
                 max_entries: int = MAX_ENTRIES):
        self.ttl = ttl
        self.cache_dir = cache_dir
        self.max_entries = max_entries
        self._lock = threading.Lock()
        # key -> (expiry unix seconds, status, headers, body)
        self._mem: dict[str, tuple[float, int, dict, bytes]] = {}
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)

    @staticmethod
    def _key(req: ProxyRequest) -> str:
        accept = next((v for k, v in req.headers.items()
                       if k.lower() == "accept"), "")
        return hashlib.blake2s(
            f"{req.path}?{sorted(req.query.items())}|{accept}".encode()
        ).hexdigest()[:32]

    def _disk_path(self, key: str) -> Optional[str]:
        if not self.cache_dir:
            return None
        return os.path.join(self.cache_dir, f"{key}.json")

    def _load(self, key: str):
        with self._lock:
            ent = self._mem.get(key)
        if ent is not None:
            return ent
        path = self._disk_path(key)
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    d = json.load(f)
                ent = (d["expiry"], d["status"], d["headers"],
                       base64.b64decode(d["body_b64"]))
                with self._lock:
                    self._mem[key] = ent
                return ent
            except (OSError, ValueError, KeyError):
                return None
        return None

    def _store(self, key: str, status: int, headers: dict,
               body: bytes) -> None:
        ent = (time.time() + self.ttl, status, headers, body)
        evicted: list[str] = []
        with self._lock:
            self._mem[key] = ent
            while len(self._mem) > self.max_entries:
                victim = min(self._mem, key=lambda k: self._mem[k][0])
                del self._mem[victim]
                evicted.append(victim)
        for v in evicted:
            p = self._disk_path(v)
            if p:
                try:
                    os.unlink(p)
                except OSError:
                    pass
        path = self._disk_path(key)
        if path:
            tmp = f"{path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    json.dump({
                        "expiry": ent[0], "status": status,
                        "headers": headers,
                        "body_b64": base64.b64encode(body).decode(),
                    }, f)
                os.replace(tmp, path)
            except OSError:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    @staticmethod
    def _replay(ent) -> ProxyResponse:
        _, status, headers, body = ent
        return ProxyResponse(status=status, headers=dict(headers), body=body)

    async def serve(self, req: ProxyRequest, upstream) -> ProxyResponse:
        """Fresh cache hit -> cached response; miss/expired -> upstream
        (2xx responses cached); upstream failure -> stale entry if any."""
        key = self._key(req)
        ent = self._load(key)
        now = time.time()
        if ent is not None and ent[0] > now:
            return self._replay(ent)
        # request identity encoding: a cached body is replayed to clients
        # with arbitrary Accept-Encoding, so it must never be compressed
        req.headers = {k: v for k, v in req.headers.items()
                       if k.lower() != "accept-encoding"}
        try:
            resp = await upstream(req)
        except Exception:
            if ent is not None:  # serve stale over failing hard
                return self._replay(ent)
            raise
        if 200 <= resp.status < 300 and resp.stream is None:
            self._store(key, resp.status, dict(resp.headers), resp.body)
        elif ent is not None and resp.status >= 500:
            return self._replay(ent)
        return resp
