"""Fault-injection failpoints.

Mirrors /root/reference/pkg/failpoints/failpoints_on.go:19-48: named panic
sites armed with per-name call budgets. The reference compiles them in via a
build tag; here they are armed at runtime (API or
``FAILPOINTS=name:count,name2`` env) and are a no-op when not armed, so they
stay in production code paths like the reference's activity hooks
(activity.go:48,61,153,155,176,213).

The chaos campaign (chaos/) extends the raise-N-times model to seeded,
deterministic FAULT SCHEDULES over the same named sites: each armed rule
carries an ACTION (``error`` raise, ``drop`` a frame, ``delay`` the op,
``crash`` the process), a trigger budget, and — for probabilistic rules —
a decision sequence PRE-DRAWN from a seeded RNG keyed by ``(seed, site,
p)``, so the k-th hit of a site decides identically in every process and
every re-run of the same seed. Env arming accepts ``name:p=0.25`` backed
by the same derivation (seed from ``CHAOS_SEED``, default 0), so even
env-armed probabilistic sites stay byte-for-byte reproducible.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import random
import signal
import threading
import time
from typing import Optional

log = logging.getLogger("sdbkp.failpoints")

# fault actions a rule can carry; ``hit`` sites surface error/drop as a
# raised FailPointError (the transport-failure classification chaos tests
# drive), ``branch`` sites surface them as True (the frame/heartbeat is
# dropped); delay sleeps and lets the op proceed; crash SIGKILLs the
# process — the hard-death the persistence/failover recovery paths are
# specified against
ACTION_ERROR = "error"
ACTION_DROP = "drop"
ACTION_DELAY = "delay"
ACTION_CRASH = "crash"

ACTIONS = (ACTION_ERROR, ACTION_DROP, ACTION_DELAY, ACTION_CRASH)

# how many decisions a probabilistic rule pre-draws: past this many hits
# the rule stops firing (deterministically) rather than drawing fresh
# randomness at hit time
DECISION_HORIZON = 4096


class FailPointError(RuntimeError):
    """Raised at an armed failpoint (the reference panics; activities catch
    this to simulate side-effect-edge crashes)."""

    def __init__(self, name: str):
        super().__init__(f"failpoint {name!r} triggered")
        self.name = name


def decision_sequence(seed, name: str, p: float,
                      horizon: int = DECISION_HORIZON) -> list[bool]:
    """The pre-drawn Bernoulli decisions for a probabilistic rule: the
    ONE derivation shared by env arming, API arming, and the wire-armed
    chaos schedules — identical ``(seed, site, p)`` means identical
    decisions in every process, which is what makes a multi-process
    fault history reproducible from one seed."""
    rng = random.Random(f"{seed}:{name}:{p:.6f}")
    return [rng.random() < p for _ in range(horizon)]


class FaultRule:
    """One armed site: action + budget + (optional) pre-drawn decisions.

    ``budget`` counts TRIGGERS, not hits — a probabilistic rule stays
    armed through declined hits. The legacy ``enable(name, n)`` is the
    special case (error action, p=1, budget=n)."""

    __slots__ = ("name", "action", "budget", "delay_s", "p", "seed",
                 "decisions", "hits", "fired")

    def __init__(self, name: str, action: str = ACTION_ERROR,
                 budget: int = 1, p: float = 1.0, seed=None,
                 delay_s: float = 0.0):
        if action not in ACTIONS:
            raise ValueError(f"unknown fault action {action!r}")
        if budget < 1:
            raise ValueError("fault budget must be >= 1")
        if not 0.0 < p <= 1.0:
            raise ValueError("fault probability must be in (0, 1]")
        self.name = name
        self.action = action
        self.budget = budget
        self.delay_s = max(0.0, float(delay_s))
        self.p = p
        self.seed = seed
        self.decisions = (None if p >= 1.0
                          else decision_sequence(seed, name, p))
        self.hits = 0
        self.fired = 0

    def decide(self) -> Optional[str]:
        """One hit's verdict (called under the registry lock): the action
        to perform, or None. Deterministic: the k-th hit always lands on
        decision ``k`` of the pre-drawn sequence."""
        i = self.hits
        self.hits += 1
        if self.fired >= self.budget:
            return None
        if self.decisions is not None:
            if i >= len(self.decisions) or not self.decisions[i]:
                return None
        self.fired += 1
        return self.action

    def exhausted(self) -> bool:
        return self.fired >= self.budget

    def status(self) -> dict:
        return {"name": self.name, "action": self.action,
                "budget": self.budget, "p": self.p,
                "delay_ms": self.delay_s * 1000.0,
                "hits": self.hits, "fired": self.fired}


class _Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._armed: dict[str, FaultRule] = {}
        # one (site, hit-index, action) record per trigger: the process's
        # fault history; history_digest() fingerprints it so two runs of
        # the same seed can be compared byte-for-byte
        self._history: list[tuple[str, int, str]] = []
        try:
            self._seed = int(os.environ.get("CHAOS_SEED", "0") or 0)
        except ValueError:
            log.warning("ignoring malformed CHAOS_SEED %r",
                        os.environ.get("CHAOS_SEED"))
            self._seed = 0
        env = os.environ.get("FAILPOINTS", "")
        for part in env.split(","):
            part = part.strip()
            if not part:
                continue
            if ":" not in part:
                self.enable(part, 1)
                continue
            name, spec = part.split(":", 1)
            if spec.startswith("p="):
                try:
                    p = float(spec[2:])
                    if not 0.0 < p <= 1.0:
                        raise ValueError(p)
                except ValueError:
                    log.warning("ignoring malformed FAILPOINTS entry %r "
                                "(want name:p=<0..1])", part)
                    continue
                # probabilistic arming stays reproducible: decisions come
                # from the seeded chaos RNG (CHAOS_SEED), never from hit-
                # time randomness. Unbudgeted: the sequence horizon bounds
                # total triggers instead.
                self.enable_probabilistic(name, p, seed=self._seed,
                                          budget=DECISION_HORIZON)
                continue
            try:
                budget = int(spec)
            except ValueError:
                # a malformed entry must not take down every process
                # importing the package (this runs at import time)
                log.warning("ignoring malformed FAILPOINTS entry %r "
                            "(want name:count or name:p=<prob>)", part)
                continue
            if budget <= 0:
                # `name:-3` used to arm and then pop on the first hit —
                # an operator typo silently became a one-shot fault
                log.warning("ignoring FAILPOINTS entry %r: budget must "
                            "be a positive count", part)
                continue
            self.enable(name, budget)

    @property
    def seed(self) -> int:
        return self._seed

    def enable(self, name: str, budget: int = 1) -> None:
        self.arm(FaultRule(name, ACTION_ERROR, budget=budget))

    def enable_probabilistic(self, name: str, p: float, seed=None,
                             budget: int = DECISION_HORIZON,
                             action: str = ACTION_ERROR,
                             delay_s: float = 0.0) -> None:
        self.arm(FaultRule(name, action, budget=budget, p=p,
                           seed=self._seed if seed is None else seed,
                           delay_s=delay_s))

    def arm(self, rule: FaultRule) -> None:
        with self._lock:
            self._armed[rule.name] = rule

    def disable(self, name: str) -> None:
        with self._lock:
            self._armed.pop(name, None)

    def disable_all(self) -> None:
        with self._lock:
            self._armed.clear()
            self._history.clear()

    def _decide(self, name: str) -> tuple[Optional[str], float]:
        with self._lock:
            rule = self._armed.get(name)
            if rule is None:
                return None, 0.0
            act = rule.decide()
            if act is not None:
                self._history.append((name, rule.hits - 1, act))
            if rule.exhausted() and rule.decisions is None:
                # legacy raise-N-times semantics: an exhausted
                # deterministic rule disarms (tests assert `armed()`
                # flips); probabilistic rules stay visible for status
                self._armed.pop(name, None)
            return act, rule.delay_s

    def _perform(self, name: str, act: str, delay_s: float) -> bool:
        """Execute a decided action OUTSIDE the lock; returns True when
        the site should treat the hit as a fault (raise/drop)."""
        if act == ACTION_DELAY:
            if delay_s > 0:
                import asyncio

                try:
                    asyncio.get_running_loop()
                except RuntimeError:
                    time.sleep(delay_s)
                else:
                    # this site runs ON an event-loop thread (upstream
                    # transport, engine.respond): a blocking sleep here
                    # would stall EVERY in-flight request and every
                    # heartbeat on the loop — far more than the one op
                    # the schedule targeted (spurious elections, not a
                    # brownout). The decision is already recorded in
                    # the fault history; the latency effect is simply
                    # not applied at loop-side sites. Arm delays at
                    # worker-side sites (engine.dispatch) instead.
                    log.warning(
                        "failpoint %s: skipping delay action on an "
                        "event-loop thread (use a worker-side site "
                        "like engine.dispatch for delays)", name)
            return False
        if act == ACTION_CRASH:
            log.warning("failpoint %s: crashing the process (SIGKILL)",
                        name)
            os.kill(os.getpid(), signal.SIGKILL)
            return False  # unreachable
        return True  # error | drop

    def hit(self, name: str) -> None:
        """Call at a potential fault site; raises while the budget lasts.
        ``delay`` actions sleep and let the op proceed; ``crash`` kills
        the process; ``error``/``drop`` raise."""
        act, delay_s = self._decide(name)
        if act is None:
            return
        if self._perform(name, act, delay_s):
            raise FailPointError(name)

    def branch(self, name: str) -> bool:
        """Like :meth:`hit` but RETURNS True (consuming one budget unit)
        instead of raising — for sites that model dropped or suppressed
        work rather than a surfaced error: ``mirror.partition`` drops a
        mirror frame on the floor, ``mirror.heartbeat`` suppresses a
        liveness heartbeat (engine/remote.py `_push_mirror`), so election
        paths are testable without real network chaos."""
        act, delay_s = self._decide(name)
        if act is None:
            return False
        return self._perform(name, act, delay_s)

    def armed(self, name: str) -> bool:
        with self._lock:
            return name in self._armed

    def status(self) -> list[dict]:
        """Per-site arming state + trigger counts (the chaos_status wire
        op and the campaign's episode reports read this)."""
        with self._lock:
            return [r.status() for r in self._armed.values()]

    def history(self) -> list[tuple[str, int, str]]:
        with self._lock:
            return list(self._history)

    def history_digest(self) -> str:
        """Fingerprint of every fault this process actually performed,
        in order — two runs of the same seed over the same request
        sequence produce the same digest."""
        with self._lock:
            doc = json.dumps(self._history, separators=(",", ":"))
        return hashlib.sha256(doc.encode()).hexdigest()


failpoints = _Registry()
