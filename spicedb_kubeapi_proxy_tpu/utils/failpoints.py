"""Fault-injection failpoints.

Mirrors /root/reference/pkg/failpoints/failpoints_on.go:19-48: named panic
sites armed with per-name call budgets. The reference compiles them in via a
build tag; here they are armed at runtime (API or
``FAILPOINTS=name:count,name2`` env) and are a no-op when not armed, so they
stay in production code paths like the reference's activity hooks
(activity.go:48,61,153,155,176,213).
"""

from __future__ import annotations

import logging
import os
import threading

log = logging.getLogger("sdbkp.failpoints")


class FailPointError(RuntimeError):
    """Raised at an armed failpoint (the reference panics; activities catch
    this to simulate side-effect-edge crashes)."""

    def __init__(self, name: str):
        super().__init__(f"failpoint {name!r} triggered")
        self.name = name


class _Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._armed: dict[str, int] = {}
        env = os.environ.get("FAILPOINTS", "")
        for part in env.split(","):
            part = part.strip()
            if not part:
                continue
            if ":" in part:
                name, count = part.split(":", 1)
                try:
                    budget = int(count)
                except ValueError:
                    # a malformed entry must not take down every process
                    # importing the package (this runs at import time)
                    log.warning("ignoring malformed FAILPOINTS entry %r "
                                "(want name:count)", part)
                    continue
                self.enable(name, budget)
            else:
                self.enable(part, 1)

    def enable(self, name: str, budget: int = 1) -> None:
        with self._lock:
            self._armed[name] = budget

    def disable(self, name: str) -> None:
        with self._lock:
            self._armed.pop(name, None)

    def disable_all(self) -> None:
        with self._lock:
            self._armed.clear()

    def hit(self, name: str) -> None:
        """Call at a potential fault site; raises while the budget lasts."""
        with self._lock:
            left = self._armed.get(name)
            if left is None:
                return
            if left <= 1:
                self._armed.pop(name, None)
            else:
                self._armed[name] = left - 1
        raise FailPointError(name)

    def branch(self, name: str) -> bool:
        """Like :meth:`hit` but RETURNS True (consuming one budget unit)
        instead of raising — for sites that model dropped or suppressed
        work rather than a surfaced error: ``mirror.partition`` drops a
        mirror frame on the floor, ``mirror.heartbeat`` suppresses a
        liveness heartbeat (engine/remote.py `_push_mirror`), so election
        paths are testable without real network chaos."""
        try:
            self.hit(name)
        except FailPointError:
            return True
        return False

    def armed(self, name: str) -> bool:
        with self._lock:
            return name in self._armed


failpoints = _Registry()
