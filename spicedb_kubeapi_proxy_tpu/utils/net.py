"""Shared asyncio server shutdown discipline.

Idle streaming/pooled connections (a watch with no traffic, a client's
pooled engine socket blocked in a read) never write, so their handlers
only notice a dead peer on write — and ``Server.wait_closed()`` waits
for ALL connection handlers on Python 3.12+, hanging shutdown forever.
Used by both the proxy HTTP server (proxy/server.py) and the engine
host (engine/remote.py).
"""

from __future__ import annotations

import asyncio
import logging


async def drain_server(server: asyncio.AbstractServer, conns: set,
                       grace: float = 2.0) -> None:
    """Close ``server`` and drain its handler tasks (``conns`` is the
    live-task set each handler registers itself in).

    - yields once so just-accepted handler tasks can register before the
      emptiness check (the accept callback creates tasks that may not
      have run yet);
    - loops until the set is EMPTY — late registrants appear during the
      grace await, so one snapshot would miss them;
    - bounds ``wait_closed()`` with a cancel sweep rather than trusting
      emptiness: a handler can still register between loop exit and the
      wait.
    """
    server.close()
    await asyncio.sleep(0)
    while conns:
        _, pending = await asyncio.wait(list(conns), timeout=grace)
        for t in pending:
            t.cancel()
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)
        grace = 0.1  # later rounds only sweep late registrants
    for sweep in range(10):
        try:
            await asyncio.wait_for(server.wait_closed(), timeout=1.0)
            return
        except asyncio.TimeoutError:
            for t in list(conns):
                t.cancel()
            if conns:
                await asyncio.gather(*list(conns), return_exceptions=True)
    # A handler wedged in non-cancellable work can defeat wait_closed()
    # forever; after the sweep budget, give up rather than hang stop().
    logging.getLogger(__name__).warning(
        "drain_server: wait_closed() unresolved after 10 cancel sweeps; "
        "abandoning drain with %d handler task(s) still live", len(conns))
