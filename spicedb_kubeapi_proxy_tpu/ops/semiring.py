"""Masked boolean-semiring SpMM: the one propagation primitive.

One reachability hop is a sparse-matrix/dense-"vector" product over the
(OR, AND) boolean semiring, with a per-edge activation mask fused into the
multiply: ``prop[b, d] = OR_e [dst(e)=d] (V[b, src(e)] AND act(e))`` where
``act(e) = (exp(e) > now) AND cav_ok[cav(e)]``. Direct tuples, userset
tuples, and arrow-term edges all share this one form (they were lowered to
the uniform ``dst <- src`` edge set at compile time), so this module is
the single owner of propagation for BOTH the single-device fixpoint
(ops/reachability._run) and the shard_map body (parallel/sharded
._run_sharded) — there is no second propagate body to drift.

The multiply runs in one of two modes, switched PER ITERATION by a
``lax.cond`` on the traced frontier occupancy (so the choice never
re-specializes the trace):

- **push** — frontier-driven: the dense blocks' frontier columns are
  bit-packed (ops/bitprop.pack_frontier) and contracted by the bit-packed
  VPU kernel, streaming 8x less HBM per hop. Best while the frontier is
  sparse: the kernel's operand is 1 bit per potential edge and the work
  is proportional to reached sources, not the full block.

  (A literal COO gather/scatter push — touching only frontier edges —
  is the textbook formulation, but TPU gathers are scalar-bound: the
  measured 10M-edge bench block runs ~100x SLOWER on the gather path
  than on blocks (see reachability.DENSE_MIN_EDGES notes). The
  bit-packed contraction is the TPU-shaped spelling of "push".)

- **pull** — column-dense: every dst row pulls its full source range
  through an MXU matmul (``A[n_dst, n_src] @ frontier^T``), lowered to
  an MXU-tile-shaped Pallas kernel (ops/bitprop.dense_or_matmul) when
  eligible, with a ``lax.dot_general`` fallback otherwise. Best when
  the frontier saturates and the batch amortizes the A stream.

The crossover threshold is a TRACED scalar fed by the engine from its
``engine_frontier_occupancy`` histogram (EWMA of observed final-frontier
occupancy -> ``crossover_from_occupancy``), so tuning it costs zero
recompiles. Both modes compute the exact same boolean product — the
differential suite (tests/test_parallel.py / tests/test_semiring.py)
pins byte-identical verdicts across push, pull, Pallas, and the numpy
oracle.

Residual (expiring / caveated / sparse) edges and the incremental delta
overlay always ride the gather/segment-max path: their edge sets are
small by construction (compile_graph routes everything big and static
into dense blocks), so mode switching would only add latency there.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Optional

import jax
import jax.numpy as jnp

from . import bitprop

# resolved_mode() values: "auto" = per-iteration lax.cond on occupancy;
# "push"/"pull" force one branch (the bench's same-revision baseline knob)
_MODES = ("auto", "push", "pull")
_FORCED: Optional[str] = None


def resolved_mode() -> str:
    """The propagation-mode policy baked into the next trace: a
    force_mode() override wins, then ``SDBKP_SEMIRING_MODE`` (auto /
    push / pull), else auto. Part of the jit-cache key
    (reachability._jit_run_for), so flipping it never reuses a stale
    trace."""
    if _FORCED is not None:
        return _FORCED
    mode = os.environ.get("SDBKP_SEMIRING_MODE", "auto")
    return mode if mode in _MODES else "auto"


@contextmanager
def force_mode(mode: str):
    """Force push/pull/auto for the duration (bench baseline + tests)."""
    global _FORCED
    if mode not in _MODES:
        raise ValueError(f"unknown semiring mode {mode!r}")
    prev = _FORCED
    _FORCED = mode
    try:
        yield
    finally:
        _FORCED = prev


def crossover_from_occupancy(ewma: Optional[float]) -> float:
    """Map the engine's frontier-occupancy EWMA (fraction of slots set in
    observed final frontiers, [0, 1]) to the push/pull crossover fed to
    :func:`propagate`: push while the traced per-iteration occupancy is
    <= the returned threshold. No signal yet (None) -> 1.0, i.e. always
    push where the bit path exists — the pre-semiring behavior. A hot
    (dense) workload shrinks the threshold so saturated iterations take
    the MXU pull path; the 0.05 floor keeps the cheap first hops (seeds
    only) on push even under a fully-dense steady state."""
    if ewma is None:
        return 1.0
    return float(min(1.0, max(0.05, 1.0 - ewma)))


def edge_activation(exp_rel: jax.Array, now_rel, cav: jax.Array,
                    cav_ok: Optional[jax.Array]) -> jax.Array:
    """The fused ``(exp > now) AND cav_ok[row]`` edge-activation mask,
    uint8 per edge. Computed ONCE per dispatch (callers hoist it outside
    their iteration/level loops — under K-step fusing that is once per
    fused window, not once per hop) and fed to the semiring multiply as
    its mask operand."""
    act = (exp_rel > now_rel).astype(jnp.uint8)
    if cav_ok is not None:
        act = act & cav_ok[cav]
    return act


def frontier_occupancy(Vflat: jax.Array) -> jax.Array:
    """Traced occupancy of the current frontier/state in [0, 1]: the
    mean of the uint8 0/1 state. Feeds the per-iteration push/pull
    ``lax.cond`` — a device-side scalar, never synced to the host."""
    return jnp.mean(Vflat.astype(jnp.float32))


def propagate(block_meta, blocks, blocks_bits, src, dst, act,
              dsrc, ddst, dact, Vflat, occ, crossover, *,
              level: Optional[int] = None, mode: str = "auto",
              shard: Optional[tuple] = None):
    """One masked-semiring hop: ``(prop [B, Mp] uint8, is_push int32)``.

    ``src``/``dst``/``act`` are the residual edge slice for this level
    (dst-sorted; ``act`` from :func:`edge_activation`); ``dsrc``/``ddst``/
    ``dact`` the incremental delta overlay (append order). ``block_meta``
    is the slim _BlockMeta tuple; ``blocks``/``blocks_bits`` the device
    matrices (bits entries may be None). Blocks are filtered here by
    ``level`` (None = all).

    ``occ``/``crossover`` are traced scalars: in auto mode the dense
    phase picks push (bit-packed) vs pull (dense matmul) via
    ``lax.cond(occ <= crossover, ...)`` — both branches are pure local
    compute (collective joins stay with the caller, so shard_map callers
    whose shards diverge on the branch cannot deadlock). ``mode``
    (static) forces one branch; when no selected block has a bit dual
    the branches are identical and the cond is elided (is_push = 0).

    ``shard``: ``(g_idx, ng)`` when the caller runs inside shard_map with
    block matrices sharded ``P(None, "graph")`` — the frontier slice then
    covers only this device's src-axis chunk.
    """
    B = Vflat.shape[0]
    Mp = Vflat.shape[1]
    # residual edges: gather + segment-max (boolean OR) over the slot
    # axis; trash padding lands in the trash row
    if src.shape[0]:
        gathered = (Vflat[:, src] & act[None, :]).T  # [E_slice, B]
        prop = jax.ops.segment_max(
            gathered, dst, num_segments=Mp, indices_are_sorted=True
        ).T  # [B, Mp]
    else:
        prop = jnp.zeros((B, Mp), dtype=jnp.uint8)
    # delta overlay: applied at EVERY level (contributions outside the
    # level's ranges are dropped by the caller's range-scoped merge)
    gathered_d = (Vflat[:, dsrc] & dact[None, :]).T  # [D_pad, B]
    prop = prop | jax.ops.segment_max(
        gathered_d, ddst, num_segments=Mp, indices_are_sorted=False
    ).T

    sel = [(bm, A, Ab)
           for bm, A, Ab in zip(block_meta, blocks, blocks_bits)
           if level is None or bm.level == level]
    if not sel:
        return prop, jnp.int32(0)

    def frontier_of(bm):
        if shard is None:
            return jax.lax.dynamic_slice(
                Vflat, (0, bm.src_off), (B, bm.n_src))
        g_idx, ng = shard
        w = bm.n_src // ng
        return jax.lax.dynamic_slice(
            Vflat, (0, bm.src_off + g_idx * w), (B, w))

    def pull_one(bm, A, frontier):
        # column-dense: MXU-tile Pallas kernel when the block's local
        # shard is tile-aligned and the kernel is enabled, else the XLA
        # dot_general (the lax fallback). Static choice — enablement is
        # part of the jit-cache key.
        if bitprop.dense_kernel_enabled() and bitprop.dense_eligible(
                A.shape[0], A.shape[1], B):
            return bitprop.dense_or_matmul(A, frontier)
        return (
            jax.lax.dot_general(
                frontier.astype(jnp.int8), A,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32) > 0
        ).astype(jnp.uint8)  # [B, n_dst]

    def push_one(bm, A, Ab, frontier):
        # frontier-driven: bit-packed contraction (8x smaller A stream);
        # blocks without a bit dual degrade to pull within the push pass
        if Ab is not None and B <= bitprop.BIT_B_MAX:
            vb = bitprop.pack_frontier(frontier, frontier.shape[1])
            return bitprop.bit_or_matmul(Ab, vb, B).T  # [B, n_dst]
        return pull_one(bm, A, frontier)

    def apply_blocks(p, use_push: bool):
        for bm, A, Ab in sel:
            f = frontier_of(bm)
            contrib = (push_one(bm, A, Ab, f) if use_push
                       else pull_one(bm, A, f))
            cur = jax.lax.dynamic_slice(
                p, (0, bm.dst_off), (B, bm.n_dst))
            p = jax.lax.dynamic_update_slice(
                p, cur | contrib, (0, bm.dst_off))
        return p

    push_differs = any(Ab is not None and B <= bitprop.BIT_B_MAX
                       for _, _, Ab in sel)
    if mode == "push" and push_differs:
        return apply_blocks(prop, True), jnp.int32(1)
    if mode == "pull" or not push_differs:
        return apply_blocks(prop, False), jnp.int32(0)
    # auto: per-iteration branch on TRACED occupancy — a lax.cond, never
    # a Python branch (the jit-stability lint pins this), so the mode
    # flips at runtime without re-specializing
    is_push = (occ <= crossover).astype(jnp.int32)
    prop = jax.lax.cond(
        is_push > 0,
        lambda p: apply_blocks(p, True),
        lambda p: apply_blocks(p, False),
        prop)
    return prop, is_push
