"""JAX/XLA kernels for batched relationship-graph reachability."""

from .reachability import CompiledGraph, compile_graph  # noqa: F401
