"""Bit-packed block propagation: a Pallas TPU kernel for the latency path.

One hop over a dense relation block computes ``out[d, b] = OR_s A[d, s] &
V[s, b]`` (reached(dst) = any reached src with an edge). The int8 MXU
matmul used for large batches streams ``n_dst * n_src`` bytes of A from HBM
per hop; a single-subject query (B=1 — the reference's per-request
LookupResources, pkg/authz/lookups.go:49-65, which BASELINE.md turns into
the p50 list-filter target) is therefore HBM-bound on an operand that is
99.5% zeros at bench density.

Packing the src axis into uint32 words shrinks the streamed operand 8x
(one bit per potential edge) and turns the hop into an (AND, OR)-semiring
contraction the VPU executes directly:

    out[d, b] = (OR_k A_bits[d, k] & V_bits[b, k]) != 0

The kernel tiles dst over the grid, keeps the packed frontier resident in
VMEM, and OR-accumulates 128-word lanes; the lane reduction happens once
per (tile, b). Large batches (B > BIT_B_MAX) keep using the MXU matmul —
at B=1024 the systolic array amortizes the A stream across the batch and
wins; at B<=8 this kernel's 8x-smaller stream wins.
"""

from __future__ import annotations

import os
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

BIT_B_MAX = 8  # batches up to this ride the bit kernel (beyond: MXU matmul)
TILE_D = 256  # dst rows per grid step
LANES = 128

# uint8 out tiles need sublane multiples of 32; uint32 A tiles need src
# words >= one lane row. Blocks smaller than this use the matmul path.
MIN_DST = 32
MIN_SRC = 32

# VMEM is ~16MiB/core; the kernel's resident set per grid step is the
# (double-buffered) A tile + the packed frontier + out/accumulators. Blocks
# whose packed rows blow this even at the smallest tile fall back to the MXU
# matmul, which XLA tiles itself — otherwise Mosaic fails AT RUNTIME on the
# first big-block query.
VMEM_BUDGET = int(os.environ.get("SDBKP_BITPROP_VMEM_BYTES",
                                 12 * 1024 * 1024))


def _k_pad(n_src: int) -> int:
    return -(-((n_src + 31) // 32) // LANES) * LANES


def _vmem_bytes(tile_d: int, k: int) -> int:
    # 2x A tile (pipeline double-buffering), packed frontier, out tile and
    # two int32 accumulators
    return (2 * tile_d + BIT_B_MAX) * k * 4 + 3 * tile_d * LANES * 4


def _pick_tile_for_k(n_dst: int, k: int):
    for t in (TILE_D, 128, 64, 32):
        if n_dst % t == 0 and _vmem_bytes(t, k) <= VMEM_BUDGET:
            return t
    return None


def pick_tile(n_dst: int, n_src: int):
    """Largest dst tile that divides n_dst and fits VMEM, or None if even
    the smallest tile does not fit (matmul fallback)."""
    return _pick_tile_for_k(n_dst, _k_pad(n_src))


def eligible(n_dst: int, n_src: int) -> bool:
    return (n_dst % MIN_DST == 0 and n_src % MIN_SRC == 0
            and pick_tile(n_dst, n_src) is not None)


def kernel_enabled() -> bool:
    """Bit kernel runs on TPU; tests force the interpreter with
    SDBKP_BITPROP=interpret (CPU default stays on the matmul path). The
    BitKernel feature gate turns it off wholesale."""
    from ..utils.features import features

    if not features.enabled("BitKernel"):
        return False
    mode = os.environ.get("SDBKP_BITPROP", "auto")
    if mode == "0":
        return False
    if mode == "interpret":
        return True
    return jax.default_backend() == "tpu"


def _interpret() -> bool:
    return os.environ.get("SDBKP_BITPROP") == "interpret" \
        or jax.default_backend() != "tpu"


def pack_block_host(dst_local: np.ndarray, src_local: np.ndarray,
                    n_dst: int, n_src: int) -> np.ndarray:
    """Edges -> uint32 bit matrix [n_dst, K_pad]; bit w of word k set means
    an edge from src ``32k + w``. K padded to the 128-lane width."""
    k0 = (n_src + 31) // 32
    k_pad = -(-k0 // LANES) * LANES
    bits = np.zeros((n_dst, k_pad), dtype=np.uint32)
    word = src_local // 32
    bit = (src_local % 32).astype(np.uint32)
    np.bitwise_or.at(bits, (dst_local, word), np.uint32(1) << bit)
    return bits


def pack_frontier(frontier: jax.Array, n_src: int) -> jax.Array:
    """uint8 frontier [B, n_src] -> packed [8, K_pad] uint32 (B rows used).

    Device-side: a reshape + shift + sum over the 32-bit word axis. Cost
    is O(n_src * B) — negligible next to the hop.
    """
    b = frontier.shape[0]
    k0 = n_src // 32
    shifts = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    words = jnp.sum(
        frontier.astype(jnp.uint32).reshape(b, k0, 32)
        * shifts[None, None, :],
        axis=2,
    )  # [B, K0]
    k_pad = -(-k0 // LANES) * LANES
    out = jnp.zeros((BIT_B_MAX, k_pad), dtype=jnp.uint32)
    return jax.lax.dynamic_update_slice(out, words, (0, 0))


def _bit_kernel(n_b: int, a_ref, v_ref, out_ref):
    # int32 throughout: Mosaic has no unsigned reductions, and mixing i1
    # masks across int32/uint8 tilings forces unsupported relayouts
    tile_d = a_ref.shape[0]
    k = a_ref.shape[1]
    lane = jax.lax.broadcasted_iota(jnp.int32, (tile_d, LANES), 1)
    out = jnp.zeros((tile_d, LANES), dtype=jnp.int32)
    for b in range(n_b):  # static: n_b <= BIT_B_MAX
        acc = jnp.zeros((tile_d, LANES), dtype=jnp.uint32)
        for kc in range(k // LANES):  # static unroll over lane chunks
            sl = slice(kc * LANES, (kc + 1) * LANES)
            acc = acc | (a_ref[:, sl] & v_ref[b, sl][None, :])
        hit = jnp.max((acc != 0).astype(jnp.int32), axis=1,
                      keepdims=True)  # [tile_d, 1] in {0, 1}
        out = out | jnp.where(lane == b, hit, 0)
    out_ref[:] = out


def bit_or_matmul(a_bits: jax.Array, v_bits: jax.Array, n_b: int) -> jax.Array:
    """(AND, OR) contraction: a_bits [n_dst, K] uint32, v_bits
    [BIT_B_MAX, K] uint32 -> reached [n_dst, n_b] uint8."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_dst, k = a_bits.shape
    # largest tile that divides n_dst exactly AND fits the VMEM budget
    # (eligible() guarantees one exists), so the grid covers every row
    tile_d = _pick_tile_for_k(n_dst, k) or MIN_DST
    out = pl.pallas_call(
        partial(_bit_kernel, n_b),
        grid=(n_dst // tile_d,),
        in_specs=[
            pl.BlockSpec((tile_d, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((BIT_B_MAX, k), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile_d, LANES), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_dst, LANES), jnp.int32),
        interpret=_interpret(),
    )(a_bits, v_bits)
    return out[:, :n_b].astype(jnp.uint8)


# ---------------------------------------------------------------------------
# MXU-shaped dense kernel: the semiring PULL path (ops/semiring.py)
# ---------------------------------------------------------------------------

# the MXU systolic array is 128x128; the dense kernel's grid tiles both
# block axes at exactly this, so every inner contraction is one MXU pass
MXU_TILE = 128
# int8 operands need sublane multiples of 32: batches are padded up to it
SUBLANE = 32
# frontier rows the dense kernel will pad/stream before the plain XLA
# matmul (which tiles the batch itself) is the better schedule
DENSE_B_MAX = 4096


def dense_kernel_enabled() -> bool:
    """Dense MXU Pallas kernel runs on TPU; tests force the interpreter
    with SDBKP_SEMIRING=interpret (CPU default stays on dot_general).
    The SemiringDenseKernel feature gate turns it off wholesale. Part of
    the jit-cache key (reachability._jit_run_for) — flipping it never
    reuses a stale trace."""
    from ..utils.features import features

    if not features.enabled("SemiringDenseKernel"):
        return False
    mode = os.environ.get("SDBKP_SEMIRING", "auto")
    if mode == "0":
        return False
    if mode == "interpret":
        return True
    return jax.default_backend() == "tpu"


def _dense_interpret() -> bool:
    return os.environ.get("SDBKP_SEMIRING") == "interpret" \
        or jax.default_backend() != "tpu"


def _dense_vmem_bytes(b32: int, n_dst: int) -> int:
    # double-buffered A tile + frontier tile + int32 out tile resident
    # per grid step
    return (2 * MXU_TILE * MXU_TILE + b32 * MXU_TILE
            + 4 * b32 * MXU_TILE)


def dense_eligible(n_dst: int, n_src: int, batch: int) -> bool:
    """Both block axes must be MXU-tile multiples (slot ranges are
    LANE=128-aligned by construction, so full blocks always qualify;
    sharded src chunks qualify when the per-device chunk stays
    tile-aligned) and the padded batch tile must fit VMEM."""
    if n_dst % MXU_TILE or n_src % MXU_TILE:
        return False
    if batch > DENSE_B_MAX:
        return False
    b32 = -(-batch // SUBLANE) * SUBLANE
    return _dense_vmem_bytes(b32, n_dst) <= VMEM_BUDGET


def _dense_kernel(f_ref, a_ref, out_ref):
    """One (dst-tile, src-tile) grid step of the masked boolean matmul:
    ``out[b, d] |= OR_s f[b, s] & a[d, s]`` via an int8 MXU contraction.
    The out tile is revisited across the src-tile grid axis (zeroed at
    the first step) — the standard Pallas accumulation pattern; the
    frontier-tile emptiness predicate skips the matmul for all-zero
    frontier chunks, the push-flavored work skip that makes the pull
    kernel cheap on sparse iterations too."""
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    @pl.when(jnp.any(f_ref[:] != 0))
    def _accum():
        part = jax.lax.dot_general(
            f_ref[:], a_ref[:],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)  # [b32, MXU_TILE]
        out_ref[:] = out_ref[:] | (part > 0).astype(jnp.int32)


def dense_or_matmul(A: jax.Array, frontier: jax.Array) -> jax.Array:
    """Masked boolean-semiring block hop on the MXU: ``A [n_dst, n_src]``
    int8, ``frontier [B, n_src]`` uint8 -> reached ``[B, n_dst]`` uint8.
    Grid = (dst tiles, src tiles), every tile exactly MXU-shaped;
    eligibility is the caller's job (:func:`dense_eligible`)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_dst, n_src = A.shape
    b = frontier.shape[0]
    b32 = -(-b // SUBLANE) * SUBLANE
    f = jnp.zeros((b32, n_src), dtype=jnp.int8)
    f = jax.lax.dynamic_update_slice(f, frontier.astype(jnp.int8), (0, 0))
    out = pl.pallas_call(
        _dense_kernel,
        grid=(n_dst // MXU_TILE, n_src // MXU_TILE),
        in_specs=[
            pl.BlockSpec((b32, MXU_TILE), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((MXU_TILE, MXU_TILE), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((b32, MXU_TILE), lambda i, j: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b32, n_dst), jnp.int32),
        interpret=_dense_interpret(),
    )(f, A)
    return (out[:b] > 0).astype(jnp.uint8)


def dense_hop_reference(A: np.ndarray, frontier: np.ndarray) -> np.ndarray:
    """Pure-numpy oracle of one dense block hop (tests)."""
    return ((frontier.astype(np.int64) @ A.astype(np.int64).T) > 0
            ).astype(np.uint8)


def bit_hop_reference(a_bits: np.ndarray, frontier: np.ndarray) -> np.ndarray:
    """Pure-numpy oracle of one packed hop (tests)."""
    n_dst, k = a_bits.shape
    n_src, n_b = frontier.shape
    out = np.zeros((n_dst, n_b), dtype=np.uint8)
    for b in range(n_b):
        idx = np.flatnonzero(frontier[:, b])
        words = idx // 32
        bits = np.uint32(1) << (idx % 32).astype(np.uint32)
        v = np.zeros(k, dtype=np.uint32)
        np.bitwise_or.at(v, words, bits)
        out[:, b] = ((a_bits & v[None, :]).any(axis=1)).astype(np.uint8)
    return out
