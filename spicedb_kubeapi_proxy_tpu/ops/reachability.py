"""Slot-space reachability: the TPU execution backend for permission checks.

This is the "native tier" the north star mandates (BASELINE.json): what the
reference delegates to SpiceDB's recursive graph dispatcher (CheckPermission
/ CheckBulkPermissions / LookupResources — reference pkg/authz/check.go:41-48,
pkg/authz/lookups.go:49-65) is compiled here into a fixed-shape, jit-friendly
fixpoint over a flat boolean state vector.

Design
------
Every ``(definition, relation-or-permission, object)`` triple is interned
into one flat "slot" index. The whole evaluation state is a single uint8
tensor ``V[M, B]`` (M = total slots, B = batch of subjects). Three kinds of
graph structure all become the SAME uniform edge form ``dst <- src``:

- direct relation tuples   ``pod:x#viewer@user:alice``
      src = slot(user, __self, alice),   dst = slot(pod, viewer, x)
- userset tuples           ``pod:x#viewer@group:eng#member``
      src = slot(group, member, eng),    dst = slot(pod, viewer, x)
- arrow terms              ``permission view = namespace->view`` over tuple
  ``pod:x#namespace@namespace:ns``
      src = slot(namespace, view, ns),   dst = slot(pod, __arrow_k, x)

Wildcard subjects (``user:*``) fall out for free: the wildcard object is
interned at index 1 of every type, and every query seeds both its concrete
subject slot and its type's wildcard slot.

One propagation step is then a gather + segment-max (boolean OR) over the
edge array, followed by a static elementwise program that recomputes every
permission slot range from its userset-rewrite expression (union ``|``,
intersection ``&``, exclusion ``& ^1``, nil ``0``). The full evaluation is
``V_{t+1} = elementwise(base | propagate(V_t))`` iterated to fixpoint in a
``lax.while_loop`` — monotone in the graph, so it converges in at most
graph-diameter steps; exclusion/intersection are re-evaluated every step so
userset rewrites keep exact semantics under vectorization (SURVEY.md §7
"hard parts" (a)). Relationship expiration is a per-edge timestamp mask
applied at query time.

Checks read single slots; LookupResources reads a slot range. Both are
encoded host-side as int32 slot indices, so the device computation has
fixed shapes (§7 hard part (b)): E, M, B, Q are bucket-padded and jit
re-specializes only when a bucket grows.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import TYPE_CHECKING, Optional

import numpy as np

import jax
import jax.numpy as jnp

from . import bitprop
from .. import native
from ..models.schema import (
    Arrow,
    Exclude,
    Expr,
    Intersect,
    Nil,
    RelationRef,
    Schema,
    Union,
)

if TYPE_CHECKING:  # break the ops <-> engine import cycle: annotation only
    from ..engine.store import Snapshot

SELF_REL = "__self"
VOID_IDX = 0  # reserved per-type object index for unknown ids
WILDCARD_IDX = 1  # reserved per-type object index for '*'

DEFAULT_MAX_ITERS = 128

# jitted fixpoint functions shared across CompiledGraph revisions with equal
# signatures (bounded: distinct schemas/bucket layouts, not revisions)
_JIT_CACHE: dict = {}
_JIT_CACHE_MAX = 32

# serializes lazy device-state init across worker threads (one lock for all
# graphs: init is rare — once per store revision — and never nests)
_DEV_INIT_LOCK = threading.Lock()


class ConvergenceError(RuntimeError):
    """The fixpoint hit its iteration budget before converging — the analog
    of SpiceDB's dispatch-depth error (embedded depth 50, reference
    pkg/spicedb/spicedb.go:33). Raised instead of silently denying."""


def _next_bucket(n: int, minimum: int = 8) -> int:
    """Pad sizes to power-of-two buckets to bound jit re-specialization."""
    b = minimum
    while b < n:
        b *= 2
    return b


# Every slot range is padded to a multiple of LANE so the state tensor can
# live as [B, rows, LANE] with ranges row-aligned: slot s = (s // LANE,
# s % LANE). Without this, a [M, B] layout at B=1 pads the lane axis 1->128
# and every elementwise op streams 128x more HBM than the state holds.
LANE = 128


@dataclass
class _BlockMeta:
    """One dense relation block: edges between a (src slot range, dst slot
    range) pair compiled to a dense int8 matrix ``A[n_dst, n_src]`` so one
    propagation hop over the block is an MXU matmul ``A @ V[src_range]``
    instead of elementwise gathers (TPU gathers are scalar-bound; matmuls
    stream at HBM bandwidth). Only never-expiring edges are eligible —
    expiring edges stay on the residual gather/segment path where the
    query-time clock masks them."""

    dst_off: int
    n_dst: int
    src_off: int
    n_src: int
    # host-side local edge coordinates used to materialize A on device
    dst_local: np.ndarray
    src_local: np.ndarray


# dense-block eligibility: a block must carry enough edges to beat the
# segment path (DENSE_MIN_EDGES), must fit in memory (DENSE_MAX_CELLS), and
# big blocks must additionally be dense enough that streaming A beats
# scalar gathers (DENSE_MIN_DENSITY)
DENSE_MIN_EDGES = 1024
DENSE_MIN_CELLS = 1 << 24  # 16M cells (16 MiB int8) — density waived below
DENSE_MIN_DENSITY = 5e-4
DENSE_MAX_CELLS = 3 << 30  # 3 GiB


@dataclass
class _PermProgram:
    """One permission's elementwise recompute: (dst_offset, size, expr),
    with expression leaves resolved to slot offsets."""

    dst_off: int
    size: int
    expr: Expr
    # leaf name -> slot offset (RelationRef name or Arrow term id)
    leaf_off: dict


@dataclass
class CompiledGraph:
    """An immutable device-ready compilation of (schema, snapshot)."""

    schema: Schema
    revision: int
    base_time: float
    M: int  # real slots (M is also the trash slot index; arrays sized M+1)
    slot_offset: dict  # (type_name, rel_name) -> offset
    type_sizes: dict  # type_name -> object count (incl. void/wildcard)
    # host edge arrays, sorted by dst, padded to bucket; pad rows point at
    # the trash slot with -inf expiration (never valid). The FULL edge set
    # lives here (the sharded path consumes it directly); the single-chip
    # path splits it into dense blocks + a residual at _dev() time.
    src: np.ndarray
    dst: np.ndarray
    exp_rel: np.ndarray  # float32 seconds relative to base_time; +inf = never
    n_edges: int
    programs: list  # topo-ordered _PermProgram list
    # dense-block split (see _BlockMeta): blocks cover the big never-expiring
    # relation ranges; res_idx indexes the edges that stay on the
    # gather/segment path (expiring, tiny, or too-sparse-to-densify)
    blocks: list = field(default_factory=list)
    res_idx: Optional[np.ndarray] = None
    # lazily-populated device state
    _device: dict = field(default_factory=dict)

    # -- host-side encoding ------------------------------------------------

    def offset_of(self, type_name: str, rel_name: str) -> Optional[int]:
        return self.slot_offset.get((type_name, rel_name))

    def encode_subject(self, type_name: str, obj_id: str,
                       subject_relation: Optional[str] = None,
                       objects=None) -> tuple[int, int]:
        """-> (subject_seed_slot, wildcard_seed_slot); trash slot when
        unknown so unknown subjects simply seed nothing."""
        trash = self.M
        if subject_relation:
            off = self.offset_of(type_name, subject_relation)
            # wildcards match only concrete subjects (oracle: a userset
            # subject query never matches a `type:*` tuple), so userset
            # subjects must not seed the wildcard slot
            wc_off = None
        else:
            off = self.offset_of(type_name, SELF_REL)
            wc_off = off
        if off is None:
            return trash, trash
        idx = self._obj_index(type_name, obj_id, objects)
        seed = off + idx if idx is not None else trash
        wc = wc_off + WILDCARD_IDX if wc_off is not None else trash
        return seed, wc

    def encode_target(self, type_name: str, permission: str, obj_id: str,
                      objects=None) -> int:
        """Slot to read a check result from; trash slot (always 0) when the
        type/permission/object is unknown."""
        off = self.offset_of(type_name, permission)
        if off is None:
            return self.M
        idx = self._obj_index(type_name, obj_id, objects)
        return off + idx if idx is not None else off + VOID_IDX

    def _obj_index(self, type_name: str, obj_id: str, objects) -> Optional[int]:
        if objects is None:
            return None
        it = objects.get(type_name)
        if it is None:
            return None
        i = it.lookup(obj_id)
        # ids interned after this snapshot was compiled have no edges; void
        # behaves identically (no edges) and keeps indices in range.
        if i is None or i >= self.type_sizes.get(type_name, 0):
            return VOID_IDX
        return i

    # -- device execution --------------------------------------------------

    def signature(self) -> tuple:
        """Everything baked statically into the traced computation. Two
        CompiledGraphs with equal signatures can share one jitted function —
        type sizes are bucket-padded, so steady-state writes (new tuples,
        even new objects within a bucket) keep the signature stable and hit
        the XLA compile cache."""

        def expr_sig(e: Expr, leaf_off: dict) -> tuple:
            if isinstance(e, Nil):
                return ("nil",)
            if isinstance(e, (RelationRef, Arrow)):
                return ("leaf", leaf_off[e])
            if isinstance(e, Union):
                return ("or",) + tuple(expr_sig(o, leaf_off) for o in e.operands)
            if isinstance(e, Intersect):
                return ("and",) + tuple(expr_sig(o, leaf_off) for o in e.operands)
            if isinstance(e, Exclude):
                return ("sub", expr_sig(e.base, leaf_off),
                        expr_sig(e.subtract, leaf_off))
            raise TypeError(e)

        return (
            self.M,
            tuple((p.dst_off, p.size, expr_sig(p.expr, p.leaf_off))
                  for p in self.programs),
            tuple((b.dst_off, b.n_dst, b.src_off, b.n_src)
                  for b in self.blocks),
            # padded residual length: the only residual property that is
            # baked into traced shapes (edge values are runtime args)
            -1 if self.res_idx is None
            else _next_bucket(max(len(self.res_idx), 1)),
        )

    def _dev(self):
        # concurrent first queries (asyncio.to_thread workers) race to
        # initialize; build into a local dict and publish atomically
        d = self._device
        if not d:
            with _DEV_INIT_LOCK:
                return self._dev_locked()
        return d

    def _dev_locked(self):
        d = self._device
        if not d:
            d = {}
            if self.res_idx is None:
                # no dense split computed: everything rides the segment path
                res_src, res_dst, res_exp = self.src, self.dst, self.exp_rel
            else:
                n_res = len(self.res_idx)
                E_pad = _next_bucket(max(n_res, 1))
                res_src = np.full(E_pad, self.M, dtype=np.int32)
                res_dst = np.full(E_pad, self.M, dtype=np.int32)
                res_exp = np.full(E_pad, -np.inf, dtype=np.float32)
                # res_idx is ascending into dst-sorted edge arrays, so the
                # residual stays dst-sorted (indices_are_sorted=True relies
                # on this)
                res_src[:n_res] = self.src[self.res_idx]
                res_dst[:n_res] = self.dst[self.res_idx]
                res_exp[:n_res] = self.exp_rel[self.res_idx]
            d["src"] = jnp.asarray(res_src)
            d["dst"] = jnp.asarray(res_dst)
            d["exp"] = jnp.asarray(res_exp)

            d["blocks"] = tuple(
                jnp.zeros((b.n_dst, b.n_src), dtype=jnp.int8)
                .at[jnp.asarray(b.dst_local), jnp.asarray(b.src_local)]
                .set(1)
                for b in self.blocks
            )
            # bit-packed duals of the dense blocks for the small-batch
            # latency path (ops/bitprop.py); None = block stays matmul-only.
            # Packing + device residency is skipped entirely when the bit
            # kernel cannot run (the toggle is part of the jit-cache key,
            # so no trace reads the bits in that case).
            bits_on = bitprop.kernel_enabled()
            d["blocks_bits"] = tuple(
                jnp.asarray(bitprop.pack_block_host(
                    b.dst_local, b.src_local, b.n_dst, b.n_src))
                if bits_on and bitprop.eligible(b.n_dst, b.n_src) else None
                for b in self.blocks
            )
            # the bit-kernel toggle is baked into traces, so it is part of
            # the shared-function cache key
            sig = (self.signature(), bitprop.kernel_enabled())
            run = _JIT_CACHE.get(sig)
            if run is None:
                run = jax.jit(partial(_run, self),
                              static_argnames=("max_iters",))
                if len(_JIT_CACHE) >= _JIT_CACHE_MAX:
                    _JIT_CACHE.pop(next(iter(_JIT_CACHE)))
                _JIT_CACHE[sig] = run
            d["run"] = run
            self._device = d
        return self._device

    def query_async(
        self,
        seed_slots: np.ndarray,  # int32 [B, 2] (subject slot, wildcard slot)
        q_slots: np.ndarray,  # int32 [Q]
        q_batch: np.ndarray,  # int32 [Q] batch row per query
        now: Optional[float] = None,
        max_iters: int = DEFAULT_MAX_ITERS,
    ) -> "QueryFuture":
        """Dispatch the fixpoint without blocking.

        The device→host copy is started eagerly (``copy_to_host_async``) so
        concurrent queries overlap their readback latency — the analog of
        the reference overlapping its LookupResources RPC with the upstream
        kube request (pkg/authz/responsefilterer.go:165-183). Call
        ``.result()`` on the returned future to wait.
        """
        d = self._dev()
        B = seed_slots.shape[0]
        Q = len(q_slots)
        B_pad = _next_bucket(B, 1)
        Q_pad = _next_bucket(Q, 8)
        seeds = np.full((B_pad, 2), self.M, dtype=np.int32)
        seeds[:B] = seed_slots
        qs = np.full(Q_pad, self.M, dtype=np.int32)
        qs[:Q] = q_slots
        qb = np.zeros(Q_pad, dtype=np.int32)
        qb[:Q] = q_batch
        now_rel = np.float32((time.time() if now is None else now) - self.base_time)
        out, converged, iters = d["run"](
            d["blocks"], d["blocks_bits"], d["src"], d["dst"], d["exp"],
            jnp.asarray(seeds), jnp.asarray(qs), jnp.asarray(qb),
            now_rel, max_iters=max_iters,
        )
        try:
            out.copy_to_host_async()
            converged.copy_to_host_async()
            # iters feeds the fixpoint-iterations metric in the engine's
            # result finalizer; without the prefetch that int() is a
            # synchronous device roundtrip per query (a full tunnel RTT on
            # remotely-attached chips)
            iters.copy_to_host_async()
        except AttributeError:  # non-jax array backends in tests
            pass
        return QueryFuture(out, converged, iters, Q, max_iters)

    def query(
        self,
        seed_slots: np.ndarray,
        q_slots: np.ndarray,
        q_batch: np.ndarray,
        now: Optional[float] = None,
        max_iters: int = DEFAULT_MAX_ITERS,
    ) -> np.ndarray:
        """Run the fixpoint synchronously; returns bool [Q]."""
        return self.query_async(
            seed_slots, q_slots, q_batch, now=now, max_iters=max_iters
        ).result()

    def hop_bytes(self, batch: int = 1) -> dict:
        """Estimated HBM traffic per fixpoint hop (bytes) for roofline
        reporting: residual gather/segment streams, dense-block operand
        streams (bit-packed or int8 A), and the elementwise program passes.
        An estimate of bytes *touched* — XLA fusion can only reduce it, so
        effective-bandwidth numbers derived from it are conservative."""
        rows = self.M // LANE + 1
        Mp = rows * LANE
        E_res = len(self.res_idx) if self.res_idx is not None \
            else self.n_edges
        E_pad = _next_bucket(max(E_res, 1))
        # per edge: src+dst int32 + valid uint8 + B gathered bytes; plus
        # the propagated state write
        res = E_pad * (4 + 4 + 1 + batch) + batch * Mp
        blocks = 0
        use_bits = batch <= bitprop.BIT_B_MAX and bitprop.kernel_enabled()
        for b in self.blocks:
            if use_bits and bitprop.eligible(b.n_dst, b.n_src):
                k0 = (b.n_src + 31) // 32
                k_pad = -(-k0 // bitprop.LANES) * bitprop.LANES
                blocks += b.n_dst * k_pad * 4
            else:
                blocks += b.n_dst * b.n_src
        prog = sum(2 * p.size * batch for p in self.programs)
        return {"residual": res, "blocks": blocks, "programs": prog,
                "total": res + blocks + prog}


@dataclass
class QueryFuture:
    """A dispatched reachability query. ``result()`` blocks and validates
    convergence. ``iterations()`` (valid after result/convergence check)
    reports how many fixpoint hops the query ran — the analog of SpiceDB's
    dispatch depth, exported to the metrics registry by the engine."""

    _out: object
    _converged: object
    _iters: object
    _q: int
    _max_iters: int

    def result(self) -> np.ndarray:
        if not bool(self._converged):
            raise ConvergenceError(
                f"reachability did not converge within {self._max_iters} "
                "iterations (graph deeper than the dispatch budget)"
            )
        return np.asarray(self._out)[: self._q]

    def iterations(self) -> int:
        return int(self._iters)


def _apply_program(cg: CompiledGraph, V):
    """Recompute every permission slot range from its expression. V is
    [B, rows, LANE]; every range offset/size is a multiple of LANE, so a
    range is a row-aligned static slice along axis 1."""

    def ev(expr: Expr, p: _PermProgram):
        if isinstance(expr, Nil):
            return jnp.zeros((V.shape[0], p.size // LANE, LANE),
                             dtype=V.dtype)
        if isinstance(expr, (RelationRef, Arrow)):
            off = p.leaf_off[expr]
            return jax.lax.dynamic_slice_in_dim(
                V, off // LANE, p.size // LANE, axis=1)
        if isinstance(expr, Union):
            out = ev(expr.operands[0], p)
            for e in expr.operands[1:]:
                out = out | ev(e, p)
            return out
        if isinstance(expr, Intersect):
            out = ev(expr.operands[0], p)
            for e in expr.operands[1:]:
                out = out & ev(e, p)
            return out
        if isinstance(expr, Exclude):
            return ev(expr.base, p) & (ev(expr.subtract, p) ^ 1)
        raise TypeError(f"unknown expr {expr!r}")

    for p in cg.programs:
        V = jax.lax.dynamic_update_slice_in_dim(
            V, ev(p.expr, p), p.dst_off // LANE, axis=1)
    return V


def _propagate(cg: CompiledGraph, blocks, blocks_bits, src, dst, valid, V):
    """One hop: dense relation blocks as MXU matmuls (large batch) or
    bit-packed VPU contractions (small batch), plus residual edges as a
    gather/segment-max. V is [B, rows, LANE]; returns prop in the flat
    [B, rows*LANE] view (caller reshapes)."""
    B = V.shape[0]
    Mp = V.shape[1] * LANE  # M + trash row
    Vflat = V.reshape(B, Mp)
    # residual (expiring / sparse / tiny) edges: gather + segment-max over
    # the slot axis (edge arrays index flat slots; trash padding lands in
    # the trash row)
    gathered = (Vflat[:, src] & valid[None, :]).T  # [E_res, B]
    prop = jax.ops.segment_max(
        gathered, dst, num_segments=Mp, indices_are_sorted=True
    ).T  # [B, Mp]
    # B is static under trace, so the representation choice is baked into
    # the compiled program: bit kernel streams 8x less HBM per hop at
    # B<=BIT_B_MAX; the MXU matmul amortizes A across large batches
    use_bits = B <= bitprop.BIT_B_MAX and bitprop.kernel_enabled()
    for bm, A, Abits in zip(cg.blocks, blocks, blocks_bits):
        frontier = jax.lax.dynamic_slice(
            Vflat, (0, bm.src_off), (B, bm.n_src)
        )  # [B, n_src]
        if use_bits and Abits is not None:
            vb = bitprop.pack_frontier(frontier, bm.n_src)
            contrib = bitprop.bit_or_matmul(Abits, vb, B).T  # [B, n_dst]
        else:
            contrib = (
                jax.lax.dot_general(
                    frontier.astype(jnp.int8), A,
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.int32) > 0
            ).astype(jnp.uint8)  # [B, n_dst]
        cur = jax.lax.dynamic_slice(prop, (0, bm.dst_off), (B, bm.n_dst))
        prop = jax.lax.dynamic_update_slice(
            prop, cur | contrib, (0, bm.dst_off)
        )
    return prop


def _seed_base(cg: CompiledGraph, seeds):
    """Seed the [B, rows, LANE] state from subject/wildcard slot pairs and
    run the permission programs once. The single source of the layout
    invariants (rows = M/LANE + trash row; trash row stays 0 so unknown
    subjects seed nothing) — both the single-chip and sharded fixpoints
    build their base here."""
    B = seeds.shape[0]
    rows = cg.M // LANE + 1  # + trash row (slots M .. M+LANE-1)
    Mp = rows * LANE
    brange = jnp.arange(B, dtype=jnp.int32)
    base = jnp.zeros((B, Mp), dtype=jnp.uint8)
    base = base.at[brange, seeds[:, 0]].max(1)
    base = base.at[brange, seeds[:, 1]].max(1)
    base = base.at[:, cg.M:].set(0)
    return _apply_program(cg, base.reshape(B, rows, LANE))


def _run(cg: CompiledGraph, blocks, blocks_bits, src, dst, exp_rel, seeds,
         q_slots, q_batch, now_rel, *, max_iters: int):
    """The jitted fixpoint. V layout: [B, rows, LANE] uint8 — the slot
    space rides the lane axis so a B=1 query streams exactly M bytes per
    elementwise pass instead of a lane-padded 128x that; slot s lives at
    (s // LANE, s % LANE) and every range is row-aligned."""
    B = seeds.shape[0]
    rows = cg.M // LANE + 1  # + trash row (slots M .. M+LANE-1)
    Mp = rows * LANE
    valid = (exp_rel > now_rel).astype(jnp.uint8)  # [E_res]
    base = _seed_base(cg, seeds)

    def step(V):
        prop = _propagate(cg, blocks, blocks_bits, src, dst, valid, V)
        return _apply_program(
            cg, prop.reshape(B, rows, LANE) | base)

    def cond(state):
        V, prev_changed, it = state
        return prev_changed & (it < max_iters)

    def body(state):
        V, _, it = state
        V2 = step(V)
        return V2, jnp.any(V2 != V), it + 1

    V0 = base
    V, still_changing, iters = jax.lax.while_loop(
        cond, body, (V0, jnp.bool_(True), 0))
    # still_changing at loop exit means we hit max_iters before convergence;
    # surface it so the host can raise instead of silently denying
    out = V.reshape(B, Mp)[q_batch, q_slots].astype(jnp.bool_)
    return out, jnp.logical_not(still_changing), iters


# ---------------------------------------------------------------------------
# Compilation: (schema, snapshot) -> CompiledGraph
# ---------------------------------------------------------------------------


def _topo_permissions(defn) -> list[str]:
    """Topologically order a definition's permissions by their intra-type
    RelationRef dependencies (cross-type and cyclic deps are resolved by the
    outer fixpoint; within a pass we just avoid reading an obviously stale
    sibling where possible)."""
    deps: dict[str, set] = {}
    for name, perm in defn.permissions.items():
        refs = set()

        def walk(e):
            if isinstance(e, RelationRef) and e.name in defn.permissions:
                refs.add(e.name)
            elif isinstance(e, (Union, Intersect)):
                for o in e.operands:
                    walk(o)
            elif isinstance(e, Exclude):
                walk(e.base)
                walk(e.subtract)

        walk(perm.expr)
        deps[name] = refs
    out: list[str] = []
    seen: set = set()

    def visit(n, path):
        if n in seen or n in path:
            return
        for d in sorted(deps[n]):
            visit(d, path | {n})
        seen.add(n)
        out.append(n)

    for n in sorted(deps):
        visit(n, set())
    return out


def compile_graph(schema: Schema, snapshot: Snapshot) -> CompiledGraph:
    """Compile a store snapshot into device-ready slot-space form.

    Everything here is vectorized numpy over the snapshot's columnar arrays
    — no per-relationship Python loops — so 10M-edge graphs compile in
    seconds on the host.
    """
    types_in = snapshot.types
    rels_in = snapshot.relations
    cols = snapshot.cols

    # ---- slot layout ----
    slot_offset: dict[tuple, int] = {}
    type_sizes: dict[str, int] = {}
    arrow_terms: dict[tuple, list[Arrow]] = {}  # (type, perm) -> arrows in order
    off = 0
    for tname in sorted(schema.definitions):
        d = schema.definitions[tname]
        tid = types_in.lookup(tname)
        n = len(snapshot.objects[tid]) if tid is not None and tid in snapshot.objects \
            else 2
        # bucket-pad the per-type object space so slot offsets (and thus the
        # jit signature) stay stable as objects are interned within a
        # bucket; the LANE floor keeps every slot range row-aligned in the
        # [B, rows, LANE] state layout
        n = _next_bucket(max(n, 2), LANE)
        type_sizes[tname] = n
        slot_offset[(tname, SELF_REL)] = off
        off += n
        for rname in sorted(d.relations):
            slot_offset[(tname, rname)] = off
            off += n
        for pname in sorted(d.permissions):
            arrows: list[Arrow] = []

            def collect(e):
                if isinstance(e, Arrow):
                    arrows.append(e)
                elif isinstance(e, (Union, Intersect)):
                    for o in e.operands:
                        collect(o)
                elif isinstance(e, Exclude):
                    collect(e.base)
                    collect(e.subtract)

            collect(d.permissions[pname].expr)
            arrow_terms[(tname, pname)] = arrows
            for k in range(len(arrows)):
                slot_offset[(tname, f"__arrow_{pname}_{k}")] = off
                off += n
        for pname in sorted(d.permissions):
            slot_offset[(tname, pname)] = off
            off += n
    M = off

    # ---- store-id -> offset lookup tables ----
    n_st = len(types_in)
    n_sr = len(rels_in)
    self_off = np.full(n_st + 1, -1, dtype=np.int64)
    rel_off = np.full((n_st + 1, n_sr + 1), -1, dtype=np.int64)  # writable rels
    relperm_off = np.full((n_st + 1, n_sr + 1), -1, dtype=np.int64)
    for tname, d in schema.definitions.items():
        tid = types_in.lookup(tname)
        if tid is None:
            continue
        self_off[tid] = slot_offset[(tname, SELF_REL)]
        for rname in d.relations:
            rid = rels_in.lookup(rname)
            if rid is not None:
                rel_off[tid, rid] = slot_offset[(tname, rname)]
                relperm_off[tid, rid] = slot_offset[(tname, rname)]
        for pname in d.permissions:
            rid = rels_in.lookup(pname)
            if rid is not None:
                relperm_off[tid, rid] = slot_offset[(tname, pname)]

    # ---- edges ----
    srcs: list[np.ndarray] = []
    dsts: list[np.ndarray] = []
    exps: list[np.ndarray] = []
    base_time = time.time()
    exp_rel_all = (cols.exp - base_time).astype(np.float32)

    rt = cols.rt.astype(np.int64)
    st = cols.st.astype(np.int64)
    rl = cols.rl.astype(np.int64)
    srl = cols.srl.astype(np.int64)

    dst_all = rel_off[rt, rl] + cols.rid  # -1-based stays negative
    dst_valid = rel_off[rt, rl] >= 0

    # direct tuples (includes wildcard subjects: wildcard object index is 1)
    m = (srl == 0) & dst_valid & (self_off[st] >= 0)
    srcs.append(self_off[st[m]] + cols.sid[m])
    dsts.append(dst_all[m])
    exps.append(exp_rel_all[m])

    # userset tuples: src is the subject's (type, relation|permission) slot
    us_off = relperm_off[st, srl]
    m = (srl != 0) & dst_valid & (us_off >= 0) & (cols.sid != WILDCARD_IDX)
    srcs.append(us_off[m] + cols.sid[m])
    dsts.append(dst_all[m])
    exps.append(exp_rel_all[m])

    # arrow term edges
    for (tname, pname), arrows in arrow_terms.items():
        if not arrows:
            continue
        tid = types_in.lookup(tname)
        if tid is None:
            continue
        for k, a in enumerate(arrows):
            ts_id = rels_in.lookup(a.tupleset)
            if ts_id is None:
                continue
            term_off = slot_offset[(tname, f"__arrow_{pname}_{k}")]
            # per-subject-type offset of the arrow target
            tgt_off = np.full(n_st + 1, -1, dtype=np.int64)
            d = schema.definitions[tname]
            for asub in d.relations[a.tupleset].allowed:
                if asub.relation:
                    continue  # arrows walk concrete subjects only
                sub_tid = types_in.lookup(asub.type)
                if sub_tid is None:
                    continue
                if schema.definitions[asub.type].relation_or_permission(a.target):
                    tgt_off[sub_tid] = slot_offset[(asub.type, a.target)]
            m = (
                (rt == tid) & (rl == ts_id) & (srl == 0)
                & (tgt_off[st] >= 0) & (cols.sid != WILDCARD_IDX)
            )
            srcs.append(tgt_off[st[m]] + cols.sid[m])
            dsts.append(term_off + cols.rid[m])
            exps.append(exp_rel_all[m])

    src = np.concatenate(srcs) if srcs else np.empty(0, dtype=np.int64)
    dst = np.concatenate(dsts) if dsts else np.empty(0, dtype=np.int64)
    exp = np.concatenate(exps) if exps else np.empty(0, dtype=np.float32)

    order = native.sort_perm(dst)
    if order is None:
        order = np.argsort(dst, kind="stable")
    src, dst, exp = src[order], dst[order], exp[order]

    n_edges = len(src)
    E_pad = _next_bucket(max(n_edges, 1))
    src_p = np.full(E_pad, M, dtype=np.int32)
    dst_p = np.full(E_pad, M, dtype=np.int32)
    exp_p = np.full(E_pad, -np.inf, dtype=np.float32)
    src_p[:n_edges] = src
    dst_p[:n_edges] = dst
    exp_p[:n_edges] = exp

    # ---- dense/residual split (single-chip MXU path) ----
    # ranges: every (type, rel) slot range, ascending; edges map to a
    # (dst range, src range) pair by binary search
    range_items = sorted(slot_offset.items(), key=lambda kv: kv[1])
    offs = np.asarray([o for _, o in range_items], dtype=np.int64)
    sizes = np.asarray(
        [type_sizes[t] for (t, _), _ in range_items], dtype=np.int64
    )
    blocks: list[_BlockMeta] = []
    res_parts: list[np.ndarray] = []
    if n_edges:
        never_expires = exp == np.inf
        dst_rid = np.searchsorted(offs, dst, side="right") - 1
        src_rid = np.searchsorted(offs, src, side="right") - 1
        key = dst_rid * len(offs) + src_rid
        # expiring edges always ride the residual path (query-time clock)
        key = np.where(never_expires, key, -1)
        uniq, inv, counts = np.unique(key, return_inverse=True,
                                      return_counts=True)
        for ui, (k, cnt) in enumerate(zip(uniq.tolist(), counts.tolist())):
            sel = np.flatnonzero(inv == ui)
            if k < 0:
                res_parts.append(sel)
                continue
            d_rid, s_rid = divmod(k, len(offs))
            n_dst, n_src = int(sizes[d_rid]), int(sizes[s_rid])
            cells = n_dst * n_src
            if (cnt < DENSE_MIN_EDGES or cells > DENSE_MAX_CELLS
                    or (cells > DENSE_MIN_CELLS
                        and cnt / cells < DENSE_MIN_DENSITY)):
                res_parts.append(sel)
                continue
            blocks.append(_BlockMeta(
                dst_off=int(offs[d_rid]), n_dst=n_dst,
                src_off=int(offs[s_rid]), n_src=n_src,
                dst_local=(dst[sel] - offs[d_rid]).astype(np.int32),
                src_local=(src[sel] - offs[s_rid]).astype(np.int32),
            ))
    res_idx = (np.sort(np.concatenate(res_parts)) if res_parts
               else np.empty(0, dtype=np.int64))

    # ---- elementwise programs ----
    programs: list[_PermProgram] = []
    for tname in sorted(schema.definitions):
        d = schema.definitions[tname]
        n = type_sizes[tname]
        for pname in _topo_permissions(d):
            arrows = arrow_terms[(tname, pname)]
            leaf_off: dict = {}
            arrow_seen = 0

            def resolve(e):
                nonlocal arrow_seen
                if isinstance(e, RelationRef):
                    leaf_off[e] = slot_offset[(tname, e.name)]
                elif isinstance(e, Arrow):
                    # nth arrow occurrence maps to its own term range
                    leaf_off[e] = slot_offset[
                        (tname, f"__arrow_{pname}_{arrow_seen}")
                    ]
                    arrow_seen += 1
                elif isinstance(e, (Union, Intersect)):
                    for o in e.operands:
                        resolve(o)
                elif isinstance(e, Exclude):
                    resolve(e.base)
                    resolve(e.subtract)

            expr = d.permissions[pname].expr
            resolve(expr)
            programs.append(
                _PermProgram(slot_offset[(tname, pname)], n, expr, leaf_off)
            )

    return CompiledGraph(
        schema=schema,
        revision=snapshot.revision,
        base_time=base_time,
        M=M,
        slot_offset=slot_offset,
        type_sizes=type_sizes,
        src=src_p,
        dst=dst_p,
        exp_rel=exp_p,
        n_edges=n_edges,
        programs=programs,
        blocks=blocks,
        res_idx=res_idx,
    )
