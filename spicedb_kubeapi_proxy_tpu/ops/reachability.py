"""Slot-space reachability: the TPU execution backend for permission checks.

This is the "native tier" the north star mandates (BASELINE.json): what the
reference delegates to SpiceDB's recursive graph dispatcher (CheckPermission
/ CheckBulkPermissions / LookupResources — reference pkg/authz/check.go:41-48,
pkg/authz/lookups.go:49-65) is compiled here into a fixed-shape, jit-friendly
fixpoint over a flat boolean state vector.

Design
------
Every ``(definition, relation-or-permission, object)`` triple is interned
into one flat "slot" index. The whole evaluation state is a single uint8
tensor ``V[M, B]`` (M = total slots, B = batch of subjects). Three kinds of
graph structure all become the SAME uniform edge form ``dst <- src``:

- direct relation tuples   ``pod:x#viewer@user:alice``
      src = slot(user, __self, alice),   dst = slot(pod, viewer, x)
- userset tuples           ``pod:x#viewer@group:eng#member``
      src = slot(group, member, eng),    dst = slot(pod, viewer, x)
- arrow terms              ``permission view = namespace->view`` over tuple
  ``pod:x#namespace@namespace:ns``
      src = slot(namespace, view, ns),   dst = slot(pod, __arrow_k, x)

Wildcard subjects (``user:*``) fall out for free: the wildcard object is
interned at index 1 of every type, and every query seeds both its concrete
subject slot and its type's wildcard slot.

One propagation step is then a gather + segment-max (boolean OR) over the
edge array, followed by a static elementwise program that recomputes every
permission slot range from its userset-rewrite expression (union ``|``,
intersection ``&``, exclusion ``& ^1``, nil ``0``). The full evaluation is
``V_{t+1} = elementwise(base | propagate(V_t))`` iterated to fixpoint in a
``lax.while_loop`` — monotone in the graph, so it converges in at most
graph-diameter steps; exclusion/intersection are re-evaluated every step so
userset rewrites keep exact semantics under vectorization (SURVEY.md §7
"hard parts" (a)). Relationship expiration is a per-edge timestamp mask
applied at query time.

Checks read single slots; LookupResources reads a slot range. Both are
encoded host-side as int32 slot indices, so the device computation has
fixed shapes (§7 hard part (b)): E, M, B, Q are bucket-padded and jit
re-specializes only when a bucket grows.
"""

from __future__ import annotations

import threading
import time
from contextlib import nullcontext
from dataclasses import dataclass, field, replace
from functools import partial
from typing import TYPE_CHECKING, Optional

import numpy as np

import jax
import jax.numpy as jnp

from . import bitprop, semiring
from .. import native
from ..utils.metrics import metrics
from ..models.schema import (
    Arrow,
    Exclude,
    Expr,
    Intersect,
    Nil,
    RelationRef,
    Schema,
    Union,
)

if TYPE_CHECKING:  # break the ops <-> engine import cycle: annotation only
    from ..engine.store import Snapshot

SELF_REL = "__self"
VOID_IDX = 0  # reserved per-type object index for unknown ids
WILDCARD_IDX = 1  # reserved per-type object index for '*'

DEFAULT_MAX_ITERS = 128

# Incremental-update sizing: small writes append edges into a separate
# FIXED-CAPACITY "delta" overlay segment (own gather/segment pass per hop)
# instead of recompiling the whole graph; invalidated base edges get their
# expiration forced to -inf on device (residual) or their dense-block cell
# cleared. The capacity is static — part of the jit signature — so overlay
# appends NEVER re-specialize; running out of capacity is a back-pressure
# signal (engine/compaction.py folds the tail into a fresh base off the
# write path), not a growth event.
DELTA_PAD_MIN = 1024  # legacy floor for hand-built graphs (signature only)
DELTA_CAPACITY = 4096  # default overlay capacity (engine --delta-capacity)
MAX_DELTA_RECORDS = 8192


def _fallback(reason: str) -> None:
    """Count one silent-no-more incremental fallback: the caller is about
    to decline the O(write) path and force a full recompile. Reasons:
    ``overflow`` (overlay/dead-ledger capacity or per-batch record cap),
    ``stratification-inversion`` (a first-ever dependency direction),
    ``closured-expiry`` (expiration attached to a closured block pair),
    ``closured-caveat`` (a conditional grant attached to a closured
    block pair — derived closure cells would serve it unconditionally),
    ``caveat`` (a caveat/context pair not expressible against the
    frozen instance tables: first-ever caveat, full row bucket, or an
    unencodable context),
    ``history-trimmed`` / ``unlogged`` (store-side, engine.py),
    ``layout`` (tuple not expressible against the frozen slot layout),
    ``unstratified`` (hand-built graph without overlay state)."""
    metrics.counter("engine_graph_incremental_fallback_total",
                    reason=reason).inc()

# jitted fixpoint functions shared across CompiledGraph revisions with equal
# signatures (bounded: distinct schemas/bucket layouts, not revisions)
_JIT_CACHE: dict = {}
_JIT_CACHE_MAX = 32

# Monotonic count of fresh jit traces built (cache misses in
# _jit_run_for). Tests freeze it after warmup to prove that residency
# churn — promote / demote / stream-in — never alters a jit signature:
# steady-state streaming must be ZERO recompiles.
_TRACE_BUILDS = 0

# serializes lazy device-state init across worker threads (one lock for all
# graphs: init is rare — once per store revision); re-entrant so the shared
# jit-cache helper can take it from both the init path (already holding it)
# and incremental_update
_DEV_INIT_LOCK = threading.RLock()


def _jit_run_for(cg: "CompiledGraph", active: Optional[tuple] = None):
    """The jitted fixpoint for cg's signature, shared across revisions.
    Cache mutation is serialized on _DEV_INIT_LOCK — _dev_locked and
    incremental_update would otherwise race the get/evict/insert.

    The closure captures a slim static-metadata view, NOT the graph: a
    captured CompiledGraph would pin its host edge arrays and _device HBM
    buffers for as long as the cache entry lives — a dead-revision memory
    leak proportional to graph size x cached signatures.

    Kernel/mode toggles that are baked into traces (bit kernel, dense
    Pallas kernel, forced semiring mode) discriminate the key — flipping
    one mid-process gets a fresh trace, never a stale one.

    ``active``: tiered dispatch passes the demand-set block indices
    (sorted tuple) — the trace consumes exactly those blocks. The key is
    a function of the QUERY SHAPE (which ranges seed / are read), never
    of residency, so promote/demote churn cannot cause a retrace."""
    global _TRACE_BUILDS
    sig = (cg.signature(), bitprop.kernel_enabled(),
           bitprop.dense_kernel_enabled(), semiring.resolved_mode(),
           active)
    with _DEV_INIT_LOCK:
        run = _JIT_CACHE.get(sig)
        if run is None:
            _TRACE_BUILDS += 1
            run = jax.jit(partial(_run, cg.run_meta(active)),
                          static_argnames=("max_iters", "q_contig_len",
                                           "q_contig_rows"))
            if len(_JIT_CACHE) >= _JIT_CACHE_MAX:
                _JIT_CACHE.pop(next(iter(_JIT_CACHE)))
            _JIT_CACHE[sig] = run
    return run


class ConvergenceError(RuntimeError):
    """The fixpoint hit its iteration budget before converging — the analog
    of SpiceDB's dispatch-depth error (embedded depth 50, reference
    pkg/spicedb/spicedb.go:33). Raised instead of silently denying."""


def _next_bucket(n: int, minimum: int = 8) -> int:
    """Pad sizes to power-of-two buckets to bound jit re-specialization."""
    b = minimum
    while b < n:
        b *= 2
    return b


# Every slot range is padded to a multiple of LANE so the state tensor can
# live as [B, rows, LANE] with ranges row-aligned: slot s = (s // LANE,
# s % LANE). Without this, a [M, B] layout at B=1 pads the lane axis 1->128
# and every elementwise op streams 128x more HBM than the state holds.
LANE = 128


@dataclass
class _BlockMeta:
    """One dense relation block: edges between a (src slot range, dst slot
    range) pair compiled to a dense int8 matrix ``A[n_dst, n_src]`` so one
    propagation hop over the block is an MXU matmul ``A @ V[src_range]``
    instead of elementwise gathers (TPU gathers are scalar-bound; matmuls
    stream at HBM bandwidth). Only never-expiring edges are eligible —
    expiring edges stay on the residual gather/segment path where the
    query-time clock masks them."""

    dst_off: int
    n_dst: int
    src_off: int
    n_src: int
    # host-side local edge coordinates used to materialize A on device;
    # None in the slim run_meta() view (the traced code reads offsets only)
    dst_local: Optional[np.ndarray]
    src_local: Optional[np.ndarray]
    # stratification level of the dst range (0 = iterated core; k>=1 =
    # applied once at phase k — see _stratify)
    level: int = 0
    # True when dst_local/src_local hold the REFLEXIVE-TRANSITIVE CLOSURE
    # of a self-pair (src range == dst range) instead of its base edges:
    # one application then yields every multi-hop value, so the range
    # peels out of the iterated core (see _stratify's ignore_self). The
    # diagonal keeps already-merged values alive across the replacing
    # per-level merge. Derived cells cannot be deleted individually —
    # incremental deletes RE-CLOSE the block from its base edges
    # (base_dst_local/base_src_local, kept for exactly this) in O(block).
    closured: bool = False
    base_dst_local: Optional[np.ndarray] = None
    base_src_local: Optional[np.ndarray] = None

    def slim(self) -> "_BlockMeta":
        return _BlockMeta(self.dst_off, self.n_dst, self.src_off,
                          self.n_src, None, None, self.level, self.closured)

    def reclosed(self, remove: set) -> Optional["_BlockMeta"]:
        """A new closured block with ``remove`` (local (dst, src) pairs)
        deleted from the BASE edge set and the closure recomputed — the
        O(block) alternative to a full graph recompile on membership
        deletes. None when the closure overflows (caller recompiles)."""
        keep = np.fromiter(
            ((int(d), int(s)) not in remove
             for d, s in zip(self.base_dst_local.tolist(),
                             self.base_src_local.tolist())),
            dtype=bool, count=len(self.base_dst_local))
        nb_dst = self.base_dst_local[keep]
        nb_src = self.base_src_local[keep]
        coo = _closure_pairs(nb_dst, nb_src, self.n_dst)
        if coo is None:
            return None
        dl, sl = coo
        return _BlockMeta(self.dst_off, self.n_dst, self.src_off,
                          self.n_src, dl, sl, self.level, True,
                          nb_dst, nb_src)


# dense-block eligibility: a block must carry enough edges to beat the
# segment path (DENSE_MIN_EDGES), must fit in memory (DENSE_MAX_CELLS), and
# big blocks must additionally be dense enough that streaming A beats
# scalar gathers (DENSE_MIN_DENSITY). Measured on v5e at the 10M-rel
# bench shape: the 9.85M-edge pod#viewer block (density 4.6e-3) runs
# ~3ms/query bit-packed vs ~310ms on the gather/segment path — TPU
# gathers are ~100x worse per edge, so lean strongly toward blocks.
DENSE_MIN_EDGES = 1024
DENSE_MIN_CELLS = 1 << 24  # 16M cells (16 MiB int8) — density waived below
DENSE_MIN_DENSITY = 5e-4
DENSE_MAX_CELLS = 3 << 30  # 3 GiB


@dataclass
class _PermProgram:
    """One permission's elementwise recompute: (dst_offset, size, expr),
    with expression leaves resolved to slot offsets."""

    dst_off: int
    size: int
    expr: Expr
    # leaf name -> slot offset (RelationRef name or Arrow term id)
    leaf_off: dict
    # stratification level of the permission range (see _stratify)
    level: int = 0


def _range_id(offs: np.ndarray, slot) -> int:
    """Range id owning a slot: offs is ascending range offsets."""
    return int(np.searchsorted(offs, slot, side="right")) - 1


# self-pair closures larger than this many pairs fall back to the plain
# iterated-core block (the closure of a dense DAG can approach n^2 pairs;
# the dense matrix tolerates that, but host join memory should stay bounded)
CLOSURE_MAX_PAIRS = 1 << 24


def _closure_pairs(dst_local: np.ndarray, src_local: np.ndarray,
                   n: int) -> Optional[tuple[np.ndarray, np.ndarray]]:
    """Reflexive-transitive closure of an n-node COO self-block (edge
    ``src -> dst`` flows the src slot's value to the dst slot). Returns
    (dst_local, src_local) int32 arrays INCLUDING the diagonal, or None
    when the closure exceeds CLOSURE_MAX_PAIRS. Sparse semi-join on the
    host: group graphs are shallow and narrow, so this is microseconds
    where a dense matrix power would stream gigabytes. Handles instance
    cycles (recursive groups) — the pair-set union converges regardless."""
    base_order = np.argsort(src_local, kind="stable")
    b_src = src_local[base_order].astype(np.int64)
    b_dst = dst_local[base_order].astype(np.int64)
    cur = np.unique(src_local.astype(np.int64) * n + dst_local)
    while True:
        cs, cd = cur // n, cur % n
        # compose: (s -> d) ∘ (d -> d2) gives (s -> d2)
        lo = np.searchsorted(b_src, cd, side="left")
        hi = np.searchsorted(b_src, cd, side="right")
        counts = hi - lo
        total = int(counts.sum())
        if total:
            starts = np.repeat(lo, counts)
            offsets = np.arange(total) - np.repeat(
                np.cumsum(counts) - counts, counts)
            new_pairs = (np.repeat(cs, counts) * n
                         + b_dst[starts + offsets])
            merged = np.unique(np.concatenate([cur, new_pairs]))
        else:
            merged = cur
        if len(merged) > CLOSURE_MAX_PAIRS:
            return None
        if len(merged) == len(cur):
            break
        cur = merged
    diag = np.arange(n, dtype=np.int64)
    cur = np.unique(np.concatenate([cur, diag * n + diag]))
    return (cur % n).astype(np.int32), (cur // n).astype(np.int32)


def _stratify(offs: np.ndarray, src_rid: np.ndarray, dst_rid: np.ndarray,
              programs: list, ignore_self: frozenset = frozenset(),
              ) -> tuple[dict, int]:
    """Range-level stratification of the dependency graph.

    Build the range-granularity dependency graph (edges: src range feeds
    dst range; programs: every leaf range feeds the permission range) and
    iteratively peel ranges NOTHING still depends on. What cannot be
    peeled — cycles (recursive groups/orgs) and their ancestors — is the
    **core** (level 0), the only part the fixpoint must iterate. Peeled
    ranges get levels 1..L in reverse peel order, so every level-k
    range's inputs sit strictly below k and one application per level
    suffices.

    Why it matters: in kube-shaped graphs the overwhelmingly largest
    ranges (per-pod relations) are acyclic sinks — iterating them with
    the core multiplies the dominant per-hop HBM traffic by the graph
    diameter for nothing. Returns ({range_id: level}, n_levels).

    ``ignore_self``: range ids whose self-dependency (r -> r edges) is
    satisfied by a closured dense block (one application = all hops), so
    the self-edge must not force the range into the core.
    """
    n_ranges = len(offs)
    consumers: list[set] = [set() for _ in range(n_ranges)]
    if len(src_rid):
        # dedup range pairs vectorized (millions of edges -> dozens of
        # pairs) before touching Python objects
        pairs = np.unique(src_rid.astype(np.int64) * n_ranges + dst_rid)
        for p in pairs.tolist():
            s, d = divmod(p, n_ranges)
            if s == d and s in ignore_self:
                continue
            consumers[s].add(d)
    for p in programs:
        p_rid = _range_id(offs, p.dst_off)
        for off in set(p.leaf_off.values()):
            consumers[_range_id(offs, off)].add(p_rid)
    remaining = set(range(n_ranges))
    peel: list[list[int]] = []
    while True:
        removable = [r for r in remaining if not (consumers[r] & remaining)]
        if not removable:
            break
        peel.append(removable)
        remaining -= set(removable)
    n_levels = len(peel)
    level = {r: 0 for r in remaining}  # cyclic core + its ancestors
    for i, grp in enumerate(peel):  # peeled first -> evaluated last
        for r in grp:
            level[r] = n_levels - i
    return level, n_levels


@dataclass(frozen=True)
class RunMeta:
    """What the traced fixpoint reads from the graph: slot count,
    permission programs, dense-block offsets, stratification (residual
    level bounds + per-level edge-dst masks), and the caveat VM's
    static shapes. Captured by jit closures in place of the full
    CompiledGraph (see _jit_run_for)."""

    M: int
    programs: tuple
    blocks: tuple
    res_level_bounds: tuple  # len n_levels+2: slice bounds into residual
    n_levels: int
    # per level 1..L: tuple of (offset, size) slot ranges finalized at
    # that level (merged via per-range slice writes — no dense masks)
    level_ranges: tuple
    # caveat VM static meta (caveats/vm.py CavMeta per caveat) and the
    # total validity-row count (1 = no caveats: the VM is skipped and
    # edge activation is the expiration mask alone)
    caveats: tuple = ()
    cav_rows: int = 1
    # semiring propagation-mode policy baked into the trace: "auto" =
    # per-iteration lax.cond on traced occupancy; "push"/"pull" force one
    # branch (ops/semiring.py force_mode / SDBKP_SEMIRING_MODE)
    spmm_mode: str = "auto"


def convergence_fuse_steps(meta: "RunMeta") -> int:
    """Propagation steps the mesh backend fuses per convergence
    collective — the K in parallel/sharded.py's K-step fused while body.

    Derived from the compiled graph's stratification: a stratified graph
    iterates only its small cyclic core (recursive groups/orgs, which
    converge in a few hops — the per-pod bulk is peeled into one-shot
    acyclic levels), so K=2 halves the convergence collectives without
    wasting propagation work; an unstratified graph (hand-built, no
    level split) iterates everything with unknown diameter, so a deeper
    fuse amortizes better. The fixpoint is monotone — steps past
    convergence are no-ops — so K only trades at most K-1 cheap wasted
    hops against saved cross-axis collectives and host syncs."""
    return 2 if meta.n_levels else 4


@dataclass
class CompiledGraph:
    """An immutable device-ready compilation of (schema, snapshot)."""

    schema: Schema
    revision: int
    base_time: float
    M: int  # real slots (M is also the trash slot index; arrays sized M+1)
    slot_offset: dict  # (type_name, rel_name) -> offset
    type_sizes: dict  # type_name -> object count (incl. void/wildcard)
    # host edge arrays, sorted by dst, padded to bucket; pad rows point at
    # the trash slot with -inf expiration (never valid). The FULL edge set
    # lives here (the sharded path consumes it directly); the single-chip
    # path splits it into dense blocks + a residual at _dev() time.
    src: np.ndarray
    dst: np.ndarray
    exp_rel: np.ndarray  # float32 seconds relative to base_time; +inf = never
    n_edges: int
    programs: list  # topo-ordered _PermProgram list
    # dense-block split (see _BlockMeta): blocks cover the big never-expiring
    # relation ranges; res_idx indexes the edges that stay on the
    # gather/segment path (expiring, tiny, or too-sparse-to-densify)
    blocks: list = field(default_factory=list)
    res_idx: Optional[np.ndarray] = None
    # incremental-update state (engine write path, incremental_update()):
    # a FIXED-CAPACITY delta overlay segment consumed by its own
    # gather/segment pass each hop (append order, NOT dst-sorted), and the
    # (src, dst) pairs of base edges invalidated since the last full
    # compile (consumed by ShardedGraph so a sharded view of an
    # incrementally-updated graph stays consistent). The host arrays are
    # SHARED across incremental descendants of one compiled base and
    # mutated in place under ``host_lock`` — per-revision immutability
    # lives in the watermarks (n_delta / n_dead) and the functional
    # device arrays, not in host copies.
    delta_src: Optional[np.ndarray] = None  # int32 [cap], trash-padded
    delta_dst: Optional[np.ndarray] = None
    delta_exp: Optional[np.ndarray] = None  # float32 rel to base_time
    delta_cav: Optional[np.ndarray] = None  # int32 [cap] caveat rows
    n_delta: int = 0
    dead_pairs: Optional[np.ndarray] = None  # int64 [K, 2] (src, dst) view
    n_dead: int = 0
    delta_cap: int = 0  # static overlay capacity (0 = legacy/hand-built)
    # shared writer-state (one object per compiled base, carried by every
    # incremental descendant; reads/writes only under the engine's
    # graph-advance lock + host_lock):
    delta_pos: Optional[dict] = None  # (src, dst) -> overlay slot
    dead_set: Optional[set] = None  # (src, dst) pairs killed in the base
    dead_buf: Optional[np.ndarray] = None  # int64 [cap, 2] append buffer
    host_lock: Optional[object] = None  # guards shared host-array reads
    block_codes: Optional[dict] = None  # id(_BlockMeta) -> sorted codes
    # host residual views (padded; ordered by (level, dst) — see
    # _stratify/res_level_bounds) for device upload + incremental search
    res_src: Optional[np.ndarray] = None
    res_dst: Optional[np.ndarray] = None
    res_exp: Optional[np.ndarray] = None
    # per-residual-edge caveat validity row (0 = unconditional); the
    # edge participates in a hop iff its expiration passes AND its row
    # in the per-dispatch cav_ok vector reads 1 (caveats/vm.py)
    res_cav: Optional[np.ndarray] = None
    # compiled caveat table (caveats/vm.py CompiledCaveats): instance
    # context columns + op tapes, shared across incremental descendants
    caveats: Optional[object] = None
    # stratification: residual slice bounds per level (len n_levels+2)
    # and the level of every slot range (range_offs-aligned)
    res_level_bounds: Optional[tuple] = None
    n_levels: int = 0
    range_levels: Optional[np.ndarray] = None
    # compile-time lookup tables reused by the incremental path
    range_offs: Optional[np.ndarray] = None  # ascending slot-range offsets
    block_index: dict = field(default_factory=dict)  # (dst_off,src_off)->i
    self_off: Optional[np.ndarray] = None  # [n_types+1]
    rel_off: Optional[np.ndarray] = None  # [n_types+1, n_rels+1]
    relperm_off: Optional[np.ndarray] = None
    # (resource tid, tupleset rel id, term slot offset, tgt_off[n_types+1])
    arrow_maps: list = field(default_factory=list)
    # range-granularity dependency adjacency retained from compile time:
    # sorted ((src range id, dst range id), ...) pairs covering the FULL
    # edge set (computed before the dense split) plus every program's
    # leaf -> permission edge. The tiered dispatch path intersects
    # forward reachability from the seed ranges with backward
    # reachability from the queried ranges over this graph (plus the
    # live overlay) to pick the dense blocks a dispatch actually needs.
    # None on hand-built graphs (tiering then streams every block).
    range_adj: Optional[tuple] = None
    # tiered-storage residency state (storage/tiers.TierStore), attached
    # by enable_tiering(); None = classic all-resident placement. NOT
    # part of signature(): residency is invisible to traces. Shared
    # across incremental descendants of one compiled base (carried by
    # dataclasses.replace), rebuilt fresh by each compaction fold.
    tier: Optional[object] = None
    # push/pull crossover threshold fed to the semiring primitive as a
    # TRACED scalar (ops/semiring.propagate): push while the traced
    # per-iteration occupancy is <= this. Mutated in place by the engine
    # from its frontier-occupancy EWMA
    # (semiring.crossover_from_occupancy) — tuning costs zero recompiles.
    spmm_crossover: float = 1.0
    # lazily-populated device state
    _device: dict = field(default_factory=dict)

    # -- host-side encoding ------------------------------------------------

    def offset_of(self, type_name: str, rel_name: str) -> Optional[int]:
        return self.slot_offset.get((type_name, rel_name))

    def encode_subject(self, type_name: str, obj_id: str,
                       subject_relation: Optional[str] = None,
                       objects=None) -> tuple[int, int]:
        """-> (subject_seed_slot, wildcard_seed_slot); trash slot when
        unknown so unknown subjects simply seed nothing."""
        trash = self.M
        if subject_relation:
            off = self.offset_of(type_name, subject_relation)
            # wildcards match only concrete subjects (oracle: a userset
            # subject query never matches a `type:*` tuple), so userset
            # subjects must not seed the wildcard slot
            wc_off = None
        else:
            off = self.offset_of(type_name, SELF_REL)
            wc_off = off
        if off is None:
            return trash, trash
        idx = self._obj_index(type_name, obj_id, objects)
        seed = off + idx if idx is not None else trash
        wc = wc_off + WILDCARD_IDX if wc_off is not None else trash
        return seed, wc

    def encode_target(self, type_name: str, permission: str, obj_id: str,
                      objects=None) -> int:
        """Slot to read a check result from; trash slot (always 0) when the
        type/permission/object is unknown."""
        off = self.offset_of(type_name, permission)
        if off is None:
            return self.M
        idx = self._obj_index(type_name, obj_id, objects)
        return off + idx if idx is not None else off + VOID_IDX

    def _obj_index(self, type_name: str, obj_id: str, objects) -> Optional[int]:
        if objects is None:
            return None
        it = objects.get(type_name)
        if it is None:
            return None
        i = it.lookup(obj_id)
        # ids interned after this snapshot was compiled have no edges; void
        # behaves identically (no edges) and keeps indices in range.
        if i is None or i >= self.type_sizes.get(type_name, 0):
            return VOID_IDX
        return i

    # -- device execution --------------------------------------------------

    def signature(self) -> tuple:
        """Everything baked statically into the traced computation. Two
        CompiledGraphs with equal signatures can share one jitted function —
        type sizes are bucket-padded, so steady-state writes (new tuples,
        even new objects within a bucket) keep the signature stable and hit
        the XLA compile cache."""

        def expr_sig(e: Expr, leaf_off: dict) -> tuple:
            if isinstance(e, Nil):
                return ("nil",)
            if isinstance(e, (RelationRef, Arrow)):
                return ("leaf", leaf_off[e])
            if isinstance(e, Union):
                return ("or",) + tuple(expr_sig(o, leaf_off) for o in e.operands)
            if isinstance(e, Intersect):
                return ("and",) + tuple(expr_sig(o, leaf_off) for o in e.operands)
            if isinstance(e, Exclude):
                return ("sub", expr_sig(e.base, leaf_off),
                        expr_sig(e.subtract, leaf_off))
            raise TypeError(e)

        return (
            self.M,
            tuple((p.dst_off, p.size, p.level,
                   expr_sig(p.expr, p.leaf_off))
                  for p in self.programs),
            tuple((b.dst_off, b.n_dst, b.src_off, b.n_src, b.level,
                   b.closured)
                  for b in self.blocks),
            # padded delta-segment length (grows by buckets under
            # incremental updates; each growth re-specializes once). The
            # residual's traced shape is fully determined by
            # res_level_bounds below (per-level buckets).
            self._delta_pad(),
            # stratification: the traced program slices the residual at
            # these bounds and bakes per-level merge ranges, so two graphs
            # may share a jit ONLY with identical stratification. The
            # unstratified fallback (hand-built graphs) discriminates on
            # its full padded residual length instead.
            self.n_levels,
            self.res_level_bounds if self.res_level_bounds is not None
            else ("unstratified", len(self.res_src)
                  if self.res_src is not None else len(self.src)),
            None if self.range_levels is None
            else tuple(self.range_levels.tolist()),
            # the per-level merge windows (RunMeta.level_ranges) derive
            # from the range offsets; pin them so signature-equal graphs
            # cannot differ in any baked slice coordinate
            None if self.range_offs is None
            else tuple(self.range_offs.tolist()),
            # caveat VM shapes: tape lengths, register/context/list
            # layouts, instance-row buckets — all baked into the trace
            None if self.caveats is None else self.caveats.signature(),
        )

    def _delta_pad(self) -> int:
        if self.delta_src is not None:
            return len(self.delta_src)
        if self.delta_cap:
            return self.delta_cap
        return _next_bucket(max(self.n_delta, 1), DELTA_PAD_MIN)

    def _host_guard(self):
        """Context guarding reads of the SHARED mutable host arrays
        (delta segment, res_exp) against an in-flight overlay append."""
        return self.host_lock if self.host_lock is not None \
            else nullcontext()

    def run_meta(self, active: Optional[tuple] = None) -> "RunMeta":
        """Slim static-metadata view for jit closures: everything the
        traced fixpoint reads from the graph object, nothing that holds
        host edge arrays or device buffers alive.

        ``active`` (tiered dispatch): keep only these block indices —
        the trace then takes exactly that many block operands. The
        per-level merge windows stay UNFILTERED: an excluded closured
        block's range merges plain propagation values, which is safe
        because demand closure guarantees excluded ranges cannot
        influence any queried slot."""
        bounds = self.res_level_bounds
        if bounds is None:
            n_res = (len(self.res_src) if self.res_src is not None
                     else len(self.src))
            bounds = (0, n_res)  # unstratified: everything is core
        level_ranges = []
        if self.n_levels and self.range_levels is not None:
            offs = self.range_offs
            ends = np.append(offs[1:], self.M)
            for k in range(1, self.n_levels + 1):
                wins = [
                    (int(offs[rid]), int(ends[rid]) - int(offs[rid]))
                    for rid in np.flatnonzero(
                        self.range_levels == k).tolist()]
                # even phases merge exactly the closured blocks' ranges
                # (their in-edges merged at the odd phase just before;
                # the closure application finalizes them here)
                wins += [(b.dst_off, b.n_dst) for b in self.blocks
                         if b.closured and b.level == k]
                level_ranges.append(tuple(wins))
        cav = self.caveats
        kept = (self.blocks if active is None
                else [self.blocks[i] for i in active])
        return RunMeta(
            M=self.M,
            programs=tuple(self.programs),
            blocks=tuple(b.slim() for b in kept),
            res_level_bounds=tuple(bounds),
            n_levels=self.n_levels,
            level_ranges=tuple(level_ranges),
            caveats=cav.metas if cav is not None else (),
            cav_rows=cav.n_rows if cav is not None else 1,
            spmm_mode=semiring.resolved_mode(),
        )

    def _dev(self):
        # concurrent first queries (asyncio.to_thread workers) race to
        # initialize; build into a local dict and publish atomically
        d = self._device
        if not d:
            with _DEV_INIT_LOCK:
                return self._dev_locked()
        return d

    def _dev_locked(self):
        d = self._device
        if not d:
            with self._host_guard():
                d = self._dev_build()
                self._device = d
        return self._device

    def _dev_build(self):
        d = {}
        res_cav = self.res_cav
        if self.res_src is not None:
            res_src, res_dst, res_exp = \
                self.res_src, self.res_dst, self.res_exp
        elif self.res_idx is None:
            # no dense split computed: everything rides the segment path
            res_src, res_dst, res_exp = self.src, self.dst, self.exp_rel
        else:
            n_res = len(self.res_idx)
            E_pad = _next_bucket(max(n_res, 1))
            res_src = np.full(E_pad, self.M, dtype=np.int32)
            res_dst = np.full(E_pad, self.M, dtype=np.int32)
            res_exp = np.full(E_pad, -np.inf, dtype=np.float32)
            # res_idx is ascending into dst-sorted edge arrays, so the
            # residual stays dst-sorted (indices_are_sorted=True relies
            # on this)
            res_src[:n_res] = self.src[self.res_idx]
            res_dst[:n_res] = self.dst[self.res_idx]
            res_exp[:n_res] = self.exp_rel[self.res_idx]
        if res_cav is None or len(res_cav) != len(res_src):
            res_cav = np.zeros(len(res_src), dtype=np.int32)
        d["src"] = jnp.asarray(res_src)
        d["dst"] = jnp.asarray(res_dst)
        d["exp"] = jnp.asarray(res_exp)
        d["cav"] = jnp.asarray(res_cav)
        d["dsrc"], d["ddst"], d["dexp"], d["dcav"] = (
            jnp.asarray(a) for a in self._delta_host())
        # caveat VM instance tables (tapes + per-tuple context columns);
        # () when the graph carries no conditional grants
        d["cav_static"] = (self.caveats.device_static()
                          if self.caveats is not None
                          and self.caveats.metas else ())

        # Tiered placement: NOTHING is device-resident up front — every
        # block starts cold and streams in on first demand, which is
        # what makes "namespaces never touched by traffic cost zero
        # device bytes" literally true. The placeholder tuples keep the
        # dict shape for non-dispatch consumers; the dispatch path
        # assembles its own per-demand-set operand tuples.
        if self.tier is not None:
            d["blocks"] = tuple(None for _ in self.blocks)
            d["blocks_bits"] = tuple(None for _ in self.blocks)
            return d

        # dense blocks from host meta, minus any cells killed by
        # incremental updates since the last full compile (host meta is
        # not rewritten by incremental_update; dead_pairs is the ledger)
        blocks_dev = []
        bits_on = bitprop.kernel_enabled()
        bits_dev = []
        for b in self.blocks:
            dl_dead, sl_dead = self._dead_cells(b)
            A = jnp.zeros((b.n_dst, b.n_src), dtype=jnp.int8) \
                .at[jnp.asarray(b.dst_local),
                    jnp.asarray(b.src_local)].set(1)
            if len(dl_dead):
                A = A.at[jnp.asarray(dl_dead),
                         jnp.asarray(sl_dead)].set(0)
            blocks_dev.append(A)
            # bit-packed dual for the small-batch latency path
            # (ops/bitprop.py); None = block stays matmul-only. Packing
            # + device residency is skipped entirely when the bit
            # kernel cannot run (the toggle is part of the jit-cache
            # key, so no trace reads the bits in that case).
            if bits_on and bitprop.eligible(b.n_dst, b.n_src):
                bits = bitprop.pack_block_host(
                    b.dst_local, b.src_local, b.n_dst, b.n_src)
                if len(dl_dead):
                    np.bitwise_and.at(
                        bits, (dl_dead, sl_dead // 32),
                        ~(np.uint32(1) << (sl_dead % 32).astype(
                            np.uint32)))
                bits_dev.append(jnp.asarray(bits))
            else:
                bits_dev.append(None)
        d["blocks"] = tuple(blocks_dev)
        d["blocks_bits"] = tuple(bits_dev)
        # kernel/mode toggles are baked into traces, so they are part of
        # the shared-function cache key; query_async keeps a per-mode
        # entry so a force_mode() flip (bench baseline knob) cannot
        # dispatch through a stale trace
        d[("run", semiring.resolved_mode())] = _jit_run_for(self)
        return d

    def _dead_cells(self, bm: _BlockMeta) -> tuple[np.ndarray, np.ndarray]:
        """Local (dst, src) coordinates of dead_pairs falling inside a
        dense block's ranges."""
        if self.dead_pairs is None or not len(self.dead_pairs):
            z = np.empty(0, dtype=np.int64)
            return z, z
        s, t = self.dead_pairs[:, 0], self.dead_pairs[:, 1]
        m = ((t >= bm.dst_off) & (t < bm.dst_off + bm.n_dst)
             & (s >= bm.src_off) & (s < bm.src_off + bm.n_src))
        return t[m] - bm.dst_off, s[m] - bm.src_off

    def _delta_host(self) -> tuple[np.ndarray, np.ndarray, np.ndarray,
                                   np.ndarray]:
        """Host delta overlay segment (fixed capacity, append order —
        NOT dst-sorted); empty = all trash. Shared across incremental
        descendants; callers snapshotting it hold ``_host_guard``."""
        if self.delta_src is not None:
            cav = self.delta_cav if self.delta_cav is not None \
                else np.zeros(len(self.delta_src), dtype=np.int32)
            return self.delta_src, self.delta_dst, self.delta_exp, cav
        pad = self._delta_pad()
        return (np.full(pad, self.M, dtype=np.int32),
                np.full(pad, self.M, dtype=np.int32),
                np.full(pad, -np.inf, dtype=np.float32),
                np.zeros(pad, dtype=np.int32))

    # -- tiered storage ----------------------------------------------------

    def enable_tiering(self, budget_bytes: int,
                       spill_dir: Optional[str] = None):
        """Split this graph's dense blocks into residency-tracked tiers
        under an explicit device byte budget (storage/): every block's
        COO is encoded into a host-cold arena, nothing is uploaded until
        a dispatch demands it, and streamed blocks stay hot only while
        the budget allows. Call before serving queries (the engine does,
        right after compile); any previously built device block state is
        dropped. Returns the TierStore."""
        from ..storage import ColdArena, TierStore
        if self.tier is not None:
            # re-enable (budget change): retire the old store's
            # prefetch workers before the fresh one takes over
            self.tier.close()
        arena = ColdArena(spill_dir)
        tier = TierStore(budget_bytes, arena)
        bits_on = bitprop.kernel_enabled()
        for i, b in enumerate(self.blocks):
            nb = b.n_dst * b.n_src  # int8 dense cells
            if bits_on and bitprop.eligible(b.n_dst, b.n_src):
                k_pad = -(-((b.n_src + 31) // 32) // bitprop.LANES) \
                    * bitprop.LANES
                nb += b.n_dst * k_pad * 4  # packed dual rides along
            cols = {"dst_local": np.asarray(b.dst_local, dtype=np.int32),
                    "src_local": np.asarray(b.src_local, dtype=np.int32)}
            if b.closured:
                cols["base_dst_local"] = np.asarray(
                    b.base_dst_local, dtype=np.int32)
                cols["base_src_local"] = np.asarray(
                    b.base_src_local, dtype=np.int32)
            arena.put(i, cols)
            tier.register(i, nb, b.level)
        self.tier = tier
        with _DEV_INIT_LOCK:
            self._device = {}
        tier.publish_gauges()
        return tier

    def _demand_blocks(self, seed_slots: np.ndarray,
                       q_slots: np.ndarray) -> Optional[tuple]:
        """Block indices this dispatch can possibly exercise: a block is
        demanded iff its src range is forward-reachable from the seed
        ranges AND its dst range is backward-reachable from the queried
        ranges, over the compile-retained range adjacency plus the live
        overlay pairs. Everything outside that intersection provably
        cannot influence a queried slot, so it neither uploads nor
        counts an access. None = no adjacency (hand-built graph):
        stream everything.

        The result is cached per (seed ranges, query ranges, overlay
        watermark) — the demand key is a pure function of query shape,
        so steady traffic reuses both the active set and its trace."""
        offs = self.range_offs
        if offs is None or self.range_adj is None or not len(self.blocks):
            return None
        trash = self.M

        def ranges_of(slots) -> frozenset:
            s = np.asarray(slots).ravel()
            s = s[(s >= 0) & (s < trash)]
            if not len(s):
                return frozenset()
            rid = np.searchsorted(offs, s, side="right") - 1
            return frozenset(np.unique(rid).tolist())

        seed_r = ranges_of(seed_slots)
        q_r = ranges_of(q_slots)
        key = (seed_r, q_r, self.n_delta)
        cached = self.tier.demand_cache_get(key)
        if cached is not None:
            return cached
        n_ranges = len(offs)
        fwd: list = [set() for _ in range(n_ranges)]
        back: list = [set() for _ in range(n_ranges)]
        for s, t in self.range_adj:
            fwd[s].add(t)
            back[t].add(s)
        if self.n_delta and self.delta_src is not None:
            with self._host_guard():
                ds = self.delta_src[:self.n_delta].copy()
                dt = self.delta_dst[:self.n_delta].copy()
            keep = (ds >= 0) & (ds < trash) & (dt >= 0) & (dt < trash)
            if np.any(keep):
                srid = np.searchsorted(offs, ds[keep], side="right") - 1
                drid = np.searchsorted(offs, dt[keep], side="right") - 1
                for s, t in zip(srid.tolist(), drid.tolist()):
                    fwd[s].add(t)
                    back[t].add(s)

        def close_over(starts, edges) -> set:
            seen = set(starts)
            frontier = list(starts)
            while frontier:
                nxt = []
                for r in frontier:
                    for t in edges[r]:
                        if t not in seen:
                            seen.add(t)
                            nxt.append(t)
                frontier = nxt
            return seen

        reach_f = close_over(seed_r, fwd)
        reach_b = close_over(q_r, back)
        active = tuple(
            i for i, b in enumerate(self.blocks)
            if _range_id(offs, b.src_off) in reach_f
            and _range_id(offs, b.dst_off) in reach_b)
        self.tier.demand_cache_put(key, active)
        return active

    def _stream_blocks(self, active: tuple) -> tuple:
        """Assemble the dispatch's block operand tuples, streaming cold
        demanded blocks in through the double-buffered prefetcher in
        stratification order (level L lands before level L+1). The wall
        time the dispatch actually blocks on arrivals is the miss stall
        (engine_tier_miss_stall_seconds)."""
        tier = self.tier
        hot, missing = tier.lookup(active)
        if missing:
            t0 = time.perf_counter()
            futs = tier.prefetcher.fetch(
                missing, partial(_materialize_block, self))
            for i in missing:
                payload = futs[i].result()
                hot[i] = payload
                tier.admit(i, payload)
            tier.observe_stall(time.perf_counter() - t0)
        return (tuple(hot[i][0] for i in active),
                tuple(hot[i][1] for i in active))

    def query_async(
        self,
        seed_slots: np.ndarray,  # int32 [B, 2] (subject slot, wildcard slot)
        q_slots: np.ndarray,  # int32 [Q]
        q_batch: np.ndarray,  # int32 [Q] batch row per query
        now: Optional[float] = None,
        max_iters: int = DEFAULT_MAX_ITERS,
        q_cache_key: Optional[tuple] = None,
        q_contiguous: Optional[bool] = None,
        q_contig_grid: Optional[tuple] = None,  # (lo, L, R): R rows x
        # one shared [lo, lo+L) window (the fused-batch shape)
        context: Optional[dict] = None,  # request caveat context
        cav_req: Optional[tuple] = None,  # pre-encoded request arrays
        # (CompiledCaveats.encode_request) — chunked bulk callers encode
        # ONCE for the whole logical call instead of per chunk
    ) -> "QueryFuture":
        """Dispatch the fixpoint without blocking.

        The device→host copy is started eagerly (``copy_to_host_async``) so
        concurrent queries overlap their readback latency — the analog of
        the reference overlapping its LookupResources RPC with the upstream
        kube request (pkg/authz/responsefilterer.go:165-183). Call
        ``.result()`` on the returned future to wait.

        ``q_cache_key``: callers whose (q_slots, q_batch) are a pure
        function of the slot layout (list-filter masks read a type's whole
        permission range every time) pass a key so the padded device
        arrays are built and uploaded ONCE per compiled-graph generation —
        at the 100k-object scale that upload is ~0.5MB per query, a large
        share of wall latency on remotely-attached chips.
        """
        d = self._dev()
        B = seed_slots.shape[0]
        Q = len(q_slots)
        B_pad = _next_bucket(B, 1)
        Q_pad = _next_bucket(Q, 8)
        seeds = np.full((B_pad, 2), self.M, dtype=np.int32)
        seeds[:B] = seed_slots
        # Contiguous-window queries (the list-filter shape: one type's full
        # permission range) take a dynamic_slice extraction instead of the
        # latency-bound random gather, and ship two scalars instead of a
        # padded ~0.5MB index upload. Two forms:
        #   rows=1: one window (``q_contiguous=True`` is a caller promise —
        #           the engine builds ``off + arange(n)`` itself; None
        #           auto-detects);
        #   rows=R: the fused-batch grid (``q_contig_grid=(lo, L, R)``
        #           promise from engine/batcher.py) — R rows reading the
        #           SAME window, q order = row-major concatenation.
        # Slice lengths are exact (static, but unconstrained), so the
        # window always lies inside the state tensor (no clamp) and the
        # flat output needs no padding re-map; jit re-specialization is
        # bounded because callers repeat the same few (off, n) windows.
        Mp_state = (self.M // LANE + 1) * LANE
        contig = q_contiguous
        if contig is None and q_contig_grid is None and Q >= 1024:
            # auto-detect only LARGE windows: q_contig_len is a static
            # jit arg, so every distinct detected length is its own XLA
            # compile — a caller whose small query sets happen to be
            # consecutive must not accumulate per-length recompiles it
            # never asked for. Big windows are where the gather hurts,
            # and their lengths (full type ranges) barely vary. Explicit
            # promises (the engine/batcher) are always honored.
            contig = (int(q_slots[-1]) - int(q_slots[0]) == Q - 1
                      and not np.any(q_batch != q_batch[0])
                      and np.array_equal(
                          q_slots,
                          q_slots[0] + np.arange(Q, dtype=np.int64)))
        run_kwargs = {}
        qs_dev = qb_dev = None
        if q_contig_grid is not None:
            lo, L, R = q_contig_grid
            if (Q == L * R and 0 < L and 0 < R <= B_pad
                    and lo + L <= Mp_state):
                qs_dev = np.int32(lo)
                qb_dev = np.int32(0)
                run_kwargs["q_contig_len"] = L
                run_kwargs["q_contig_rows"] = R
        elif contig and Q and int(q_slots[0]) + Q <= Mp_state:
            qs_dev = np.int32(q_slots[0])
            qb_dev = np.int32(q_batch[0])
            run_kwargs["q_contig_len"] = Q
        if qs_dev is None:
            cached = d.get(("q", q_cache_key)) if q_cache_key else None
            if cached is not None:
                qs_dev, qb_dev = cached
            else:
                qs = np.full(Q_pad, self.M, dtype=np.int32)
                qs[:Q] = q_slots
                qb = np.zeros(Q_pad, dtype=np.int32)
                qb[:Q] = q_batch
                qs_dev, qb_dev = jnp.asarray(qs), jnp.asarray(qb)
                if q_cache_key:
                    # bounded: each entry pins megabytes of device arrays;
                    # evict the oldest rather than grow with key cardinality
                    q_keys = [k for k in d if isinstance(k, tuple)
                              and k and k[0] == "q"]
                    if len(q_keys) >= 32:
                        d.pop(q_keys[0], None)
                    d[("q", q_cache_key)] = (qs_dev, qb_dev)
        now_abs = time.time() if now is None else now
        now_rel = np.float32(now_abs - self.base_time)
        # request caveat context -> tiny per-caveat arrays riding the
        # dispatch (scalars + known flags per declared parameter); the
        # VM merges them under the tuple contexts ON DEVICE, so the
        # caveat mask lands in the same dispatch as the fixpoint
        cav = self.caveats
        if cav is not None and cav.metas:
            if cav_req is None:
                cav_req, _ = cav.encode_request(context, now_abs)
        else:
            cav_req = ()
        # named span in jax.profiler traces (bench --profile-dir / any
        # caller-managed jax.profiler.trace): lets a device timeline
        # attribute time to the reachability dispatch specifically
        # per-mode jitted entry (force_mode flips between dispatches must
        # hit their own trace); built lazily under the shared cache lock
        mk = semiring.resolved_mode()
        if self.tier is None:
            run = d.get(("run", mk))
            if run is None:
                run = _jit_run_for(self)
                d[("run", mk)] = run
            blocks_arg = d["blocks"]
            bits_arg = d["blocks_bits"]
        else:
            # tiered dispatch: demand-set the blocks, stream in the cold
            # ones, and run the per-(mode, active-set) trace. The run
            # key depends only on query shape — residency churn between
            # dispatches reuses this exact entry (zero recompiles).
            active = self._demand_blocks(seed_slots, q_slots)
            if active is None:
                active = tuple(range(len(self.blocks)))
            blocks_arg, bits_arg = self._stream_blocks(active)
            rk = ("run", mk, active)
            run = d.get(rk)
            if run is None:
                run = _jit_run_for(self, active)
                d[rk] = run
        with jax.profiler.TraceAnnotation("sdbkp:fixpoint"):
            # seeds ride the jit call as a host array: jax folds the
            # transfer into the dispatch instead of a separate device_put
            # round trip (visible through remotely-attached chips)
            out, converged, iters, n_push, cav_missing = run(
                blocks_arg, bits_arg, d["src"], d["dst"], d["exp"],
                d["cav"], d["dsrc"], d["ddst"], d["dexp"], d["dcav"],
                d["cav_static"], cav_req,
                seeds, qs_dev, qb_dev,
                now_rel, np.float32(self.spmm_crossover),
                max_iters=max_iters, **run_kwargs,
            )
        try:
            out.copy_to_host_async()
            converged.copy_to_host_async()
            # iters feeds the fixpoint-iterations metric in the engine's
            # result finalizer; without the prefetch that int() is a
            # synchronous device roundtrip per query (a full tunnel RTT on
            # remotely-attached chips)
            iters.copy_to_host_async()
            n_push.copy_to_host_async()
            cav_missing.copy_to_host_async()
        except AttributeError:  # non-jax array backends in tests
            pass
        return QueryFuture(out, converged, iters, Q, max_iters,
                           cav_missing, n_push)

    def query(
        self,
        seed_slots: np.ndarray,
        q_slots: np.ndarray,
        q_batch: np.ndarray,
        now: Optional[float] = None,
        max_iters: int = DEFAULT_MAX_ITERS,
    ) -> np.ndarray:
        """Run the fixpoint synchronously; returns bool [Q]."""
        return self.query_async(
            seed_slots, q_slots, q_batch, now=now, max_iters=max_iters
        ).result()

    def hop_bytes(self, batch: int = 1) -> dict:
        """Estimated HBM traffic (bytes) for roofline reporting, split by
        the stratified schedule: ``total`` is the per-ITERATION cost of
        the cyclic core (what multiplies by the fixpoint iteration count);
        ``tail_once`` is the one-shot cost of all acyclic levels. Streams
        counted: residual gather/segment, dense-block operands (bit-packed
        or int8 A), elementwise program passes. An estimate of bytes
        *touched* — XLA fusion can only reduce it.

        ``modes`` reports the core dense-block bytes PER SEMIRING MODE
        (ops/semiring.py) so collective-bytes baselines (ROADMAP item 1)
        can be stated per branch instead of assuming one layout:
        ``push`` streams each block's bit-packed dual (its eligible
        blocks) or the full int8 A where no dual exists; ``pull`` always
        streams the full int8 A; ``pallas`` adds the MXU kernel's
        frontier re-stream (the [b32, n_src] operand is re-read once per
        dst-tile row of the grid). ``blocks``/``total`` keep reporting
        the mode the CURRENT configuration would run (bits when the bit
        kernel is live and the batch fits, else dense)."""
        rows = self.M // LANE + 1
        Mp = rows * LANE

        def res_bytes(n):  # src+dst int32 + valid uint8 + B gathered
            return n * (4 + 4 + 1 + batch) + batch * Mp

        def bits_bytes(b):
            k0 = (b.n_src + 31) // 32
            k_pad = -(-k0 // bitprop.LANES) * bitprop.LANES
            return b.n_dst * k_pad * 4

        def push_bytes(b):
            # bit-packed dual when one exists for this batch, else the
            # push pass degrades to the dense pull stream for the block
            if batch <= bitprop.BIT_B_MAX and bitprop.eligible(
                    b.n_dst, b.n_src):
                return bits_bytes(b)
            return b.n_dst * b.n_src

        def pull_bytes(b):
            return b.n_dst * b.n_src

        def pallas_bytes(b):
            # dense MXU kernel: A streamed once + the padded frontier
            # tile re-streamed per dst-tile grid row
            if not bitprop.dense_eligible(b.n_dst, b.n_src, batch):
                return pull_bytes(b)
            b32 = -(-batch // bitprop.SUBLANE) * bitprop.SUBLANE
            return b.n_dst * b.n_src \
                + b32 * b.n_src * (b.n_dst // bitprop.MXU_TILE)

        def block_bytes(b):
            use_bits = (batch <= bitprop.BIT_B_MAX
                        and bitprop.kernel_enabled())
            if use_bits and bitprop.eligible(b.n_dst, b.n_src):
                return bits_bytes(b)
            return b.n_dst * b.n_src

        bounds = self.res_level_bounds
        if bounds is None:
            n_core = (len(self.res_idx) if self.res_idx is not None
                      else self.n_edges)
            tail_res = 0
        else:
            n_core = bounds[1] - bounds[0]
            tail_res = bounds[-1] - bounds[1]
        delta = self._delta_pad() * (4 + 4 + 1 + batch)
        core_res = res_bytes(n_core) + delta
        core_blk = [b for b in self.blocks if b.level == 0]
        core_blocks = sum(block_bytes(b) for b in core_blk)
        core_prog = sum(2 * p.size * batch for p in self.programs
                        if p.level == 0)
        tail = (res_bytes(tail_res) if tail_res else 0) \
            + sum(block_bytes(b) for b in self.blocks if b.level > 0) \
            + sum(2 * p.size * batch for p in self.programs if p.level > 0) \
            + self.n_levels * (delta + 2 * batch * Mp)  # merges + delta
        return {"residual": core_res, "blocks": core_blocks,
                "programs": core_prog, "tail_once": tail,
                "total": core_res + core_blocks + core_prog,
                "modes": {
                    "push": sum(push_bytes(b) for b in core_blk),
                    "pull": sum(pull_bytes(b) for b in core_blk),
                    "pallas": sum(pallas_bytes(b) for b in core_blk),
                }}


def _materialize_block(cg: "CompiledGraph", i: int) -> tuple:
    """Build one dense block's device arrays from its cold-arena COO
    (falling back to the compiled host meta for blocks the arena never
    saw), minus the dead-ledger cells — the streaming twin of the loop
    in ``_dev_build``. Runs on prefetch worker threads; reads only
    per-revision-immutable state (arena payloads are replaced whole by
    recloses, dead_pairs is a frozen watermark view)."""
    bm = cg.blocks[i]
    tier = cg.tier
    dl = sl = None
    if tier is not None and tier.arena.has(i):
        coo = tier.arena.get(i)
        dl, sl = coo["dst_local"], coo["src_local"]
    if dl is None:
        dl, sl = bm.dst_local, bm.src_local
    dl = np.asarray(dl)
    sl = np.asarray(sl)
    dl_dead, sl_dead = cg._dead_cells(bm)
    A = jnp.zeros((bm.n_dst, bm.n_src), dtype=jnp.int8) \
        .at[jnp.asarray(dl), jnp.asarray(sl)].set(1)
    if len(dl_dead):
        A = A.at[jnp.asarray(dl_dead), jnp.asarray(sl_dead)].set(0)
    bits = None
    if bitprop.kernel_enabled() and bitprop.eligible(bm.n_dst, bm.n_src):
        bits_h = bitprop.pack_block_host(dl, sl, bm.n_dst, bm.n_src)
        if len(dl_dead):
            np.bitwise_and.at(
                bits_h, (dl_dead, sl_dead // 32),
                ~(np.uint32(1) << (sl_dead % 32).astype(np.uint32)))
        bits = jnp.asarray(bits_h)
    return (A, bits)


def _tier_apply_update(cg: "CompiledGraph", blocks_host: list,
                       reclose: dict, block_cells: dict) -> None:
    """Incremental edits against tiered blocks (incremental_update's
    device section when a TierStore owns placement). Re-closed blocks
    re-encode their arena payload from the new closure COO and, when
    resident, rebuild their device arrays whole; plain cell edits apply
    the same functional scatter/bit-word updates the resident path uses
    — but only to hot payloads (cold blocks need nothing: the next
    materialization reads the updated host meta and dead ledger).
    Every touched block is PINNED hot until the next compaction fold
    rebuilds the graph — and with it a fresh TierStore, which is how
    pins reset."""
    tier = cg.tier
    for b in reclose:
        bm = blocks_host[b]
        tier.arena.put(b, {
            "dst_local": np.asarray(bm.dst_local, dtype=np.int32),
            "src_local": np.asarray(bm.src_local, dtype=np.int32),
            "base_dst_local": np.asarray(bm.base_dst_local,
                                         dtype=np.int32),
            "base_src_local": np.asarray(bm.base_src_local,
                                         dtype=np.int32)})
        if tier.peek(b) is not None:
            A = jnp.zeros((bm.n_dst, bm.n_src), dtype=jnp.int8) \
                .at[jnp.asarray(bm.dst_local),
                    jnp.asarray(bm.src_local)].set(1)
            bits = None
            if bitprop.kernel_enabled() and bitprop.eligible(
                    bm.n_dst, bm.n_src):
                bits = jnp.asarray(bitprop.pack_block_host(
                    bm.dst_local, bm.src_local, bm.n_dst, bm.n_src))
            tier.replace(b, (A, bits))
        tier.pin(b)
    for b, cells in block_cells.items():
        payload = tier.peek(b)
        if payload is not None:
            A, bits = payload
            dl = np.fromiter((c[0] for c in cells), dtype=np.int32,
                             count=len(cells))
            sl = np.fromiter((c[1] for c in cells), dtype=np.int32,
                             count=len(cells))
            vals = np.fromiter(cells.values(), dtype=np.int8,
                               count=len(cells))
            A = A.at[dl, sl].set(vals)
            if bits is not None:
                # group per (row, word): multiple cells can share a
                # packed word, and a gather-modify-scatter with
                # duplicate indices would drop updates
                agg: dict = {}
                for (dli, sli), v in cells.items():
                    k = (dli, sli // 32)
                    setm, clrm = agg.get(k, (0, 0))
                    bit = 1 << (sli % 32)
                    if v:
                        setm |= bit
                    else:
                        clrm |= bit
                    agg[k] = (setm, clrm)
                rows = np.array([k[0] for k in agg], dtype=np.int32)
                words = np.array([k[1] for k in agg], dtype=np.int32)
                sets = np.array([v[0] for v in agg.values()],
                                dtype=np.uint32)
                clrs = np.array([v[1] for v in agg.values()],
                                dtype=np.uint32)
                cur = bits[rows, words]
                bits = bits.at[rows, words].set(
                    (cur & jnp.asarray(~clrs)) | jnp.asarray(sets))
            tier.replace(b, (A, bits))
        tier.pin(b)


def tier_maintain(cg: "CompiledGraph") -> None:
    """Placement sweep, run off the serving path (the Compactor's
    worker thread — engine/compaction.py is the placement engine):
    decay access recency, demote blocks that went cold while the store
    is over headroom, and eagerly re-materialize pinned-but-cold blocks
    so the write path never pays a stream-in for its own overlay's
    dense cells. Publishes the occupancy gauges afterwards."""
    tier = getattr(cg, "tier", None)
    if tier is None:
        return
    for i in tier.place():
        tier.admit(i, _materialize_block(cg, i), pinned=True)
    tier.publish_gauges()


@dataclass
class QueryFuture:
    """A dispatched reachability query. ``result()`` blocks and validates
    convergence. ``iterations()`` (valid after result/convergence check)
    reports how many fixpoint hops the query ran — the analog of SpiceDB's
    dispatch depth, exported to the metrics registry by the engine.
    ``caveats_missing()`` is the number of caveat instances that resolved
    to the missing-context tri-state this dispatch (denied fail-closed;
    feeds ``engine_caveat_denied_missing_context_total``).
    ``push_steps()`` is how many of those hops took the semiring PUSH
    branch (ops/semiring.py; the rest took pull) — the per-iteration
    mode telemetry behind ``engine_semiring_push_steps_total``."""

    _out: object
    _converged: object
    _iters: object
    _q: int
    _max_iters: int
    _cav_missing: object = None
    _push: object = None

    def result(self) -> np.ndarray:
        if not bool(self._converged):
            raise ConvergenceError(
                f"reachability did not converge within {self._max_iters} "
                "iterations (graph deeper than the dispatch budget)"
            )
        return np.asarray(self._out)[: self._q]

    def iterations(self) -> int:
        return int(self._iters)

    def push_steps(self) -> int:
        return 0 if self._push is None else int(self._push)

    def caveats_missing(self) -> int:
        return 0 if self._cav_missing is None else int(self._cav_missing)


def _apply_program(cg: CompiledGraph, V, programs=None):
    """Recompute permission slot ranges from their expressions (all of
    cg's programs, or an explicit subset). V is [B, rows, LANE]; every
    range offset/size is a multiple of LANE, so a range is a row-aligned
    static slice along axis 1."""

    def ev(expr: Expr, p: _PermProgram):
        if isinstance(expr, Nil):
            return jnp.zeros((V.shape[0], p.size // LANE, LANE),
                             dtype=V.dtype)
        if isinstance(expr, (RelationRef, Arrow)):
            off = p.leaf_off[expr]
            return jax.lax.dynamic_slice_in_dim(
                V, off // LANE, p.size // LANE, axis=1)
        if isinstance(expr, Union):
            out = ev(expr.operands[0], p)
            for e in expr.operands[1:]:
                out = out | ev(e, p)
            return out
        if isinstance(expr, Intersect):
            out = ev(expr.operands[0], p)
            for e in expr.operands[1:]:
                out = out & ev(e, p)
            return out
        if isinstance(expr, Exclude):
            return ev(expr.base, p) & (ev(expr.subtract, p) ^ 1)
        raise TypeError(f"unknown expr {expr!r}")

    for p in (cg.programs if programs is None else programs):
        V = jax.lax.dynamic_update_slice_in_dim(
            V, ev(p.expr, p), p.dst_off // LANE, axis=1)
    return V


def _seed_base(cg: CompiledGraph, seeds):
    """Seed the [B, rows, LANE] state from subject/wildcard slot pairs and
    run the permission programs once. The single source of the layout
    invariants (rows = M/LANE + trash row; trash row stays 0 so unknown
    subjects seed nothing) — both the single-chip and sharded fixpoints
    build their base here."""
    B = seeds.shape[0]
    rows = cg.M // LANE + 1  # + trash row (slots M .. M+LANE-1)
    Mp = rows * LANE
    brange = jnp.arange(B, dtype=jnp.int32)
    base = jnp.zeros((B, Mp), dtype=jnp.uint8)
    base = base.at[brange, seeds[:, 0]].max(1)
    base = base.at[brange, seeds[:, 1]].max(1)
    base = base.at[:, cg.M:].set(0)
    return _apply_program(cg, base.reshape(B, rows, LANE))


def _run(cg: "RunMeta", blocks, blocks_bits, src, dst, exp_rel, cav,
         dsrc, ddst, dexp, dcav, cav_static, cav_req,
         seeds, q_slots, q_batch, now_rel, crossover, *,
         max_iters: int, q_contig_len: int = 0, q_contig_rows: int = 1):
    """The jitted stratified fixpoint. V layout: [B, rows, LANE] uint8 —
    the slot space rides the lane axis so a B=1 query streams exactly M
    bytes per elementwise pass instead of a lane-padded 128x that; slot s
    lives at (s // LANE, s % LANE) and every range is row-aligned.

    Schedule (see _stratify): only the cyclic CORE (level 0) iterates in
    the while_loop; each acyclic level k=1..n_levels is then applied
    exactly once — its ranges' in-edges all live at level k and their
    sources are already final. In kube-shaped graphs this keeps the
    dominant per-pod blocks out of the loop entirely.

    Every hop is ONE call into the masked-semiring primitive
    (ops/semiring.propagate) — the same primitive the shard_map body
    uses — with the ``(exp > now) ∧ cav_ok[row]`` edge-activation mask
    computed exactly once per dispatch (semiring.edge_activation) and
    fused into the multiply. The caveat VM evaluates every instance's
    tri-state once up front when the graph carries caveat instances
    (cg.cav_rows > 1); caveated edges never enter dense blocks
    (compile_graph routes them residual, like expiring edges).
    ``crossover`` is the traced push/pull threshold (CompiledGraph
    .spmm_crossover): the per-iteration mode branch is a lax.cond on
    traced occupancy, so neither tuning nor the runtime flip
    re-specializes."""
    B = seeds.shape[0]
    rows = cg.M // LANE + 1  # + trash row (slots M .. M+LANE-1)
    Mp = rows * LANE
    if cg.cav_rows > 1:
        from ..caveats.vm import eval_caveats

        cav_ok, cav_missing = eval_caveats(
            cg.caveats, cav_static, cav_req, cg.cav_rows)
    else:
        cav_ok = None
        cav_missing = jnp.int32(0)
    # fused edge activation, once per dispatch (not per hop/level)
    act = semiring.edge_activation(exp_rel, now_rel, cav, cav_ok)
    dact = semiring.edge_activation(dexp, now_rel, dcav, cav_ok)
    base = _seed_base(cg, seeds)
    baseflat = base.reshape(B, Mp)
    bounds = cg.res_level_bounds
    core_progs = [p for p in cg.programs if p.level == 0]

    def level_slice(k):
        lo, hi = bounds[k], bounds[k + 1]
        return src[lo:hi], dst[lo:hi], act[lo:hi]

    def prop_level(V, k):
        Vflat = V.reshape(B, Mp)
        s, d, a = level_slice(k)
        occ = semiring.frontier_occupancy(Vflat)
        return semiring.propagate(
            cg.blocks, blocks, blocks_bits, s, d, a,
            dsrc, ddst, dact, Vflat, occ, crossover,
            level=k, mode=cg.spmm_mode)

    def step(V):
        prop, is_push = prop_level(V, 0)
        return _apply_program(
            cg, prop.reshape(B, rows, LANE) | base, core_progs), is_push

    def cond(state):
        V, prev_changed, it, _ = state
        return prev_changed & (it < max_iters)

    def body(state):
        V, _, it, n_push = state
        V2, is_push = step(V)
        return V2, jnp.any(V2 != V), it + 1, n_push + is_push

    V, still_changing, iters, n_push = jax.lax.while_loop(
        cond, body, (base, jnp.bool_(True), 0, jnp.int32(0)))
    # acyclic levels: one application each. No phase may be skipped —
    # incremental delta edges can target any level and only this phase's
    # re-application establishes their values. The merge writes only the
    # level's (row-aligned) slot ranges, so finalized lower levels are
    # untouched and no dense masks exist anywhere.
    for k in range(1, cg.n_levels + 1):
        progs_k = [p for p in cg.programs if p.level == k]
        prop, is_push = prop_level(V, k)
        n_push = n_push + is_push
        propb = prop | baseflat
        Vflat = V.reshape(B, Mp)
        for off, size in cg.level_ranges[k - 1]:
            Vflat = jax.lax.dynamic_update_slice(
                Vflat, jax.lax.dynamic_slice(propb, (0, off), (B, size)),
                (0, off))
        V = _apply_program(cg, Vflat.reshape(B, rows, LANE), progs_k)
    # still_changing at loop exit means we hit max_iters before convergence;
    # surface it so the host can raise instead of silently denying
    if q_contig_len:
        # contiguous query window (q_slots/q_batch are scalars: start slot
        # and start row): a dynamic_slice streams the window at HBM rate,
        # where the general fancy-index gather below is latency-bound
        # random access — on a v5e chip that gather was 31% of the whole
        # query's device time for the list-filter shape (which always
        # reads one type's full, contiguous permission range).
        # q_contig_rows > 1 is the fused-batch grid (engine/batcher.py:
        # R same-window rows); [R, L] row-major flatten is exactly the
        # concatenated per-row query order, so no re-mapping is needed.
        out = jax.lax.dynamic_slice(
            V.reshape(B, Mp), (q_batch, q_slots),
            (q_contig_rows, q_contig_len)
        ).reshape(q_contig_rows * q_contig_len).astype(jnp.bool_)
    else:
        out = V.reshape(B, Mp)[q_batch, q_slots].astype(jnp.bool_)
    return out, jnp.logical_not(still_changing), iters, n_push, cav_missing


# ---------------------------------------------------------------------------
# Compilation: (schema, snapshot) -> CompiledGraph
# ---------------------------------------------------------------------------


def _topo_permissions(defn) -> list[str]:
    """Topologically order a definition's permissions by their intra-type
    RelationRef dependencies (cross-type and cyclic deps are resolved by the
    outer fixpoint; within a pass we just avoid reading an obviously stale
    sibling where possible)."""
    deps: dict[str, set] = {}
    for name, perm in defn.permissions.items():
        refs = set()

        def walk(e):
            if isinstance(e, RelationRef) and e.name in defn.permissions:
                refs.add(e.name)
            elif isinstance(e, (Union, Intersect)):
                for o in e.operands:
                    walk(o)
            elif isinstance(e, Exclude):
                walk(e.base)
                walk(e.subtract)

        walk(perm.expr)
        deps[name] = refs
    out: list[str] = []
    seen: set = set()

    def visit(n, path):
        if n in seen or n in path:
            return
        for d in sorted(deps[n]):
            visit(d, path | {n})
        seen.add(n)
        out.append(n)

    for n in sorted(deps):
        visit(n, set())
    return out


def compile_graph(schema: Schema, snapshot: Snapshot,
                  delta_capacity: int = DELTA_CAPACITY) -> CompiledGraph:
    """Compile a store snapshot into device-ready slot-space form.

    Everything here is vectorized numpy over the snapshot's columnar arrays
    — no per-relationship Python loops — so 10M-edge graphs compile in
    seconds on the host.

    ``delta_capacity`` preallocates the fixed-capacity delta overlay
    (``incremental_update``): its length is part of the jit signature, so
    overlay appends never re-specialize, and running out of slots is a
    compaction/back-pressure signal (engine/compaction.py) instead of a
    growth event.
    """
    types_in = snapshot.types
    rels_in = snapshot.relations
    cols = snapshot.cols

    # ---- slot layout ----
    slot_offset: dict[tuple, int] = {}
    type_sizes: dict[str, int] = {}
    arrow_terms: dict[tuple, list[Arrow]] = {}  # (type, perm) -> arrows in order
    off = 0
    for tname in sorted(schema.definitions):
        d = schema.definitions[tname]
        tid = types_in.lookup(tname)
        n = len(snapshot.objects[tid]) if tid is not None and tid in snapshot.objects \
            else 2
        # bucket-pad the per-type object space so slot offsets (and thus the
        # jit signature) stay stable as objects are interned within a
        # bucket; the LANE floor keeps every slot range row-aligned in the
        # [B, rows, LANE] state layout
        n = _next_bucket(max(n, 2), LANE)
        type_sizes[tname] = n
        slot_offset[(tname, SELF_REL)] = off
        off += n
        for rname in sorted(d.relations):
            slot_offset[(tname, rname)] = off
            off += n
        for pname in sorted(d.permissions):
            arrows: list[Arrow] = []

            def collect(e):
                if isinstance(e, Arrow):
                    arrows.append(e)
                elif isinstance(e, (Union, Intersect)):
                    for o in e.operands:
                        collect(o)
                elif isinstance(e, Exclude):
                    collect(e.base)
                    collect(e.subtract)

            collect(d.permissions[pname].expr)
            arrow_terms[(tname, pname)] = arrows
            for k in range(len(arrows)):
                slot_offset[(tname, f"__arrow_{pname}_{k}")] = off
                off += n
        for pname in sorted(d.permissions):
            slot_offset[(tname, pname)] = off
            off += n
    M = off

    # ---- store-id -> offset lookup tables ----
    n_st = len(types_in)
    n_sr = len(rels_in)
    self_off = np.full(n_st + 1, -1, dtype=np.int64)
    rel_off = np.full((n_st + 1, n_sr + 1), -1, dtype=np.int64)  # writable rels
    relperm_off = np.full((n_st + 1, n_sr + 1), -1, dtype=np.int64)
    for tname, d in schema.definitions.items():
        tid = types_in.lookup(tname)
        if tid is None:
            continue
        self_off[tid] = slot_offset[(tname, SELF_REL)]
        for rname in d.relations:
            rid = rels_in.lookup(rname)
            if rid is not None:
                rel_off[tid, rid] = slot_offset[(tname, rname)]
                relperm_off[tid, rid] = slot_offset[(tname, rname)]
        for pname in d.permissions:
            rid = rels_in.lookup(pname)
            if rid is not None:
                relperm_off[tid, rid] = slot_offset[(tname, pname)]

    # ---- edges ----
    srcs: list[np.ndarray] = []
    dsts: list[np.ndarray] = []
    exps: list[np.ndarray] = []
    cavs: list[np.ndarray] = []
    base_time = time.time()
    exp_rel_all = (cols.exp - base_time).astype(np.float32)

    # caveat instance table: one VM row per distinct (caveat, context)
    # pair among live tuples; every edge derived from a caveated tuple
    # (direct / userset / arrow alike) carries its instance row so the
    # traced fixpoint can gate it on the per-dispatch tri-state
    from ..caveats.vm import build_caveat_table

    cav_ids = cols.cav.astype(np.int64)
    used_cavs = np.unique(cav_ids[cav_ids > 0])
    caveat_table = build_caveat_table(
        getattr(schema, "caveat_defs", None) or {},
        getattr(snapshot, "caveat_instances", None) or [("", "")],
        used_cavs)
    cav_row_all = caveat_table.inst_row[cav_ids]

    rt = cols.rt.astype(np.int64)
    st = cols.st.astype(np.int64)
    rl = cols.rl.astype(np.int64)
    srl = cols.srl.astype(np.int64)

    dst_all = rel_off[rt, rl] + cols.rid  # -1-based stays negative
    dst_valid = rel_off[rt, rl] >= 0

    # direct tuples (includes wildcard subjects: wildcard object index is 1)
    m = (srl == 0) & dst_valid & (self_off[st] >= 0)
    srcs.append(self_off[st[m]] + cols.sid[m])
    dsts.append(dst_all[m])
    exps.append(exp_rel_all[m])
    cavs.append(cav_row_all[m])

    # userset tuples: src is the subject's (type, relation|permission) slot
    us_off = relperm_off[st, srl]
    m = (srl != 0) & dst_valid & (us_off >= 0) & (cols.sid != WILDCARD_IDX)
    srcs.append(us_off[m] + cols.sid[m])
    dsts.append(dst_all[m])
    exps.append(exp_rel_all[m])
    cavs.append(cav_row_all[m])

    # arrow term edges
    arrow_maps: list = []
    for (tname, pname), arrows in arrow_terms.items():
        if not arrows:
            continue
        tid = types_in.lookup(tname)
        if tid is None:
            continue
        for k, a in enumerate(arrows):
            ts_id = rels_in.lookup(a.tupleset)
            if ts_id is None:
                continue
            term_off = slot_offset[(tname, f"__arrow_{pname}_{k}")]
            # per-subject-type offset of the arrow target
            tgt_off = np.full(n_st + 1, -1, dtype=np.int64)
            d = schema.definitions[tname]
            for asub in d.relations[a.tupleset].allowed:
                if asub.relation:
                    continue  # arrows walk concrete subjects only
                sub_tid = types_in.lookup(asub.type)
                if sub_tid is None:
                    continue
                if schema.definitions[asub.type].relation_or_permission(a.target):
                    tgt_off[sub_tid] = slot_offset[(asub.type, a.target)]
            arrow_maps.append((int(tid), int(ts_id), term_off, tgt_off))
            m = (
                (rt == tid) & (rl == ts_id) & (srl == 0)
                & (tgt_off[st] >= 0) & (cols.sid != WILDCARD_IDX)
            )
            srcs.append(tgt_off[st[m]] + cols.sid[m])
            dsts.append(term_off + cols.rid[m])
            exps.append(exp_rel_all[m])
            cavs.append(cav_row_all[m])

    src = np.concatenate(srcs) if srcs else np.empty(0, dtype=np.int64)
    dst = np.concatenate(dsts) if dsts else np.empty(0, dtype=np.int64)
    exp = np.concatenate(exps) if exps else np.empty(0, dtype=np.float32)
    cav = np.concatenate(cavs) if cavs else np.empty(0, dtype=np.int64)

    order = native.sort_perm(dst)
    if order is None:
        order = np.argsort(dst, kind="stable")
    src, dst, exp, cav = src[order], dst[order], exp[order], cav[order]

    n_edges = len(src)
    E_pad = _next_bucket(max(n_edges, 1))
    src_p = np.full(E_pad, M, dtype=np.int32)
    dst_p = np.full(E_pad, M, dtype=np.int32)
    exp_p = np.full(E_pad, -np.inf, dtype=np.float32)
    cav_p = np.zeros(E_pad, dtype=np.int32)
    src_p[:n_edges] = src
    dst_p[:n_edges] = dst
    exp_p[:n_edges] = exp
    cav_p[:n_edges] = cav

    # ---- elementwise programs ----
    programs: list[_PermProgram] = []
    for tname in sorted(schema.definitions):
        d = schema.definitions[tname]
        n = type_sizes[tname]
        for pname in _topo_permissions(d):
            arrows = arrow_terms[(tname, pname)]
            leaf_off: dict = {}
            arrow_seen = 0

            # loop vars bound as defaults: the closure is invoked within
            # this iteration, but the explicit binding keeps it correct
            # even if it ever escapes (flake8-bugbear B023)
            def resolve(e, tname=tname, pname=pname):
                nonlocal arrow_seen
                if isinstance(e, RelationRef):
                    leaf_off[e] = slot_offset[(tname, e.name)]
                elif isinstance(e, Arrow):
                    # nth arrow occurrence maps to its own term range
                    leaf_off[e] = slot_offset[
                        (tname, f"__arrow_{pname}_{arrow_seen}")
                    ]
                    arrow_seen += 1
                elif isinstance(e, (Union, Intersect)):
                    for o in e.operands:
                        resolve(o)
                elif isinstance(e, Exclude):
                    resolve(e.base)
                    resolve(e.subtract)

            expr = d.permissions[pname].expr
            resolve(expr)
            programs.append(
                _PermProgram(slot_offset[(tname, pname)], n, expr, leaf_off)
            )

    # ---- stratification + dense/residual split (single-chip path) ----
    # ranges: every (type, rel) slot range, ascending; edges map to a
    # (dst range, src range) pair by binary search
    range_items = sorted(slot_offset.items(), key=lambda kv: kv[1])
    offs = np.asarray([o for _, o in range_items], dtype=np.int64)
    sizes = np.asarray(
        [type_sizes[t] for (t, _), _ in range_items], dtype=np.int64
    )
    if n_edges:
        dst_rid = np.searchsorted(offs, dst, side="right") - 1
        src_rid = np.searchsorted(offs, src, side="right") - 1
    else:
        dst_rid = src_rid = np.empty(0, dtype=np.int64)

    # Dense-pair decisions come BEFORE stratification: a dense SELF-pair
    # (recursive relation like `group#member: group#member`) with no
    # expiring edges gets its block replaced by the reflexive-transitive
    # closure, which satisfies the self-dependency in ONE application —
    # so _stratify may peel the range instead of iterating it with the
    # core. Nested-group workloads (BASELINE config 3) then converge
    # without core iterations at all.
    dense_sel: dict[int, np.ndarray] = {}  # pair key -> edge indices
    res_parts: list[np.ndarray] = []
    closure_rids: set[int] = set()
    closure_coo: dict[int, tuple] = {}  # self range id -> closured COO
    if n_edges:
        never_expires = exp == np.inf
        # caveated edges ride the residual path like expiring edges:
        # their activation is a per-dispatch condition, and a dense
        # (let alone closured) block cell cannot carry one
        special = (~never_expires) | (cav != 0)
        key = dst_rid * len(offs) + src_rid
        key = np.where(~special, key, -1)
        uniq, inv, counts = np.unique(key, return_inverse=True,
                                      return_counts=True)
        expiring_pairs = (set(np.unique(
            dst_rid[special] * len(offs) + src_rid[special]
        ).tolist()) if special.any() else set())
        for ui, (k, cnt) in enumerate(zip(uniq.tolist(), counts.tolist())):
            sel = np.flatnonzero(inv == ui)
            if k < 0:
                res_parts.append(sel)
                continue
            d_rid, s_rid = divmod(k, len(offs))
            n_dst, n_src = int(sizes[d_rid]), int(sizes[s_rid])
            cells = n_dst * n_src
            if (cnt < DENSE_MIN_EDGES or cells > DENSE_MAX_CELLS
                    or (cells > DENSE_MIN_CELLS
                        and cnt / cells < DENSE_MIN_DENSITY)):
                res_parts.append(sel)
                continue
            dense_sel[k] = sel
            if d_rid == s_rid and k not in expiring_pairs:
                coo = _closure_pairs(
                    (dst[sel] - offs[d_rid]).astype(np.int32),
                    (src[sel] - offs[s_rid]).astype(np.int32), n_dst)
                if coo is not None:
                    closure_rids.add(d_rid)
                    closure_coo[d_rid] = coo

    level_map, n_levels = _stratify(offs, src_rid, dst_rid, programs,
                                    ignore_self=frozenset(closure_rids))

    # Retain the range-granularity adjacency for tiered demand closure:
    # every (src range, dst range) pair the FULL edge set crosses (the
    # rids above were computed before the dense split, so block edges
    # are covered) plus each program's leaf -> permission edges. Self
    # pairs stay in — unlike _stratify, reachability wants them.
    adj_pairs: set = set()
    if n_edges:
        for p in np.unique(
                src_rid.astype(np.int64) * len(offs) + dst_rid).tolist():
            adj_pairs.add(divmod(p, len(offs)))
    for p in programs:
        p_rid = _range_id(offs, p.dst_off)
        for off_ in set(p.leaf_off.values()):
            adj_pairs.add((_range_id(offs, off_), p_rid))
    range_adj = tuple(sorted(adj_pairs))
    if closure_rids:
        # Levels are DOUBLED so a peeled closured range gets two ordered
        # phases at its position in the topo order: odd phase 2k-1
        # applies the range's in-edges (+ normal blocks + programs) and
        # merges; even phase 2k applies only closure blocks, whose
        # diagonal re-gathers the freshly merged values and whose closure
        # cells complete every multi-hop chain. Without closured blocks
        # the schedule keeps its original single phase per level.
        range_levels = np.asarray(
            [0 if level_map[r] == 0 else 2 * level_map[r] - 1
             for r in range(len(offs))], dtype=np.int32)
        n_levels *= 2
    else:
        range_levels = np.asarray(
            [level_map[r] for r in range(len(offs))], dtype=np.int32)
    for p in programs:
        p.level = int(range_levels[_range_id(offs, p.dst_off)])

    blocks: list[_BlockMeta] = []
    if n_edges:
        edge_level = range_levels[dst_rid]
        for k, sel in dense_sel.items():
            d_rid, s_rid = divmod(k, len(offs))
            lvl = int(range_levels[d_rid])
            if d_rid == s_rid and d_rid in closure_rids:
                dl, sl = closure_coo[d_rid]
                blocks.append(_BlockMeta(
                    dst_off=int(offs[d_rid]), n_dst=int(sizes[d_rid]),
                    src_off=int(offs[s_rid]), n_src=int(sizes[s_rid]),
                    dst_local=dl, src_local=sl,
                    level=lvl + 1 if lvl else 0, closured=True,
                    base_dst_local=(dst[sel] - offs[d_rid]).astype(np.int32),
                    base_src_local=(src[sel] - offs[s_rid]).astype(np.int32),
                ))
            else:
                blocks.append(_BlockMeta(
                    dst_off=int(offs[d_rid]), n_dst=int(sizes[d_rid]),
                    src_off=int(offs[s_rid]), n_src=int(sizes[s_rid]),
                    dst_local=(dst[sel] - offs[d_rid]).astype(np.int32),
                    src_local=(src[sel] - offs[s_rid]).astype(np.int32),
                    level=lvl,
                ))
    res_idx = (np.sort(np.concatenate(res_parts)) if res_parts
               else np.empty(0, dtype=np.int64))

    # padded host residual views ordered by (level, dst) — the traced
    # program slices the residual per level (res_level_bounds), each slice
    # dst-sorted for segment_max's indices_are_sorted and padded to its
    # own power-of-two bucket so the bounds (part of the jit signature)
    # stay stable as edge counts drift between recompiles
    n_res = len(res_idx)
    if n_res:
        res_lvl = edge_level[res_idx]
        order = np.lexsort((dst[res_idx], res_lvl))
        res_idx = res_idx[order]
        res_lvl = res_lvl[order]
        counts_per_level = np.bincount(res_lvl, minlength=n_levels + 1)
    else:
        counts_per_level = np.zeros(n_levels + 1, dtype=np.int64)
    pads = [_next_bucket(max(int(c), 1)) for c in counts_per_level]
    res_level_bounds = tuple(int(x) for x in np.concatenate(
        [[0], np.cumsum(pads)]))
    res_src = np.full(res_level_bounds[-1], M, dtype=np.int32)
    res_dst = np.full(res_level_bounds[-1], M, dtype=np.int32)
    res_exp = np.full(res_level_bounds[-1], -np.inf, dtype=np.float32)
    res_cav = np.zeros(res_level_bounds[-1], dtype=np.int32)
    pos = 0
    for k in range(n_levels + 1):
        n_k = int(counts_per_level[k])
        lo = res_level_bounds[k]
        sel = res_idx[pos:pos + n_k]
        res_src[lo:lo + n_k] = src_p[sel]
        res_dst[lo:lo + n_k] = dst_p[sel]
        res_exp[lo:lo + n_k] = exp_p[sel]
        res_cav[lo:lo + n_k] = cav_p[sel]
        pos += n_k

    # fixed-capacity delta overlay: preallocated trash-padded segments the
    # incremental path appends into IN PLACE (watermarked by n_delta /
    # n_dead on each revision view); sized once so the jit signature never
    # moves under write churn
    # NO gauge writes here: engine_delta_occupancy belongs to the engine
    # layer (_publish_graph_gauges / incremental_update) — a background
    # compactor's off-path compile must not zero the LIVE overlay's
    # occupancy reading while it is full and shedding
    cap = max(int(delta_capacity), 64)
    return CompiledGraph(
        schema=schema,
        revision=snapshot.revision,
        base_time=base_time,
        M=M,
        slot_offset=slot_offset,
        type_sizes=type_sizes,
        src=src_p,
        dst=dst_p,
        exp_rel=exp_p,
        n_edges=n_edges,
        programs=programs,
        blocks=blocks,
        res_idx=res_idx,
        delta_src=np.full(cap, M, dtype=np.int32),
        delta_dst=np.full(cap, M, dtype=np.int32),
        delta_exp=np.full(cap, -np.inf, dtype=np.float32),
        delta_cav=np.zeros(cap, dtype=np.int32),
        n_delta=0,
        dead_pairs=None,
        n_dead=0,
        delta_cap=cap,
        delta_pos={},
        dead_set=set(),
        dead_buf=np.zeros((cap, 2), dtype=np.int64),
        host_lock=threading.Lock(),
        block_codes={},
        res_src=res_src,
        res_dst=res_dst,
        res_exp=res_exp,
        res_cav=res_cav,
        caveats=caveat_table,
        res_level_bounds=res_level_bounds,
        n_levels=n_levels,
        range_levels=range_levels,
        range_offs=offs,
        block_index={(b.dst_off, b.src_off): i
                     for i, b in enumerate(blocks)},
        self_off=self_off,
        rel_off=rel_off,
        relperm_off=relperm_off,
        arrow_maps=arrow_maps,
        range_adj=range_adj,
    )


# ---------------------------------------------------------------------------
# Incremental updates: (CompiledGraph, write delta) -> CompiledGraph
# ---------------------------------------------------------------------------


def _edges_for_tuple(cg: CompiledGraph, store, rel):
    """Slot-space (src, dst) edges for one relationship, mirroring the
    vectorized extraction in compile_graph (direct / userset / arrow).
    Returns None when the tuple cannot be mapped onto the existing slot
    layout (new type/relation id beyond the compile-time tables, or an
    object interned past its type's padded bucket) — the caller falls back
    to a full recompile."""
    tid = store.types.lookup(rel.resource_type)
    stid = store.types.lookup(rel.subject_type)
    rl = store.relations.lookup(rel.relation)
    srl = store.relations.lookup(rel.subject_relation or "")
    if None in (tid, stid, rl, srl):
        return None
    # the lookup tables carry a defensive +1 slack row, so the covered id
    # range is [0, len-1): an id interned AFTER compile lands on the slack
    # row's -1 and must force a recompile, not read as "no edge"
    n_types = len(cg.self_off) - 1
    n_rels = cg.rel_off.shape[1] - 1
    if tid >= n_types or stid >= n_types or rl >= n_rels or srl >= n_rels:
        return None  # interned after compile: tables don't cover it
    r_objs = store.objects.get(tid)
    s_objs = store.objects.get(stid)
    rid = r_objs.lookup(rel.resource_id) if r_objs else None
    sid = s_objs.lookup(rel.subject_id) if s_objs else None
    if rid is None or sid is None:
        return None
    if rid >= cg.type_sizes.get(rel.resource_type, 0) \
            or sid >= cg.type_sizes.get(rel.subject_type, 0):
        return None  # object bucket overflow: slot layout must grow
    dst_off = int(cg.rel_off[tid, rl])
    if dst_off < 0:
        return []  # not a writable relation slot (compile drops these too)
    dst = dst_off + rid
    edges: list[tuple[int, int]] = []
    if srl == 0:
        so = int(cg.self_off[stid])
        if so >= 0:  # wildcard subjects included (index 1)
            edges.append((so + sid, dst))
    elif sid != WILDCARD_IDX:
        uo = int(cg.relperm_off[stid, srl])
        if uo >= 0:
            edges.append((uo + sid, dst))
    if srl == 0 and sid != WILDCARD_IDX:
        for a_tid, ts_id, term_off, tgt_off in cg.arrow_maps:
            if a_tid == tid and ts_id == rl and int(tgt_off[stid]) >= 0:
                edges.append((int(tgt_off[stid]) + sid, term_off + rid))
    return edges


def _level_order_ok(cg: CompiledGraph, src: int, dst: int) -> bool:
    """A delta edge is compatible with the frozen stratification iff its
    source finalizes before (or iterates with) its destination: both in
    the iterated core, or level(src) < level(dst). Violations — a
    first-ever dependency direction between two ranges — need a
    re-stratifying full recompile."""
    if cg.range_levels is None:
        return True  # unstratified graph: single full fixpoint
    offs = cg.range_offs
    ls = int(cg.range_levels[_range_id(offs, src)])
    ld = int(cg.range_levels[_range_id(offs, dst)])
    return (ls == 0 and ld == 0) or ls < ld


def _pair_block(cg: CompiledGraph, src: int, dst: int):
    """Dense-block index covering a (src, dst) slot pair, or None."""
    if not cg.block_index:
        return None
    offs = cg.range_offs
    d_rid = int(np.searchsorted(offs, dst, side="right")) - 1
    s_rid = int(np.searchsorted(offs, src, side="right")) - 1
    return cg.block_index.get((int(offs[d_rid]), int(offs[s_rid])))


def _res_positions(cg: CompiledGraph, src: int, dst: int) -> list[int]:
    """Base-residual positions holding the (src, dst) edge. The residual
    is ordered by (level, dst), so each level slice is binary-searched
    and its per-dst run scanned for the src match."""
    bounds = cg.res_level_bounds or (0, len(cg.res_dst))
    out: list[int] = []
    for k in range(len(bounds) - 1):
        b0, b1 = bounds[k], bounds[k + 1]
        if b0 == b1:
            continue
        lo = b0 + int(np.searchsorted(cg.res_dst[b0:b1], dst, side="left"))
        hi = b0 + int(np.searchsorted(cg.res_dst[b0:b1], dst, side="right"))
        if lo < hi:
            out.extend(
                (lo + np.flatnonzero(cg.res_src[lo:hi] == src)).tolist())
    return out




def _block_base_codes(cg: CompiledGraph, b: int) -> np.ndarray:
    """Sorted ``dst_local * n_src + src_local`` codes of a closured
    block's BASE edges, cached on the shared ``block_codes`` dict (keyed
    by block index, validated against the block object's identity so a
    re-close invalidates the entry). O(block log block) once per base
    edge-set, O(log block) per membership probe after that."""
    bm = cg.blocks[b]
    cache = cg.block_codes
    if cache is not None:
        ent = cache.get(b)
        if ent is not None and ent[0] == id(bm):
            return ent[1]
    codes = np.sort(bm.base_dst_local.astype(np.int64) * bm.n_src
                    + bm.base_src_local)
    if cache is not None:
        cache[b] = (id(bm), codes)
    return codes


def incremental_update(cg: CompiledGraph, records, new_revision: int,
                       store) -> Optional[CompiledGraph]:
    """Apply a write delta — ``records`` is an ordered list of
    ``(is_delete, Relationship)`` derived from the store watch log since
    cg.revision — to a compiled graph without recompiling.

    The delta overlay is a FIXED-CAPACITY device-resident COO tail shared
    (host side) by every incremental descendant of one compiled base:

    - a new edge takes the next free overlay slot — a host write plus a
      functional ``.at[slot].set`` on the resident device arrays, O(write)
      regardless of how much delta has accumulated since the last
      compaction (the previous implementation rebuilt a dict + re-sorted
      + re-uploaded the whole segment per write);
    - a re-touch/delete of an overlay edge updates its slot's expiration
      in place (slots are reused, so touch/delete churn on the same pairs
      never grows occupancy);
    - a touched/deleted BASE edge is killed where it lives (residual
      expiration forced to -inf, dense-block cell cleared) and recorded
      once in the append-only dead ledger (``dead_buf``/``dead_set``) for
      ShardedGraph replay and lazy device builds.

    Capacity is static — part of the jit signature — so appends NEVER
    re-specialize; running out of slots (or dead-ledger room) declines the
    update, which the engine turns into compaction back-pressure rather
    than a growth event. Returns a new CompiledGraph view sharing the
    overlay (per-revision immutability lives in the n_delta/n_dead
    watermarks and the functional device arrays), or None when the delta
    cannot be expressed against the frozen layout — every decline is
    counted in ``engine_graph_incremental_fallback_total{reason}``.

    Keeps the fully-consistent-read contract (reference
    pkg/authz/check.go:42-44) at O(write) instead of O(graph) per write.
    """
    if cg.res_src is None or cg.self_off is None or cg.delta_pos is None \
            or cg.delta_src is None or cg.dead_buf is None \
            or cg.delta_cav is None:
        _fallback("unstratified")
        return None
    if len(records) > MAX_DELTA_RECORDS:
        _fallback("overflow")
        return None

    delta_pos = cg.delta_pos
    dead_set = cg.dead_set

    # ---- plan (NO mutation): a fallback must leave the shared overlay
    # exactly as it was — the caller recompiles from a fresh snapshot and
    # in-flight queries keep serving the untouched current view ----------
    appends: dict = {}  # pair -> (exp, cav row) for a new overlay slot
    updates: dict = {}  # overlay slot -> (new exp, cav row | None=keep)
    res_kill: list[int] = []
    block_cells: dict[int, dict[tuple[int, int], int]] = {}
    new_dead: list[tuple[int, int]] = []
    dead_seen: set = set()
    # closured blocks whose BASE edges lost pairs: re-closed wholesale
    reclose: dict[int, set] = {}  # block idx -> local (dst, src) pairs
    # new (caveat, context) instance rows reserved this batch — applied
    # to the shared tables only at commit (caveats/vm.py plan_append)
    planned_inst: dict = {}

    for is_delete, relationship in records:
        edges = _edges_for_tuple(cg, store, relationship)
        if edges is None:
            _fallback("layout")
            return None
        cav_row = 0
        if not is_delete and relationship.caveat:
            # conditional grant: resolve (caveat, context) to a VM
            # instance row — an existing one, or a reserved spare row in
            # the caveat's padded bucket. No tape for the caveat (first
            # caveated tuple ever) or no spare row: the instance tables
            # must re-shape, which is a full recompile.
            table = cg.caveats
            ctx = relationship.caveat_context or ""
            row = (table.lookup_row(relationship.caveat, ctx)
                   if table is not None else None)
            if row is None and table is not None:
                row = table.plan_append(relationship.caveat, ctx,
                                        planned_inst)
            if row is None:
                _fallback("caveat")
                return None
            cav_row = row
        if not is_delete:
            for src, dst in edges:
                if relationship.expiration is not None \
                        or relationship.caveat:
                    b_ = _pair_block(cg, src, dst)
                    if b_ is not None and cg.blocks[b_].closured:
                        # a touch attaching an expiration (or a caveat)
                        # de-qualifies the pair from closure entirely
                        # (conditional/expiring edges must ride the
                        # residual path — a derived closure cell would
                        # serve the grant unconditionally). Classified
                        # BEFORE the level-order check: a closured
                        # self-block lifts its range out of the iterated
                        # core, so the generic check would fire first
                        # and miscount this as an inversion.
                        _fallback("closured-expiry"
                                  if relationship.expiration is not None
                                  else "closured-caveat")
                        return None
                if not _level_order_ok(cg, src, dst):
                    # the new edge would invert the frozen stratification
                    # (e.g. a first-ever dependency creating a cycle
                    # across levels): re-stratify via a full recompile
                    _fallback("stratification-inversion")
                    return None
        for src, dst in edges:
            pair = (src, dst)
            b = _pair_block(cg, src, dst)
            bm = cg.blocks[b] if b is not None else None
            if bm is not None and bm.closured:
                # (expiration-attaching touches on closured pairs already
                # fell back in the pre-classification loop above)
                if is_delete:
                    # closure cells are DERIVED reachability — clearing
                    # one cell would leave multi-hop products of the
                    # deleted edge alive (over-allow) and could kill
                    # cells still justified by alternative paths
                    # (under-allow). Instead RE-CLOSE the block from its
                    # base edges minus the deleted pair, O(block); the
                    # pair must NOT enter the dead ledger/block_cells —
                    # the recomputed closure is the sole truth.
                    dl_ = int(dst - bm.dst_off)
                    sl_ = int(src - bm.src_off)
                    codes = _block_base_codes(cg, b)
                    code = dl_ * bm.n_src + sl_
                    p_ = int(np.searchsorted(codes, code))
                    if p_ < len(codes) and codes[p_] == code:
                        reclose.setdefault(b, set()).add((dl_, sl_))
                    # overlay copy (delta-only or re-added): killing the
                    # slot is the rest of the delete
                    slot = delta_pos.get(pair)
                    if slot is not None:
                        updates[slot] = (float("-inf"), None)
                    appends.pop(pair, None)
                    continue
            # invalidate everywhere the BASE edge may live (once per pair
            # across the base's whole incremental lifetime — the dead
            # ledger makes the kill idempotent and the host arrays are
            # mutated in place, so an already-dead pair costs nothing):
            # dense-block cell cleared, residual expiration forced stale,
            # and the pair recorded so ShardedGraph can replay the kill
            if pair not in dead_set and pair not in dead_seen:
                dead_seen.add(pair)
                new_dead.append(pair)
                if bm is not None:
                    block_cells.setdefault(b, {})[
                        (dst - bm.dst_off, src - bm.src_off)] = 0
                res_kill.extend(_res_positions(cg, src, dst))
            slot = delta_pos.get(pair)
            if is_delete:
                if slot is not None:
                    updates[slot] = (float("-inf"), None)
                appends.pop(pair, None)
                continue
            # adds (including re-touches of block-covered pairs) always
            # land in the overlay — one ledger for both the single-chip
            # and sharded consumers; base copies are only ever cleared.
            # The caveat row rides the slot alongside the expiration:
            # a touch may attach, replace, or strip the condition.
            exp_rel = (np.inf if relationship.expiration is None
                       else relationship.expiration - cg.base_time)
            if slot is not None:
                updates[slot] = (float(exp_rel), cav_row)
            else:
                appends[pair] = (float(exp_rel), cav_row)

    n_app = len(appends)
    if cg.n_delta + n_app > cg.delta_cap \
            or cg.n_dead + len(new_dead) > len(cg.dead_buf):
        _fallback("overflow")
        return None

    blocks_host = cg.blocks
    if reclose:
        blocks_host = list(cg.blocks)
        for b, pairs in reclose.items():
            nb = blocks_host[b].reclosed(pairs)
            if nb is None:  # closure overflow: re-stratify instead
                _fallback("overflow")
                return None
            blocks_host[b] = nb
        if cg.block_codes is not None:
            for b in reclose:
                cg.block_codes.pop(b, None)

    # ---- apply: in-place host mutation under host_lock. Descendant
    # views see the appended slots via their n_delta watermark; an OLDER
    # revision that lazily builds device state afterwards may observe
    # newer writes — fully-consistent reads only promise at-least-as-
    # fresh, so that is correct (and rare: device state initializes on
    # the first query after compile) ------------------------------------
    app_items = list(appends.items())
    n0 = cg.n_delta
    nd0 = cg.n_dead
    with cg.host_lock:
        for i, ((s, t), (ex, cv)) in enumerate(app_items):
            slot = n0 + i
            cg.delta_src[slot] = s
            cg.delta_dst[slot] = t
            cg.delta_exp[slot] = ex
            cg.delta_cav[slot] = cv
            delta_pos[(s, t)] = slot
        for slot, (ex, cv) in updates.items():
            cg.delta_exp[slot] = ex
            if cv is not None:
                cg.delta_cav[slot] = cv
        if res_kill:
            cg.res_exp[np.asarray(res_kill, dtype=np.int64)] = -np.inf
        for j, (s, t) in enumerate(new_dead):
            cg.dead_buf[nd0 + j, 0] = s
            cg.dead_buf[nd0 + j, 1] = t
        dead_set.update(new_dead)
        # reserved caveat-instance rows land in the shared host tables
        # (same commit discipline as the overlay slots)
        inst_dev = (cg.caveats.apply_appends(planned_inst)
                    if planned_inst else [])
    n_delta2 = n0 + len(app_items)
    n_dead2 = nd0 + len(new_dead)
    metrics.gauge("engine_delta_occupancy").set(n_delta2)

    # ---- device state: functional O(write) updates against the current
    # resident arrays — published into the NEW view only, so concurrent
    # queries against older revisions keep their immutable arrays. If the
    # base never initialized single-chip device state (mesh engines query
    # through ShardedGraph instead), don't force it here: a later lazy
    # _dev_locked builds correctly from the updated host arrays ----------
    old = cg._device
    d = {}
    if old:
        d = dict(old)
        if app_items:
            ai = np.arange(n0, n0 + len(app_items), dtype=np.int64)
            d["dsrc"] = old["dsrc"].at[ai].set(np.asarray(
                [p[0] for p, _ in app_items], dtype=np.int32))
            d["ddst"] = old["ddst"].at[ai].set(np.asarray(
                [p[1] for p, _ in app_items], dtype=np.int32))
        if app_items or updates:
            ui = np.asarray(
                [n0 + i for i in range(len(app_items))]
                + list(updates.keys()), dtype=np.int64)
            uv = np.asarray(
                [ex for _, (ex, _) in app_items]
                + [ex for ex, _ in updates.values()],
                dtype=np.float32)
            d["dexp"] = d["dexp"].at[ui].set(uv)
        cav_slots = [n0 + i for i in range(len(app_items))] \
            + [slot for slot, (_, cv) in updates.items()
               if cv is not None]
        cav_vals = [cv for _, (_, cv) in app_items] \
            + [cv for _, cv in updates.values() if cv is not None]
        if cav_slots:
            d["dcav"] = d["dcav"].at[
                np.asarray(cav_slots, dtype=np.int64)].set(
                np.asarray(cav_vals, dtype=np.int32))
        if inst_dev and d.get("cav_static"):
            # new instance rows: O(row) functional column writes on the
            # resident context tables, published into this view only
            cs = list(d["cav_static"])
            for ci, local, cols_ in inst_dev:
                sce, scv, sck, lle, llv, lhe, lhv, lk = cols_
                ent = dict(cs[ci])
                ent["ce"] = ent["ce"].at[:, local].set(sce)
                ent["cv"] = ent["cv"].at[:, local].set(scv)
                ent["ck"] = ent["ck"].at[:, local].set(sck)
                ent["loe"] = ent["loe"].at[:, :, local].set(lle)
                ent["lov"] = ent["lov"].at[:, :, local].set(llv)
                ent["hie"] = ent["hie"].at[:, :, local].set(lhe)
                ent["hiv"] = ent["hiv"].at[:, :, local].set(lhv)
                ent["lk"] = ent["lk"].at[:, local].set(lk)
                ent["real"] = ent["real"].at[local].set(True)
                cs[ci] = ent
            d["cav_static"] = tuple(cs)
        if res_kill:
            d["exp"] = old["exp"].at[np.asarray(
                res_kill, dtype=np.int64)].set(-np.inf)
        if (block_cells or reclose) and cg.tier is None:
            blocks_dev = list(old["blocks"])
            bits_dev = list(old["blocks_bits"])
            for b in reclose:
                # re-closed block: fresh device matrix scattered from the
                # new closure COO (uploading the pairs, not the matrix)
                bm = blocks_host[b]
                blocks_dev[b] = jnp.zeros(
                    (bm.n_dst, bm.n_src), dtype=jnp.int8
                ).at[jnp.asarray(bm.dst_local),
                     jnp.asarray(bm.src_local)].set(1)
                if bits_dev[b] is not None:
                    bits_dev[b] = jnp.asarray(bitprop.pack_block_host(
                        bm.dst_local, bm.src_local, bm.n_dst, bm.n_src))
            for b, cells in block_cells.items():
                dl = np.fromiter((c[0] for c in cells), dtype=np.int32,
                                 count=len(cells))
                sl = np.fromiter((c[1] for c in cells), dtype=np.int32,
                                 count=len(cells))
                vals = np.fromiter(cells.values(), dtype=np.int8,
                                   count=len(cells))
                blocks_dev[b] = blocks_dev[b].at[dl, sl].set(vals)
                bits = bits_dev[b]
                if bits is not None:
                    # group per (row, word): multiple cells can share a
                    # packed word, and a gather-modify-scatter with
                    # duplicate indices would drop updates
                    agg: dict[tuple[int, int], tuple[int, int]] = {}
                    for (dli, sli), v in cells.items():
                        k = (dli, sli // 32)
                        setm, clrm = agg.get(k, (0, 0))
                        bit = 1 << (sli % 32)
                        if v:
                            setm |= bit
                        else:
                            clrm |= bit
                        agg[k] = (setm, clrm)
                    rows = np.array([k[0] for k in agg], dtype=np.int32)
                    words = np.array([k[1] for k in agg], dtype=np.int32)
                    sets = np.array([v[0] for v in agg.values()],
                                    dtype=np.uint32)
                    clrs = np.array([v[1] for v in agg.values()],
                                    dtype=np.uint32)
                    cur = bits[rows, words]
                    bits_dev[b] = bits.at[rows, words].set(
                        (cur & jnp.asarray(~clrs)) | jnp.asarray(sets))
            d["blocks"] = tuple(blocks_dev)
            d["blocks_bits"] = tuple(bits_dev)
        # capacity is static, so the signature — and with it d["run"] —
        # cannot change across overlay appends

    # Tiered placement: overlay-touched blocks update through the tier
    # store instead of the resident device tuples (which are
    # placeholders). Runs regardless of whether single-chip device state
    # ever initialized — the cold arena's COO must not go stale.
    if cg.tier is not None and (block_cells or reclose):
        _tier_apply_update(cg, blocks_host, reclose, block_cells)

    return replace(
        cg,
        revision=new_revision,
        n_delta=n_delta2,
        n_dead=n_dead2,
        dead_pairs=cg.dead_buf[:n_dead2],
        blocks=blocks_host,
        _device=d,
    )
