"""Crash recovery: newest valid snapshot + WAL tail replay.

Durability layer three of three. On boot:

1. Try snapshots newest-first; a snapshot that fails to load (truncated
   file, bad zip, mangled meta) is logged and skipped — the checkpointer
   retains ``keep`` generations and prunes the WAL only up to the OLDEST
   retained one, so falling back a generation always leaves enough log
   to replay forward.
2. Replay WAL records with ``rev`` past the loaded snapshot, with
   torn-tail truncation (wal.py) for the kill-mid-append case.
3. Enforce revision monotonicity: every replayed record must advance the
   revision, and the recovered counter resumes ABOVE every revision ever
   acknowledged — a post-restart write can never mint a revision that
   collides with a pre-restart decision-cache key (engine/decision_cache
   keys are ``(kind, revision, query)``; a reused revision with different
   rows would silently serve the dead lineage's verdicts).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Optional

from ..utils.metrics import metrics
from . import wal as walmod
from .codec import decode_bulk_cols
from .snapshot import list_snapshots

log = logging.getLogger("sdbkp.persistence.recovery")


class RecoveryError(Exception):
    pass


@dataclass
class RecoveryResult:
    revision: int = 0
    snapshot_revision: int = 0
    snapshot_path: Optional[str] = None
    corrupt_snapshots: list = field(default_factory=list)
    replayed_records: int = 0
    duration_s: float = 0.0


def apply_record(store, meta: dict, blob: Optional[bytes]) -> None:
    """Apply ONE journal record to the store at its recorded revision.
    Shared with nothing else on purpose: the journal kinds are written in
    exactly one place (Store) and replayed in exactly one place (here)."""
    kind = meta.get("kind")
    rev = int(meta["rev"])
    if kind in ("write", "delete", "apply"):
        store.apply_effects(meta["effects"], rev)
    elif kind == "bulk_load":
        if blob is None:
            raise RecoveryError(
                f"bulk_load record at revision {rev} has no column payload")
        store.bulk_load(decode_bulk_cols(blob), _revision=rev)
    elif kind == "load_state":
        if blob is None:
            raise RecoveryError(
                f"load_state record at revision {rev} has no payload")
        store.load_state_bytes(blob)
    else:
        raise RecoveryError(f"unknown journal record kind {kind!r}")


def recover(store, data_dir: str) -> RecoveryResult:
    """Restore ``store`` from ``data_dir`` (layout: manager.py). The
    store must be otherwise idle — recovery runs before the engine
    serves. Returns what happened; raises :class:`RecoveryError` only on
    monotonicity violations (a broken log is worse served by guessing)."""
    import os

    t0 = time.perf_counter()
    res = RecoveryResult()
    snap_dir = os.path.join(data_dir, "snapshots")
    wal_dir = os.path.join(data_dir, "wal")

    for rev, path in reversed(list_snapshots(snap_dir)):
        try:
            store.load(path)
            res.snapshot_revision = rev
            res.snapshot_path = path
            break
        except Exception as e:  # corrupt snapshot: fall back a generation
            log.warning("snapshot %s unreadable (%s: %s); falling back",
                        path, type(e).__name__, e)
            res.corrupt_snapshots.append(path)

    last = store.revision
    try:
        for meta, blob in walmod.replay(wal_dir, from_revision=last):
            rev = int(meta["rev"])
            if rev <= last:
                raise RecoveryError(
                    f"WAL revision went backwards: {rev} after {last}")
            if rev != last + 1:
                # revisions are journaled densely; a hole means lost
                # segments — keep going (later state is still newer than
                # stopping here) but say so loudly
                log.warning("WAL revision gap: %d -> %d (pruned or lost "
                            "segment?)", last, rev)
            apply_record(store, meta, blob)
            last = rev
            res.replayed_records += 1
    except walmod.WalError as e:
        # mid-history corruption (a SEALED segment failed its CRC —
        # distinct from the torn tail, which wal.replay truncates and
        # tolerates): fail CLOSED. Serving here would strand every
        # record journaled after the corrupt segment — including writes
        # the new process would go on to acknowledge — as permanently
        # unreplayable on all future boots, compounding the loss while
        # reporting healthy. The operator must repair or discard the
        # log (the error names the segment).
        raise RecoveryError(
            f"unrecoverable WAL corruption mid-history: {e}; repair or "
            "remove the named segment (acknowledged writes after it "
            "would otherwise be silently lost)") from e

    res.revision = store.revision
    res.duration_s = time.perf_counter() - t0
    if res.replayed_records:
        metrics.counter("recovery_replayed_records_total").inc(
            res.replayed_records)
    metrics.histogram("recovery_duration_seconds").observe(res.duration_s)
    log.info(
        "recovered revision %d (%d rows) from %s + %d WAL records in %.3fs",
        res.revision, len(store), res.snapshot_path or "empty store",
        res.replayed_records, res.duration_s)
    return res
