"""Snapshot checkpoints: background compaction of the WAL into restart
points.

Durability layer two of three. A checkpoint atomically serializes the
store's columnar chunks, interner tables, and revision counter (the
existing compacted ``.npz`` format from ``Store.save`` — write-temp +
rename, so a crash mid-checkpoint leaves only the previous snapshots)
into ``<dir>/snapshot-<revision 020d>.npz``, then prunes WAL segments
sealed at or below the OLDEST retained snapshot's revision. Pruning to
the oldest — not the newest — keeps enough log that recovery can fall
back a full snapshot generation on corruption and still replay forward
(recovery.py).

The checkpointer triggers when WAL bytes or records appended since the
last checkpoint cross a threshold; the work runs on a background thread
so the write path never pays snapshot serialization inline.
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
import uuid
from typing import Optional

from ..utils.metrics import metrics

log = logging.getLogger("sdbkp.persistence.snapshot")

_SNAP_RE = re.compile(r"^snapshot-(\d{20})\.npz$")

DEFAULT_CHECKPOINT_WAL_BYTES = 64 << 20
DEFAULT_CHECKPOINT_WAL_RECORDS = 50_000
DEFAULT_KEEP = 2


def list_snapshots(snap_dir: str) -> list[tuple[int, str]]:
    """(revision, path) ascending; ignores temp and foreign files."""
    out = []
    try:
        names = os.listdir(snap_dir)
    except FileNotFoundError:
        return []
    for name in names:
        m = _SNAP_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(snap_dir, name)))
    out.sort()
    return out


def write_snapshot(store, snap_dir: str) -> tuple[int, str]:
    """Checkpoint the store into the directory; returns (revision, path).
    Two atomic publishes: ``Store.save`` writes temp-then-rename to a
    scratch name (the saved revision is only known afterwards), then one
    more rename onto the revision-stamped final name."""
    os.makedirs(snap_dir, exist_ok=True)
    scratch = os.path.join(snap_dir, f".inprogress-{uuid.uuid4().hex}.npz")
    try:
        rev = store.save(scratch)
    except BaseException:
        try:
            os.unlink(scratch)
        except OSError:
            pass
        raise
    final = os.path.join(snap_dir, f"snapshot-{rev:020d}.npz")
    os.replace(scratch, final)
    # directory fsync so the rename itself survives power loss
    try:
        dfd = os.open(snap_dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass
    return rev, final


class Checkpointer:
    """Threshold-triggered background checkpoints + retention.

    ``notify(wal)`` is cheap (the WAL calls it per append, under no lock
    here); crossing a threshold wakes the worker thread, which
    checkpoints, drops snapshots beyond ``keep``, and prunes the WAL.
    """

    def __init__(self, store, wal, snap_dir: str,
                 wal_bytes: int = DEFAULT_CHECKPOINT_WAL_BYTES,
                 wal_records: int = DEFAULT_CHECKPOINT_WAL_RECORDS,
                 keep: int = DEFAULT_KEEP):
        self.store = store
        self.wal = wal
        self.snap_dir = snap_dir
        self.wal_bytes = int(wal_bytes)
        self.wal_records = int(wal_records)
        self.keep = max(1, int(keep))
        self._cond = threading.Condition()
        self._pending = False
        self._closed = False
        # appended totals at the last checkpoint (thresholds measure the
        # delta since then, not process lifetime)
        self._base_bytes = 0
        self._base_records = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="store-checkpointer")
        self._thread.start()

    # -- triggers ------------------------------------------------------------

    def notify(self, wal) -> None:
        if (wal.appended_bytes - self._base_bytes < self.wal_bytes and
                wal.appended_records - self._base_records
                < self.wal_records):
            return
        self.request()

    def request(self) -> None:
        """Ask for an async checkpoint (idempotent while one is queued)."""
        with self._cond:
            if self._closed:
                return
            self._pending = True
            self._cond.notify()

    # -- worker --------------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if self._closed and not self._pending:
                    return
                self._pending = False
            try:
                self.checkpoint()
            except Exception:
                log.exception("checkpoint failed (will retry on next "
                              "threshold crossing)")

    def checkpoint(self) -> int:
        """Synchronous checkpoint + retention + WAL prune; returns the
        checkpointed revision. Also the direct entry point for the final
        checkpoint on graceful shutdown."""
        t0 = time.perf_counter()
        # make everything up to the checkpointed revision durable BEFORE
        # the snapshot exists: the snapshot will justify pruning those
        # records, so they must not be sitting in an un-fsynced buffer
        self.wal.sync()
        self._base_bytes = self.wal.appended_bytes
        self._base_records = self.wal.appended_records
        rev, path = write_snapshot(self.store, self.snap_dir)
        dur = time.perf_counter() - t0
        metrics.counter("checkpoints_total").inc()
        metrics.histogram("checkpoint_duration_seconds").observe(dur)
        snaps = list_snapshots(self.snap_dir)
        for old_rev, old_path in snaps[:-self.keep]:
            try:
                os.unlink(old_path)
            except OSError:
                log.exception("failed to drop old snapshot %s", old_path)
        kept = list_snapshots(self.snap_dir)
        if kept:
            self.wal.prune_upto(kept[0][0])
        log.info("checkpointed revision %d in %.3fs (%s)", rev, dur, path)
        return rev

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._thread.join(timeout=60.0)
