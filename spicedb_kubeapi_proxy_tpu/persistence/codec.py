"""Binary codec for columnar relationship payloads.

One encoding, three consumers: WAL ``bulk_load`` frames (wal.py), the
multi-host mirror's bulk-load frames (parallel/multihost.py — replacing
the per-element ``str()`` JSON lists that serialized one Python string
per cell), and the leader->follower full-state catch-up transfer
(engine/remote.py ``mirror_subscribe`` with ``from_revision``).

The container is an uncompressed ``.npz`` written to memory: fixed-width
numpy string columns pass through zero-copy-ish, and ``np.load`` with its
default ``allow_pickle=False`` guarantees no code execution on the decode
side — the encoder never produces object arrays.
"""

from __future__ import annotations

import io
import os
import tempfile

import numpy as np


def _string_column(v) -> np.ndarray:
    """Coerce a column of ids/types to a fixed-width numpy string array.
    ndarray 'S'/'U' columns keep their layout; lists and object arrays
    (the trust-boundary case: elements may be bytes or non-strings) are
    normalized element-wise — the slow path only runs for inputs that
    were never fixed-width to begin with."""
    if isinstance(v, np.ndarray) and v.dtype.kind in "SU":
        return v
    items = v.tolist() if isinstance(v, np.ndarray) else list(v)
    out = [x.decode(errors="surrogateescape")
           if isinstance(x, (bytes, bytearray)) else str(x)
           for x in items]
    return np.asarray(out, dtype=str) if out else \
        np.empty(0, dtype="U1")


def encode_bulk_cols(rels_cols: dict) -> bytes:
    """Columnar bulk-load payload -> npz bytes. ``expiration`` becomes
    float64 with NaN for "never" (the store's bulk_load normalizes NaN
    back to +inf); every other column becomes a fixed-width string
    array."""
    arrays = {}
    for k, v in rels_cols.items():
        if k == "expiration":
            if isinstance(v, np.ndarray):
                arrays[k] = v.astype(np.float64)
            else:
                arrays[k] = np.asarray(
                    [np.nan if x is None else float(x) for x in v],
                    dtype=np.float64)
        else:
            arrays[k] = _string_column(v)
    bio = io.BytesIO()
    np.savez(bio, **arrays)
    return bio.getvalue()


def decode_bulk_cols(blob: bytes) -> dict:
    """npz bytes -> {column: ndarray}, ready for ``Store.bulk_load``.
    allow_pickle stays at its False default: a hostile frame cannot
    smuggle object arrays."""
    with np.load(io.BytesIO(blob)) as z:
        return {k: z[k] for k in z.files}


def save(path: str, arrays: dict) -> int:
    """Persist ``{name: ndarray}`` as a *directory* of one ``.npy`` file
    per column, and return the total bytes written.

    The directory form exists because ``np.load(..., mmap_mode=...)``
    silently ignores the mmap request for ``.npz`` archives (zip members
    can't be mapped); one flat ``.npy`` per column is the only layout
    numpy will genuinely map. Each column is written to a temp file in
    the target directory and atomically renamed, mirroring the store's
    snapshot discipline, so a torn write never leaves a half-length
    column behind.
    """
    os.makedirs(path, exist_ok=True)
    total = 0
    for name, arr in arrays.items():
        a = np.ascontiguousarray(arr)
        fd, tmp = tempfile.mkstemp(dir=path, suffix=".npy.tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.save(f, a)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(path, name + ".npy"))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        total += int(a.nbytes)
    return total


def load(path: str, mmap: bool = False) -> dict:
    """Load a ``save()`` directory back into ``{name: ndarray}``.

    With ``mmap=True`` every column comes back as a read-only memory map
    (``mmap_mode="r"``): snapshot recovery and cold-arena installs touch
    pages on demand instead of transiently holding a second full copy of
    the graph in host RAM. ``allow_pickle`` stays False in both modes —
    same trust boundary as ``decode_bulk_cols``.
    """
    out = {}
    for fn in sorted(os.listdir(path)):
        if not fn.endswith(".npy"):
            continue
        out[fn[:-4]] = np.load(
            os.path.join(path, fn),
            mmap_mode="r" if mmap else None,
            allow_pickle=False,
        )
    return out
