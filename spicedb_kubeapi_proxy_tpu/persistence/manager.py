"""Persistence manager: the one object an engine owns when ``--data-dir``
is configured.

``Persistence.open(store, data_dir)`` runs crash recovery (recovery.py),
opens the WAL for append (wal.py), installs the store's journal hook so
every subsequent revision-advancing mutation is logged before its
transaction returns, and starts the background checkpointer
(snapshot.py). ``close()`` unhooks, takes a final checkpoint (so the
next boot replays nothing), and fsyncs.

Directory layout::

    <data-dir>/
      wal/        wal-<first-revision>.seg ...
      snapshots/  snapshot-<revision>.npz ...
      dtx.sqlite  (the dual-write workflow DB, wired by proxy options)

Persistence is strictly opt-in: with no data dir configured the store
behaves exactly as before — in-memory, revision counter reset on boot —
which is what every existing test and the embedded engine get.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from .recovery import RecoveryResult, recover
from .snapshot import (
    Checkpointer,
    DEFAULT_CHECKPOINT_WAL_BYTES,
    DEFAULT_CHECKPOINT_WAL_RECORDS,
    DEFAULT_KEEP,
)
from .wal import DEFAULT_FSYNC, DEFAULT_SEGMENT_BYTES, WriteAheadLog

log = logging.getLogger("sdbkp.persistence")

# replication-term file (leader failover, parallel/failover.py): one
# line of JSON, written atomically + fsynced on every bump so a fencing
# decision survives SIGKILL — a restarted process must never come back
# believing an older term than the one it acted under
TERM_FILE = "term"


def load_term(data_dir: str) -> int:
    """The highest replication term this data dir has adopted (0 when
    never set / no durable state)."""
    import json

    try:
        with open(os.path.join(data_dir, TERM_FILE)) as f:
            return int(json.load(f)["term"])
    except (OSError, ValueError, KeyError, TypeError):
        # TypeError: valid-JSON-but-not-an-object content ("5", "[7]")
        return 0


def store_term(data_dir: str, term: int) -> None:
    """Durably adopt ``term`` (atomic tmp + rename + fsync): after this
    returns, no crash can roll the process back into accepting frames
    from a lineage it already fenced off."""
    import json
    import tempfile

    os.makedirs(data_dir, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=data_dir, prefix=TERM_FILE + ".tmp.")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump({"term": int(term)}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(data_dir, TERM_FILE))
        dfd = os.open(data_dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class Persistence:
    """Owns the WAL + checkpointer for one store. Construct via
    :meth:`open`."""

    def __init__(self, store, data_dir: str, wal: WriteAheadLog,
                 checkpointer: Optional[Checkpointer],
                 recovery: RecoveryResult):
        self.store = store
        self.data_dir = data_dir
        self.wal = wal
        self.checkpointer = checkpointer
        self.recovery = recovery
        self._closed = False
        # construction parameters, kept so a lineage rebase (leader
        # failover: a full-state catch-up superseding local history) can
        # reopen a byte-fresh WAL + checkpointer with identical policy
        self._params: dict = {}

    @classmethod
    def open(cls, store, data_dir: str,
             wal_fsync: str = DEFAULT_FSYNC,
             segment_bytes: int = DEFAULT_SEGMENT_BYTES,
             checkpoint_wal_bytes: int = DEFAULT_CHECKPOINT_WAL_BYTES,
             checkpoint_wal_records: int = DEFAULT_CHECKPOINT_WAL_RECORDS,
             checkpoint_keep: int = DEFAULT_KEEP,
             auto_checkpoint: bool = True) -> "Persistence":
        os.makedirs(data_dir, exist_ok=True)
        wal_dir = os.path.join(data_dir, "wal")
        snap_dir = os.path.join(data_dir, "snapshots")
        res = recover(store, data_dir)
        wal = WriteAheadLog(wal_dir, fsync=wal_fsync,
                            segment_bytes=segment_bytes)
        cp = None
        if auto_checkpoint:
            cp = Checkpointer(store, wal, snap_dir,
                              wal_bytes=checkpoint_wal_bytes,
                              wal_records=checkpoint_wal_records,
                              keep=checkpoint_keep)
            wal.on_append = cp.notify
            if res.replayed_records >= checkpoint_wal_records:
                # a crash left a long un-checkpointed tail; fold it into
                # a snapshot asynchronously so the NEXT boot is fast
                cp.request()
        p = cls(store, data_dir, wal, cp, res)
        p._params = dict(
            wal_fsync=wal_fsync, segment_bytes=segment_bytes,
            checkpoint_wal_bytes=checkpoint_wal_bytes,
            checkpoint_wal_records=checkpoint_wal_records,
            checkpoint_keep=checkpoint_keep,
            auto_checkpoint=auto_checkpoint)
        store.journal = p._journal
        return p

    # -- the store's journal hook (called under the store write lock) --------

    def _journal(self, meta: dict, blob: Optional[bytes] = None) -> None:
        self.wal.append(meta, blob)

    # -- lineage rebase (leader failover) ------------------------------------

    def rebase(self, state_payload: bytes) -> None:
        """Adopt a full-state catch-up transfer as a NEW LINEAGE
        baseline (parallel/multihost.py ``apply_catchup``): a demoted
        leader's (or far-behind follower's) local WAL + snapshots
        describe superseded history whose revision numbers may overlap
        the incoming lineage's — keeping them would make the next boot's
        replay see revisions go backwards and fail closed. Discard them,
        install the transferred state, and restart the log with that
        baseline as its first (journaled, fsynced) record.

        Crash window: a kill between the wipe and the re-journal leaves
        an empty data dir — the follower then rejoins from revision 0
        and re-fetches the same transfer. Nothing of the NEW lineage is
        ever lost, and everything discarded of the old one was fenced
        off by a higher term already."""
        store = self.store
        # bound-method EQUALITY, not identity: each attribute access
        # mints a fresh bound-method object, so `is` never matches
        detached = getattr(store, "journal", None) == self._journal
        if detached:
            store.journal = None  # install must not journal mid-rebase
        if self.checkpointer is not None:
            self.checkpointer.close()
        self.wal.close()
        wal_dir = os.path.join(self.data_dir, "wal")
        snap_dir = os.path.join(self.data_dir, "snapshots")
        removed = 0
        for d in (wal_dir, snap_dir):
            try:
                names = os.listdir(d)
            except FileNotFoundError:
                continue
            for name in names:
                try:
                    os.unlink(os.path.join(d, name))
                    removed += 1
                except OSError:
                    log.exception("rebase: failed to remove %s/%s", d,
                                  name)
        store.load_state_bytes(state_payload)
        self.wal = WriteAheadLog(wal_dir, fsync=self._params["wal_fsync"],
                                 segment_bytes=self._params["segment_bytes"])
        if self._params.get("auto_checkpoint", True):
            self.checkpointer = Checkpointer(
                store, self.wal, snap_dir,
                wal_bytes=self._params["checkpoint_wal_bytes"],
                wal_records=self._params["checkpoint_wal_records"],
                keep=self._params["checkpoint_keep"])
            self.wal.on_append = self.checkpointer.notify
        self._journal({"kind": "load_state", "rev": store.revision},
                      state_payload)
        self.wal.sync()  # the baseline is the lineage: make it durable NOW
        if detached:
            store.journal = self._journal
        log.info("rebased lineage at revision %d (%d old files discarded)",
                 store.revision, removed)

    # -- lifecycle -----------------------------------------------------------

    def checkpoint_now(self) -> int:
        """Synchronous checkpoint (graceful shutdown, tests)."""
        if self.checkpointer is not None:
            return self.checkpointer.checkpoint()
        from .snapshot import write_snapshot

        self.wal.sync()
        rev, _ = write_snapshot(self.store,
                                os.path.join(self.data_dir, "snapshots"))
        return rev

    def close(self, final_checkpoint: bool = True) -> None:
        """Detach from the store and shut the WAL down cleanly. With
        ``final_checkpoint`` the store is snapshotted first so the next
        boot loads one file and replays zero records."""
        if self._closed:
            return
        self._closed = True
        # == not `is`: attribute access mints fresh bound-method objects
        if getattr(self.store, "journal", None) == self._journal:
            self.store.journal = None
        try:
            if final_checkpoint and self.wal.appended_records:
                self.checkpoint_now()
        except Exception:
            log.exception("final checkpoint failed; WAL tail remains "
                          "authoritative")
        if self.checkpointer is not None:
            self.checkpointer.close()
        self.wal.close()
