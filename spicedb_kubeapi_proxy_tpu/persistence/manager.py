"""Persistence manager: the one object an engine owns when ``--data-dir``
is configured.

``Persistence.open(store, data_dir)`` runs crash recovery (recovery.py),
opens the WAL for append (wal.py), installs the store's journal hook so
every subsequent revision-advancing mutation is logged before its
transaction returns, and starts the background checkpointer
(snapshot.py). ``close()`` unhooks, takes a final checkpoint (so the
next boot replays nothing), and fsyncs.

Directory layout::

    <data-dir>/
      wal/        wal-<first-revision>.seg ...
      snapshots/  snapshot-<revision>.npz ...
      dtx.sqlite  (the dual-write workflow DB, wired by proxy options)

Persistence is strictly opt-in: with no data dir configured the store
behaves exactly as before — in-memory, revision counter reset on boot —
which is what every existing test and the embedded engine get.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from .recovery import RecoveryResult, recover
from .snapshot import (
    Checkpointer,
    DEFAULT_CHECKPOINT_WAL_BYTES,
    DEFAULT_CHECKPOINT_WAL_RECORDS,
    DEFAULT_KEEP,
)
from .wal import DEFAULT_FSYNC, DEFAULT_SEGMENT_BYTES, WriteAheadLog

log = logging.getLogger("sdbkp.persistence")


class Persistence:
    """Owns the WAL + checkpointer for one store. Construct via
    :meth:`open`."""

    def __init__(self, store, data_dir: str, wal: WriteAheadLog,
                 checkpointer: Optional[Checkpointer],
                 recovery: RecoveryResult):
        self.store = store
        self.data_dir = data_dir
        self.wal = wal
        self.checkpointer = checkpointer
        self.recovery = recovery
        self._closed = False

    @classmethod
    def open(cls, store, data_dir: str,
             wal_fsync: str = DEFAULT_FSYNC,
             segment_bytes: int = DEFAULT_SEGMENT_BYTES,
             checkpoint_wal_bytes: int = DEFAULT_CHECKPOINT_WAL_BYTES,
             checkpoint_wal_records: int = DEFAULT_CHECKPOINT_WAL_RECORDS,
             checkpoint_keep: int = DEFAULT_KEEP,
             auto_checkpoint: bool = True) -> "Persistence":
        os.makedirs(data_dir, exist_ok=True)
        wal_dir = os.path.join(data_dir, "wal")
        snap_dir = os.path.join(data_dir, "snapshots")
        res = recover(store, data_dir)
        wal = WriteAheadLog(wal_dir, fsync=wal_fsync,
                            segment_bytes=segment_bytes)
        cp = None
        if auto_checkpoint:
            cp = Checkpointer(store, wal, snap_dir,
                              wal_bytes=checkpoint_wal_bytes,
                              wal_records=checkpoint_wal_records,
                              keep=checkpoint_keep)
            wal.on_append = cp.notify
            if res.replayed_records >= checkpoint_wal_records:
                # a crash left a long un-checkpointed tail; fold it into
                # a snapshot asynchronously so the NEXT boot is fast
                cp.request()
        p = cls(store, data_dir, wal, cp, res)
        store.journal = p._journal
        return p

    # -- the store's journal hook (called under the store write lock) --------

    def _journal(self, meta: dict, blob: Optional[bytes] = None) -> None:
        self.wal.append(meta, blob)

    # -- lifecycle -----------------------------------------------------------

    def checkpoint_now(self) -> int:
        """Synchronous checkpoint (graceful shutdown, tests)."""
        if self.checkpointer is not None:
            return self.checkpointer.checkpoint()
        from .snapshot import write_snapshot

        self.wal.sync()
        rev, _ = write_snapshot(self.store,
                                os.path.join(self.data_dir, "snapshots"))
        return rev

    def close(self, final_checkpoint: bool = True) -> None:
        """Detach from the store and shut the WAL down cleanly. With
        ``final_checkpoint`` the store is snapshotted first so the next
        boot loads one file and replays zero records."""
        if self._closed:
            return
        self._closed = True
        if getattr(self.store, "journal", None) is self._journal:
            self.store.journal = None
        try:
            if final_checkpoint and self.wal.appended_records:
                self.checkpoint_now()
        except Exception:
            log.exception("final checkpoint failed; WAL tail remains "
                          "authoritative")
        if self.checkpointer is not None:
            self.checkpointer.close()
        self.wal.close()
