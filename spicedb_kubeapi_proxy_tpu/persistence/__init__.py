"""Durable persistence for the relationship store.

Three cooperating pieces (see each module's docstring):

- :mod:`.wal`      — segmented, CRC32-framed write-ahead log of logical
                     store mutations with configurable fsync policy
- :mod:`.snapshot` — background checkpointer: atomic columnar snapshots
                     + WAL pruning behind a retention window
- :mod:`.recovery` — boot-time restore: newest valid snapshot (falling
                     back on corruption) + WAL tail replay with
                     torn-tail truncation and revision-monotonicity
                     enforcement

:class:`.manager.Persistence` is the façade an engine enables with
``--data-dir``; :mod:`.codec` is the shared binary columnar codec (WAL
bulk-load frames, mirror bulk-load frames, follower full-state
catch-up).
"""

from .codec import decode_bulk_cols, encode_bulk_cols
from .manager import Persistence, load_term, store_term
from .recovery import RecoveryError, RecoveryResult, recover
from .snapshot import Checkpointer, list_snapshots, write_snapshot
from .wal import WalError, WriteAheadLog, parse_fsync_policy

__all__ = [
    "Checkpointer",
    "Persistence",
    "RecoveryError",
    "RecoveryResult",
    "WalError",
    "WriteAheadLog",
    "decode_bulk_cols",
    "encode_bulk_cols",
    "list_snapshots",
    "load_term",
    "parse_fsync_policy",
    "recover",
    "store_term",
    "write_snapshot",
]
