"""Segmented write-ahead log of logical store mutations.

Durability layer one of three (wal.py / snapshot.py / recovery.py): every
revision-advancing store mutation — write, delete-by-filter, bulk load,
catch-up apply — is journaled as one length-prefixed, CRC32-checked frame
BEFORE the caller's transaction returns, so an acknowledged write survives
SIGKILL. This mirrors how production graph stores persist their matrix
representation (RedisGraph serializes its GraphBLAS matrices + a
replication log to disk, PAPERS.md) rather than treating the in-memory
columns as the source of truth.

Layout: ``<dir>/wal-<first-revision 020d>.seg`` files, each starting with
an 8-byte magic. A frame is ``>II`` (payload length, CRC32 of payload)
followed by the payload. A payload is either plain JSON (starts with
``{``) or the binary convention shared with the remote protocol
(engine/remote.py): ``0x00`` + 4-byte meta length + meta JSON + blob —
bulk-load column payloads ride the binary form instead of inflating
through per-cell JSON.

Fsync policy (``--wal-fsync``):

- ``always``       — fsync after every append; an acked write survives
                     power loss, at one fsync of latency per write.
- ``interval:<ms>``— appends flush to the OS; a background syncer fsyncs
                     at most every <ms> (default policy, 100ms): SIGKILL
                     of the process loses nothing (the OS has the bytes),
                     whole-machine power loss can lose the last window.
- ``off``          — no fsync until close/rotate; fastest, bench/tests.

Segments rotate at ``segment_bytes``; sealed segments are immutable and
become prunable once a snapshot checkpoint covers their highest revision
(snapshot.py decides when, :meth:`WriteAheadLog.prune_upto` executes).
"""

from __future__ import annotations

import json
import logging
import os
import re
import struct
import threading
import time
import zlib
from typing import Iterator, Optional

from ..utils.metrics import metrics

log = logging.getLogger("sdbkp.persistence.wal")

MAGIC = b"SDBKWAL1"
_FRAME_HDR = struct.Struct(">II")  # payload length, crc32(payload)
_SEG_RE = re.compile(r"^wal-(\d{20})\.seg$")

FSYNC_ALWAYS = "always"
FSYNC_INTERVAL = "interval"
FSYNC_OFF = "off"

DEFAULT_FSYNC = "interval:100"
DEFAULT_SEGMENT_BYTES = 64 << 20

# an absurdly large frame means a corrupt length header, not a record
MAX_WAL_FRAME = 1 << 31


class WalError(Exception):
    pass


def parse_fsync_policy(spec: str) -> tuple[str, float]:
    """``always`` | ``off`` | ``interval:<ms>`` -> (mode, interval_s).
    The ONE owner of the flag format — proxy options and the engine-host
    CLI both validate through here."""
    s = (spec or "").strip().lower()
    if s == FSYNC_ALWAYS:
        return FSYNC_ALWAYS, 0.0
    if s == FSYNC_OFF:
        return FSYNC_OFF, 0.0
    if s.startswith(FSYNC_INTERVAL + ":"):
        try:
            ms = float(s.split(":", 1)[1])
        except ValueError:
            ms = -1.0
        if ms > 0:
            return FSYNC_INTERVAL, ms / 1000.0
    raise WalError(
        f"invalid wal fsync policy {spec!r} "
        "(expected always | interval:<ms> | off)")


def _pack_payload(meta: dict, blob: Optional[bytes]) -> bytes:
    m = json.dumps(meta, separators=(",", ":")).encode()
    if blob is None:
        return m
    return b"\x00" + struct.pack(">I", len(m)) + m + blob


def _unpack_payload(payload: bytes) -> tuple[dict, Optional[bytes]]:
    if payload[:1] == b"\x00":
        (m,) = struct.unpack(">I", payload[1:5])
        return json.loads(payload[5:5 + m]), payload[5 + m:]
    return json.loads(payload), None


def list_segments(wal_dir: str) -> list[tuple[int, str]]:
    """(first_revision, path) ascending; ignores non-segment files."""
    out = []
    try:
        names = os.listdir(wal_dir)
    except FileNotFoundError:
        return []
    for name in names:
        m = _SEG_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(wal_dir, name)))
    out.sort()
    return out


def total_bytes(wal_dir: str) -> int:
    return sum(os.path.getsize(p) for _, p in list_segments(wal_dir)
               if os.path.exists(p))


def _replay_segment(path: str, is_last: bool, truncate_torn: bool
                    ) -> Iterator[tuple[dict, Optional[bytes]]]:
    """Yield (meta, blob) frames from one segment. A torn or corrupt tail
    in the LAST segment is truncated back to the previous frame boundary
    (the kill-mid-write case); corruption mid-history raises."""
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            if is_last and len(magic) < len(MAGIC) and truncate_torn:
                # a segment file created but killed before the magic
                # finished landing: nothing recoverable here — remove it
                # so a later append can reuse its revision-stamped name
                log.warning("removing torn segment stub %s", path)
                _remove(path)
                return
            raise WalError(f"{path}: bad segment magic {magic!r}")
        offset = len(MAGIC)
        while True:
            hdr = f.read(_FRAME_HDR.size)
            if not hdr:
                return  # clean end
            torn = len(hdr) < _FRAME_HDR.size
            if not torn:
                n, crc = _FRAME_HDR.unpack(hdr)
                if n > MAX_WAL_FRAME:
                    torn = True  # garbage length header
                else:
                    payload = f.read(n)
                    torn = len(payload) < n or \
                        zlib.crc32(payload) != crc
            if torn:
                if not is_last:
                    raise WalError(
                        f"{path}: corrupt frame at offset {offset} in a "
                        "sealed (non-final) segment")
                if truncate_torn:
                    if offset == len(MAGIC):
                        # the tear took the segment's FIRST frame: a
                        # truncated-but-kept file would collide with the
                        # re-append of the revision it is named after
                        # (_rotate_locked refuses to overwrite segments)
                        log.warning("removing frame-less torn segment %s",
                                    path)
                        _remove(path)
                    else:
                        log.warning(
                            "truncating torn WAL tail of %s at byte %d",
                            path, offset)
                        _truncate(path, offset)
                return
            offset += _FRAME_HDR.size + n
            yield _unpack_payload(payload)


def _truncate(path: str, size: int) -> None:
    with open(path, "r+b") as f:
        f.truncate(size)
        f.flush()
        os.fsync(f.fileno())


def _remove(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        log.exception("failed to remove torn segment %s", path)


def replay(wal_dir: str, from_revision: int = 0,
           truncate_torn: bool = True
           ) -> Iterator[tuple[dict, Optional[bytes]]]:
    """Iterate journal records with ``rev > from_revision`` across all
    segments in order, applying torn-tail truncation to the newest one."""
    segs = list_segments(wal_dir)
    for i, (_, path) in enumerate(segs):
        for meta, blob in _replay_segment(path, i == len(segs) - 1,
                                          truncate_torn):
            if int(meta.get("rev", 0)) > from_revision:
                yield meta, blob


class WriteAheadLog:
    """Append end of the log. Opening always begins a FRESH segment on
    the first append (named by that record's revision) — recovery may
    have truncated the previous tail, and appends must never land in a
    file another process half-wrote. Thread-safe; the store calls
    :meth:`append` under its own write lock, so frame order == revision
    order by construction."""

    def __init__(self, wal_dir: str, fsync: str = DEFAULT_FSYNC,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 on_append=None):
        self.dir = wal_dir
        self.mode, self.interval = parse_fsync_policy(fsync)
        self.segment_bytes = int(segment_bytes)
        self.on_append = on_append  # checkpointer trigger
        os.makedirs(wal_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._f = None
        self._seg_path: Optional[str] = None
        self._seg_size = 0
        self._dirty = False
        self._closed = False
        # monotonic totals this process, for checkpoint thresholds
        self.appended_bytes = 0
        self.appended_records = 0
        self.last_revision = 0
        # live bytes currently on disk (recovered tail + appends - prunes)
        self._disk_bytes = total_bytes(wal_dir)
        metrics.gauge("wal_bytes").set(self._disk_bytes)
        self._sync_thread: Optional[threading.Thread] = None
        self._sync_stop = threading.Event()
        if self.mode == FSYNC_INTERVAL:
            t = threading.Thread(target=self._sync_loop, daemon=True,
                                 name="wal-fsync")
            self._sync_thread = t
            t.start()

    # -- append path ---------------------------------------------------------

    def append(self, meta: dict, blob: Optional[bytes] = None) -> None:
        rev = int(meta["rev"])
        payload = _pack_payload(meta, blob)
        if len(payload) > MAX_WAL_FRAME:
            # replay classifies length headers past this bound as torn
            # garbage — appending one would be written "successfully" and
            # then silently truncated away at the next recovery
            raise WalError(
                f"journal record of {len(payload)} bytes exceeds the "
                f"{MAX_WAL_FRAME}-byte frame bound")
        frame = _FRAME_HDR.pack(len(payload), zlib.crc32(payload)) + payload
        with self._lock:
            if self._closed:
                raise WalError("write-ahead log is closed")
            if self._f is None or self._seg_size >= self.segment_bytes:
                self._rotate_locked(rev)
            self._f.write(frame)
            self._dirty = True
            self._seg_size += len(frame)
            self.appended_bytes += len(frame)
            self.appended_records += 1
            self._disk_bytes += len(frame)
            self.last_revision = rev
            if self.mode == FSYNC_ALWAYS:
                self._sync_locked()
            else:
                self._f.flush()  # SIGKILL-safe either way; fsync policy
                # only governs power-loss durability
            disk = self._disk_bytes
        metrics.counter("wal_appends_total").inc()
        metrics.gauge("wal_bytes").set(disk)
        if self.on_append is not None:
            self.on_append(self)

    def _rotate_locked(self, first_rev: int) -> None:
        if self._f is not None:
            self._sync_locked()
            self._f.close()
        path = os.path.join(self.dir, f"wal-{first_rev:020d}.seg")
        if os.path.exists(path):
            raise WalError(f"segment {path} already exists "
                           "(another writer on this data dir?)")
        self._f = open(path, "wb")
        self._f.write(MAGIC)
        self._seg_path = path
        self._seg_size = len(MAGIC)
        self.appended_bytes += len(MAGIC)
        self._disk_bytes += len(MAGIC)

    def _sync_locked(self) -> None:
        if self._f is None or not self._dirty:
            return
        t0 = time.perf_counter()
        self._f.flush()
        os.fsync(self._f.fileno())
        self._dirty = False
        metrics.histogram("wal_fsync_seconds").observe(
            time.perf_counter() - t0)

    def sync(self) -> None:
        """Flush + fsync whatever has been appended so far."""
        with self._lock:
            self._sync_locked()

    def _sync_loop(self) -> None:
        while not self._sync_stop.wait(self.interval):
            try:
                self.sync()
            except OSError:
                log.exception("background wal fsync failed")

    # -- maintenance ---------------------------------------------------------

    def prune_upto(self, revision: int) -> int:
        """Delete sealed segments whose every record is at or below
        ``revision`` (provable from the NEXT segment's first-revision
        name — records are revision-ordered). The active segment is never
        pruned. Returns segments removed."""
        removed = 0
        with self._lock:
            segs = list_segments(self.dir)
            for (_, path), (next_first, _) in zip(segs, segs[1:]):
                if path == self._seg_path:
                    break
                if next_first <= revision + 1:
                    try:
                        os.unlink(path)
                        removed += 1
                    except OSError:
                        log.exception("failed to prune %s", path)
                else:
                    break
            self._disk_bytes = total_bytes(self.dir)
            disk = self._disk_bytes
        metrics.gauge("wal_bytes").set(disk)
        return removed

    def close(self) -> None:
        self._sync_stop.set()
        if self._sync_thread is not None:
            self._sync_thread.join(timeout=5.0)
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._f is not None:
                try:
                    self._sync_locked()
                finally:
                    self._f.close()
                    self._f = None
