"""SchemaMigrator: the engine's S -> S' state machine.

The rebalancer (PR 14) proved copy -> catch-up -> dual-write ->
persisted-cut -> atomic-swap on the shard axis; this module applies the
same machinery to the SCHEMA axis:

1. **classify** — ``models/schema.py::diff_schemas`` splits the
   transition into additive (no tuple rewrites), rewriting (affected
   tuples re-validated + backfilled through the journaled write path),
   or incompatible (refused with a typed error before any state
   changes).
2. **dual-compile** — the new schema's graph is compiled beside the old
   from a store snapshot, off the engine lock, exactly like the
   compactor's double buffer (engine/compaction.py); the serving graph
   keeps answering throughout.
3. **journaled backfill** — every tuple on a rewriting relation is
   re-validated under S' and TOUCHed back through
   ``engine.write_relationships`` (WAL + watch log + replication all see
   it), with the echo revisions recorded so watch streams stay
   exactly-once.
4. **dual window** — the new graph catches up on live write traffic by
   replaying watch-log records (``incremental_update``), the schema
   analog of the mover's catch-up loop; lag is the status/readyz signal.
5. **atomic cut** — a brief write freeze (the rebalancer's
   ``_SliceGate`` idiom, engine-global because a schema spans every
   namespace), drain to lag zero, a machine-checked unaffected-verdict
   parity probe (oracle under S vs S' on keys OUTSIDE the diff — any
   mismatch aborts instead of cutting), persist CUT, then swap
   ``engine.schema``/``engine._compiled`` at an UNCHANGED revision so
   decision-cache keys outside the diff survive
   (``decision_cache.retire_affected``).

Every phase transition persists to the migration record (JSON, atomic
rename) BEFORE it takes routing effect; ``recover`` is the boot-time
crash matrix: no cut persisted -> clean abort (the schema never
changed; backfill touches are idempotent), cut persisted -> resume and
finish (re-publish S'), done marker -> re-apply until the bootstrap
catches up (the rebalancer's stale-flag rule).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from typing import Optional

from ..models.schema import (
    REWRITING,
    Schema,
    SchemaError,
    parse_schema,
    require_compatible,
)
from ..utils.metrics import metrics

log = logging.getLogger("sdbkp.migration")

# phase machine — persisted before every routing-effect change
PLANNED = "planned"
COMPILING = "compiling"
BACKFILL = "backfill"
DUAL = "dual"
CUT = "cut"
DONE = "done"
# terminal non-success states (never persisted as a resumable record)
ABORTED = "aborted"
FAILED = "failed"

_PHASE_ORDER = (PLANNED, COMPILING, BACKFILL, DUAL, CUT, DONE)
_PHASE_NUM = {p: i for i, p in enumerate(_PHASE_ORDER)}


def schema_digest(text: str) -> str:
    return hashlib.sha256(text.encode()).hexdigest()[:16]


def _atomic_write_json(path: str, doc: dict) -> None:
    """Persist-before-effect: the record hits disk (fsync + rename)
    before the phase it names takes routing effect."""
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class _WriteGate:
    """Writer/freezer gate for the cutover — the rebalancer's
    ``_SliceGate`` applied engine-wide (a schema spans every namespace,
    so there is no per-slice scoping to hide behind; the freeze is
    bounded by the final drain, which runs at overlay-append speed)."""

    def __init__(self):
        self._cv = threading.Condition()
        self._writers = 0
        self._frozen = False

    def enter(self) -> None:
        with self._cv:
            while self._frozen:
                self._cv.wait()
            self._writers += 1

    def exit(self) -> None:
        with self._cv:
            self._writers -= 1
            self._cv.notify_all()

    def freeze(self) -> None:
        with self._cv:
            self._frozen = True
            while self._writers:
                self._cv.wait()

    def thaw(self) -> None:
        with self._cv:
            self._frozen = False
            self._cv.notify_all()


class SchemaMigrator:
    """One live S -> S' transition over one :class:`~..engine.Engine`.

    ``hold_at_dual=True`` parks the migration in the dual window (new
    graph caught up, lag tracked) until :meth:`request_cut` — the
    planner's coordinated-cut hook so every shard group flips in the
    same journal-recorded step. ``batch`` bounds each backfill write
    (one journaled TOUCH batch = one suppressed watch revision).
    """

    def __init__(self, engine, schema_text: str,
                 record_path: Optional[str] = None,
                 batch: int = 512,
                 hold_at_dual: bool = False,
                 parity_samples: int = 64,
                 backfill_pause: float = 0.0):
        self.engine = engine
        self.schema_text = schema_text
        self.record_path = record_path
        self.batch = max(1, int(batch))
        self.hold_at_dual = bool(hold_at_dual)
        self.parity_samples = max(0, int(parity_samples))
        # optional inter-batch pause: keeps backfill strictly below
        # serving traffic even without an admission queue in front
        self.backfill_pause = float(backfill_pause)
        self._lock = threading.Lock()
        self._cut_requested = threading.Event()
        self._abort_requested = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._phase = PLANNED
        self._error: Optional[str] = None
        self._classification: Optional[str] = None
        self._reasons: tuple = ()
        self._affected: frozenset = frozenset()
        self._backfilled = 0
        self._suppressed: list[int] = []
        self._lag = 0
        self._started = time.time()
        self._cut_at: Optional[float] = None
        self._done_at: Optional[float] = None
        self._freeze_seconds = 0.0
        self._to_digest = schema_digest(schema_text)
        self._from_digest: Optional[str] = None
        self._new_schema: Optional[Schema] = None
        self._diff = None
        self._new_cg = None

    # -- public surface ------------------------------------------------------

    @property
    def active(self) -> bool:
        return self._phase not in (DONE, ABORTED, FAILED)

    def start(self) -> None:
        """Plan synchronously (so incompatible schemas refuse on the
        caller's stack, before any state changes), then run the
        compile/backfill/dual/cut pipeline on a background thread."""
        self._plan()
        t = threading.Thread(target=self._run, name="schema-migrator",
                             daemon=True)
        self._thread = t
        t.start()

    def join(self, timeout: Optional[float] = None) -> None:
        t = self._thread
        if t is not None:
            t.join(timeout)

    def request_cut(self) -> None:
        self._cut_requested.set()

    def abort(self) -> dict:
        """Refuse once the cut persisted (the transition is one-way past
        that point, like the rebalancer's any-cut rule); before it, stop
        the worker and clear the record — the serving schema never
        changed, and backfill touches were idempotent re-writes."""
        with self._lock:
            if _PHASE_NUM.get(self._phase, 0) >= _PHASE_NUM[CUT] \
                    and self._phase != FAILED:
                from ..engine.store import StoreError

                raise StoreError(
                    f"cannot abort: migration already {self._phase}")
            self._abort_requested.set()
            self._cut_requested.set()  # unpark a dual hold
        self.join(timeout=30.0)
        with self._lock:
            if self._phase not in (DONE, ABORTED, FAILED):
                self._finish(ABORTED, "operator abort")
        return self.status()

    def status(self) -> dict:
        with self._lock:
            ttc = None
            if self._cut_at is not None:
                ttc = round((self._cut_at - self._started) * 1e3, 3)
            return {
                "active": self.active,
                "phase": self._phase,
                "classification": self._classification,
                "to_digest": self._to_digest,
                "from_digest": self._from_digest,
                "reasons": list(self._reasons),
                "affected": len(self._affected),
                "backfilled": self._backfilled,
                "suppressed": len(self._suppressed),
                "lag": self._lag,
                "started": self._started,
                "time_to_cut_ms": ttc,
                "freeze_ms": round(self._freeze_seconds * 1e3, 3),
                "error": self._error,
            }

    # -- phase machine -------------------------------------------------------

    def _set_phase(self, phase: str) -> None:
        with self._lock:
            self._phase = phase
        metrics.gauge("engine_migration_phase").set(
            _PHASE_NUM.get(phase, -1))
        self._persist()
        log.info("migration %s -> %s", self._to_digest, phase)

    def _persist(self) -> None:
        if not self.record_path:
            return
        with self._lock:
            doc = {
                "phase": self._phase,
                "to_digest": self._to_digest,
                "from_digest": self._from_digest,
                "to_text": self.schema_text,
                "classification": self._classification,
                "suppressed_revisions": list(self._suppressed),
                "backfilled": self._backfilled,
                "affected": sorted(list(p) for p in self._affected),
                "started": self._started,
                "updated": time.time(),
            }
        _atomic_write_json(self.record_path, doc)

    def _clear_record(self) -> None:
        if self.record_path:
            try:
                os.remove(self.record_path)
            except FileNotFoundError:
                pass

    def _finish(self, phase: str, error: Optional[str] = None) -> None:
        with self._lock:
            self._phase = phase
            self._error = error
            self._done_at = time.time()
        metrics.gauge("engine_migration_phase").set(
            _PHASE_NUM.get(phase, -1))
        metrics.gauge("engine_migration_lag").set(0)
        if phase == DONE:
            metrics.counter("engine_migrations_total",
                            outcome="done").inc()
            self._persist()  # the done marker (stale-flag rule)
        else:
            metrics.counter("engine_migrations_total",
                            outcome=phase).inc()
            self._clear_record()

    # -- planning (synchronous: typed refusal before any state change) ------

    def _plan(self) -> None:
        e = self.engine
        new_schema = parse_schema(self.schema_text)  # SchemaError -> caller
        # raises IncompatibleSchemaChange before ANY state changes
        diff = require_compatible(e.schema, new_schema)
        from ..models.schema import ir_digest

        with self._lock:
            self._new_schema = new_schema
            self._diff = diff
            self._classification = diff.classification
            self._reasons = diff.reasons
            self._affected = diff.affected
            self._from_digest = ir_digest(e.schema)
            self._to_digest = ir_digest(new_schema)
        if diff.classification == REWRITING:
            # tuple-level compatibility: every stored tuple on a
            # rewriting relation must re-validate under S' — an
            # invalid one (e.g. S' now REQUIRES a caveat the tuple
            # lacks) refuses the whole migration up front, before the
            # record is written or a single byte moves
            self._validate_affected_tuples(new_schema, diff)
        self._set_phase(PLANNED)

    def _validate_affected_tuples(self, new_schema, diff) -> None:
        from ..engine.engine import SchemaViolation, validate_relationship
        from ..engine.store import RelationshipFilter

        for dname, rname in sorted(diff.rewrite_relations):
            for rel in self.engine.read_relationships(
                    RelationshipFilter(resource_type=dname,
                                       relation=rname)):
                try:
                    validate_relationship(new_schema, rel)
                except (SchemaError, SchemaViolation) as err:
                    from ..models.schema import IncompatibleSchemaChange

                    raise IncompatibleSchemaChange((
                        f"stored tuple {rel} does not validate under "
                        f"the new schema: {err}",)) from None

    # -- the worker ----------------------------------------------------------

    def _run(self) -> None:
        try:
            self._compile()
            if self._abort_requested.is_set():
                self._finish(ABORTED, "operator abort")
                return
            if self._diff.classification == REWRITING:
                self._backfill()
            if self._abort_requested.is_set():
                self._finish(ABORTED, "operator abort")
                return
            self._dual()
            if self._abort_requested.is_set():
                self._finish(ABORTED, "operator abort")
                return
            self._cut()
            self._finish(DONE)
        except BaseException as err:  # noqa: BLE001 - worker boundary:
            # the failure is disposed into status()/metrics and the
            # record is cleared so boot aborts cleanly, never resumes a
            # half-state; re-raising would kill a daemon thread silently
            log.exception("schema migration failed")
            self._finish(FAILED, f"{type(err).__name__}: {err}")

    def _compile(self) -> None:
        """Dual-compile: S''s graph beside the serving one, off the
        engine lock (the compactor's double-buffer discipline — the old
        base keeps serving while this compiles)."""
        self._set_phase(COMPILING)
        e = self.engine
        from ..ops.reachability import compile_graph

        t0 = time.perf_counter()
        self._new_cg = compile_graph(self._new_schema, e.store.snapshot(),
                                     delta_capacity=e._delta_capacity)
        metrics.histogram("engine_migration_compile_seconds").observe(
            time.perf_counter() - t0)

    def _backfill(self) -> None:
        """Journaled backfill: TOUCH every tuple on a rewriting relation
        back through the ordinary write path — WAL, replication, and the
        watch log all see the re-derivation, so a crash at any point
        replays or aborts from durable state. Echo revisions are
        recorded and suppressed from watch streams (identical content:
        delivering it would duplicate events across the cut)."""
        self._set_phase(BACKFILL)
        e = self.engine
        from ..engine.store import RelationshipFilter, WriteOp

        for dname, rname in sorted(self._diff.rewrite_relations):
            rels = list(e.read_relationships(
                RelationshipFilter(resource_type=dname, relation=rname)))
            # bulk-loaded graphs can hold duplicate rows for one
            # relationship key; a TOUCH batch must carry each key once
            # (the store's atomic write plan rejects duplicate updates
            # within a single write, latest row wins here)
            uniq: dict = {}
            for r in rels:
                uniq[(r.resource_type, r.resource_id, r.relation,
                      r.subject_type, r.subject_id,
                      r.subject_relation or "")] = r
            rels = list(uniq.values())
            for s in range(0, len(rels), self.batch):
                if self._abort_requested.is_set():
                    return
                part = rels[s:s + self.batch]
                rev = self._write_backfill_batch(
                    [WriteOp("touch", r) for r in part])
                with self._lock:
                    self._backfilled += len(part)
                    self._suppressed.append(rev)
                # arm the watch filter BEFORE any watcher can read the
                # echo (the store already logged it; frozenset swap is
                # atomic for readers)
                e._watch_suppress = e._watch_suppress | {rev}
                metrics.counter(
                    "engine_migration_backfill_rows_total").inc(len(part))
                self._persist()
                if self.backfill_pause:
                    time.sleep(self.backfill_pause)

    def _write_backfill_batch(self, ops) -> int:
        """One journaled batch, shed-aware: overlay backpressure from
        the compactor is obeyed (bounded retry) — backfill rides BELOW
        serving traffic, the same deference the mover shows."""
        e = self.engine
        from ..engine.compaction import OverlayBackpressure

        for attempt in range(8):
            try:
                return e.write_relationships(list(ops))
            except OverlayBackpressure as bp:
                time.sleep(min(getattr(bp, "retry_after", 0.05) or 0.05,
                               0.5))
        return e.write_relationships(list(ops), _headroom=False)

    def _catch_up_once(self) -> int:
        """Replay watch-log records onto the new graph (the dual-apply:
        writes land in the store once, and BOTH graphs see them — the
        serving graph via the engine's own incremental path, the new one
        here). Falls back to a fresh compile when the suffix cannot be
        replayed (trimmed history, bulk load, overflow). Returns lag."""
        e = self.engine
        from ..engine.store import OP_DELETE, StoreError
        from ..ops.reachability import MAX_DELTA_RECORDS, incremental_update

        cg = self._new_cg
        st = e.store
        with st._lock:
            rev = st.revision
            if cg.revision == rev:
                return 0
            records = None
            if cg.revision >= st.unlogged_revision:
                try:
                    records = st.watch_since(cg.revision)
                except StoreError:
                    records = None
        if records is None or len(records) > MAX_DELTA_RECORDS:
            self._compile()  # refold from a newer snapshot
            return max(e.store.revision - self._new_cg.revision, 0)
        if records:
            delta = [(r.op == OP_DELETE, r.rel) for r in records]
            new = incremental_update(cg, delta, rev, st)
            if new is None:
                self._compile()
            else:
                self._new_cg = new
        return max(e.store.revision - self._new_cg.revision, 0)

    def _dual(self) -> None:
        """The dual window: keep the new graph within one overlay append
        of the store while serving continues on the old one. Holds here
        when ``hold_at_dual`` until the coordinator releases the cut."""
        self._set_phase(DUAL)
        e = self.engine
        # install the cutover gate now: entering/exiting an unfrozen
        # gate is two condition-variable ops per write — noise — and
        # having it in place means the cut never races a writer that
        # read `None` just before the freeze
        self._gate = _WriteGate()
        e._write_gate = self._gate
        while True:
            lag = self._catch_up_once()
            with self._lock:
                self._lag = lag
            metrics.gauge("engine_migration_lag").set(lag)
            if self._abort_requested.is_set():
                return
            if lag == 0 and (not self.hold_at_dual
                             or self._cut_requested.is_set()):
                return
            if lag == 0:
                # parked at dual: stay caught up at a gentle cadence
                self._cut_requested.wait(0.05)
            # lag > 0: immediately loop and keep replaying

    def _cut(self) -> None:
        """Atomic cutover: freeze writers, drain to lag zero, machine-
        check unaffected-verdict parity, persist CUT (before the routing
        effect — the crash-matrix pivot), swap schema+graph at the
        UNCHANGED revision, surgically retire affected cache keys,
        thaw."""
        e = self.engine
        gate = self._gate
        t0 = time.perf_counter()
        gate.freeze()
        try:
            lag = self._catch_up_once()
            if lag != 0:  # unreachable while frozen; belt and braces
                raise RuntimeError(f"cut drain left lag {lag}")
            self._check_unaffected_parity()
            self._set_phase(CUT)
            with self._lock:
                self._cut_at = time.time()
            with e._lock:
                e.schema = self._new_schema
                e._compiled = self._new_cg
                e._sharded = None
                e._incremental_declined = None
                cache = e._decision_cache
                if cache is not None:
                    cache.retire_affected(self._affected)
        finally:
            gate.thaw()
            e._write_gate = None
            self._freeze_seconds = time.perf_counter() - t0
            metrics.histogram(
                "engine_migration_cut_freeze_seconds").observe(
                self._freeze_seconds)

    def _check_unaffected_parity(self) -> None:
        """The no-verdict-flap machine check, run INSIDE the freeze so
        both oracles see the identical frozen store: sample permissions
        OUTSIDE the diff's affected closure and require S and S' to
        agree on every sampled (resource, subject) verdict. A mismatch
        means the diff classifier under-approximated — abort the cut
        rather than flap verdicts the classifier promised were
        untouched."""
        if not self.parity_samples:
            return
        e = self.engine
        old_schema = e.schema
        new_schema = self._new_schema
        affected = self._affected
        probes = []
        for dname in sorted(new_schema.definitions):
            d = new_schema.definitions[dname]
            if dname not in old_schema.definitions:
                continue
            for pname in sorted(d.permissions):
                if (dname, pname) in affected:
                    continue
                if pname not in old_schema.definitions[dname].permissions:
                    continue
                probes.append((dname, pname))
        if not probes:
            return
        snap_now = time.time()
        old_oracle = e.oracle(now=snap_now)
        from ..engine.evaluator import OracleEvaluator

        new_oracle = OracleEvaluator(new_schema, e.store.snapshot(),
                                     now=snap_now)
        # deterministic sample: first ids per type from the oracle's own
        # object universe, subjects from the densest subject type
        checked = 0
        for dname, pname in probes:
            rids = sorted(old_oracle.objects.get(dname, ()))[:4]
            subs = []
            for (rt, _rid, _rl), edges in old_oracle.adj.items():
                for st, sid, srl, _cav in edges:
                    if srl is None and sid != "*":
                        subs.append((st, sid))
                if len(subs) >= 4:
                    break
            for rid in rids:
                for st, sid in subs[:4]:
                    a = old_oracle.check(dname, rid, pname, st, sid)
                    b = new_oracle.check(dname, rid, pname, st, sid)
                    if a != b:
                        raise RuntimeError(
                            "unaffected-verdict parity violation at "
                            f"{dname}:{rid}#{pname}@{st}:{sid}: "
                            f"{a} under S vs {b} under S'")
                    checked += 1
                    if checked >= self.parity_samples:
                        return


# ---------------------------------------------------------------------------
# boot-time crash matrix
# ---------------------------------------------------------------------------


def recover(engine, record_path: Optional[str]) -> Optional[dict]:
    """Consult the persisted migration record and resolve it:

    ==================  =====================================================
    persisted phase     action
    ==================  =====================================================
    planned..dual       ABORT: the serving schema never changed; backfill
                        touches were idempotent re-writes of identical
                        content. Re-arm the watch-echo suppression set
                        (those revisions are in the replayed log), then
                        clear the record.
    cut                 RESUME: the cut was persisted before the swap took
                        routing effect — finish it by re-publishing S'
                        (schema + fresh compile at the recovered store),
                        then mark done.
    done                RE-APPLY: the done marker outlives the cut so a
                        boot whose bootstrap still carries S keeps serving
                        S' (the rebalancer's done-marker-vs-stale-flags
                        rule); cleared only when the booted schema already
                        matches.
    ==================  =====================================================
    """
    if not record_path or not os.path.exists(record_path):
        return None
    try:
        with open(record_path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as err:
        # an unreadable record is treated as phase<cut: fail toward the
        # schema the engine actually booted with, never guess a cut
        log.warning("unreadable migration record %s: %s", record_path,
                    err)
        os.replace(record_path, record_path + ".corrupt")
        return {"action": "aborted", "phase": None,
                "error": f"unreadable record: {err}"}
    phase = doc.get("phase")
    suppressed = frozenset(int(r) for r in
                           doc.get("suppressed_revisions", ()))
    if suppressed:
        engine._watch_suppress = engine._watch_suppress | suppressed
    if _PHASE_NUM.get(phase, 0) < _PHASE_NUM[CUT]:
        try:
            os.remove(record_path)
        except FileNotFoundError:
            pass
        metrics.counter("engine_migrations_total",
                        outcome="boot-aborted").inc()
        log.info("migration %s aborted at boot (crashed in %s)",
                 doc.get("to_digest"), phase)
        return {"action": "aborted", "phase": phase,
                "to_digest": doc.get("to_digest")}
    # cut or done: S' is the truth — finish/re-apply it
    from ..models.schema import ir_digest

    new_schema = parse_schema(doc["to_text"])
    if phase == DONE and ir_digest(engine.schema) == ir_digest(new_schema):
        # the bootstrap caught up: the marker has done its job
        try:
            os.remove(record_path)
        except FileNotFoundError:
            pass
        return {"action": "cleared", "phase": phase,
                "to_digest": doc.get("to_digest")}
    with engine._lock:
        engine.schema = new_schema
        engine._compiled = None  # next read compiles under S'
        engine._sharded = None
        engine._incremental_declined = None
    if phase != DONE:
        doc["phase"] = DONE
        doc["updated"] = time.time()
        _atomic_write_json(record_path, doc)
    metrics.counter("engine_migrations_total",
                    outcome="boot-resumed").inc()
    log.info("migration %s resumed at boot (persisted phase %s)",
             doc.get("to_digest"), phase)
    return {"action": "resumed", "phase": phase,
            "to_digest": doc.get("to_digest")}
