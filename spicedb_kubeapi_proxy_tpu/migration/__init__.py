"""Zero-downtime live schema migration (ISSUE 19 / ROADMAP item 5).

The last restart-only operation in the system — a schema change — made
online: diff-classify S -> S', dual-compile the new graph beside the
old, backfill affected tuples through the journaled write path, and cut
atomically at a revision with decision-cache and watch continuity. The
phase machine persists before every routing-effect change, exactly like
the rebalancer's transition record (scaleout/rebalance.py).
"""

from .migrator import (  # noqa: F401
    ABORTED,
    BACKFILL,
    COMPILING,
    CUT,
    DONE,
    DUAL,
    FAILED,
    PLANNED,
    SchemaMigrator,
    recover,
    schema_digest,
)
