"""Online shard rebalancing: the live tuple mover.

PR 11 made the shard map an explicit versioned artifact; changing it
still meant "drain writes + dump/reload the moved slices" — a full stop
for the affected namespaces. This module takes the fleet from map V to
map V+1 **without draining**, the standard shared-nothing
copy/catch-up/cutover protocol (the blocked-matrix repartitioning story
in RedisGraph/GraphBLAS: move blocks, not the world):

1. **plan** — diff the two maps' ring assignments into the *moving
   slice set*: contiguous hash ranges of the partition-key space whose
   owner changes, each ``src -> dst``. Global (cluster-scoped) tuples
   never move — they replicate everywhere by construction; a transition
   that ADDS groups seeds them a replica first.
2. **copy** — export each slice from its source group and import it
   into the destination (``slice_read``/``slice_load`` wire ops riding
   the PR 3 npz codec; idempotent — loads are TOUCHes). Migration
   traffic is admission-classed (``rebalance``, lowest shed priority)
   so it is cost-accounted and sheddable like any tenant; sheds back
   the mover off by the host's Retry-After.
3. **catch-up** — replay the source group's watch history for the
   slice above the copy revision onto the destination (last-per-key
   within a batch; deletes replay too) until the lag is small.
4. **dual-write window** — the planner keeps ROUTING READS at V while
   MIRRORING the slice's writes to both owners through the existing
   split journal, so a mid-window crash of planner or group replays to
   completion rather than forking the copies.
5. **cutover** — per-slice atomic flip: briefly freeze the slice's
   writes (non-moving slices never wait), drain the final catch-up to
   lag zero, record the (src, dst) cut revisions, persist CUT, thaw.
   Reads and writes for the slice now route at V+1.
6. **GC** — once every slice is cut and the planner committed map
   V+1, the source groups drop their moved rows (ordinary journaled
   deletes; the merged watch streams suppress them — see below).

**Watch continuity.** Merged watch streams stay gap-and-duplicate-free
across the flip: for a moving slice, events are delivered from the
slice's *current read owner only* — source events up to its cut
revision, destination events strictly after its cut revision (which
silences the copy/catch-up touches, the dual-write mirrors, and the GC
deletes). Resumption tokens carry the map version they were minted
under (``RevisionVector.encode(map_version=)``); a token from map V
resumed at V+1 is *translated* through the recorded transition (new
groups' components start at zero) instead of misindexed.

**Crash matrix** (chaos-checked): the transition state is persisted in
the split journal's sqlite next to every slice-state change. A crash
before any slice cut → the transition ABORTS cleanly (routing still V;
the destination's partial copies are dropped). A crash after ≥1 slice
cut → the transition is past the point of no return and RESUMES to
completion at the next boot (cut slices' routing is restored before
the first request). Either way: no acked write lost, never fail-open.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_right
from dataclasses import dataclass, field
from typing import Optional

from ..admission import AdmissionRejected
from ..engine.store import OP_DELETE, WriteOp
from ..utils.metrics import metrics
from .shardmap import (
    HASH_SPACE,
    ShardMap,
    ShardMapError,
    hash_key,
    map_from_doc,
    map_to_doc,
    split_resource,
)

import logging

log = logging.getLogger("sdbkp.rebalance")

# slice lifecycle (monotone; persisted on every change)
PLANNED = "planned"
COPYING = "copying"
CATCHUP = "catchup"
DUAL = "dual"
CUT = "cut"

_STATE_ORDER = (PLANNED, COPYING, CATCHUP, DUAL, CUT)


class RebalanceError(ShardMapError):
    pass


@dataclass
class MovingSlice:
    """One contiguous hash-range move ``src -> dst`` under a map
    transition. ``ranges`` are half-open ``[lo, hi)`` intervals over
    the 32-bit partition-key space (a slice that wraps the ring is two
    intervals)."""

    sid: int
    src: int
    dst: int
    ranges: tuple  # ((lo, hi), ...)
    state: str = PLANNED
    copy_rev: int = 0       # src revision at the copy cut
    replayed: int = 0       # src revision caught up through
    src_head: int = 0       # src revision last observed (lag basis)
    src_cut: Optional[int] = None  # src revision at the flip
    dst_cut: Optional[int] = None  # dst revision at the flip
    gate: "_SliceGate" = field(default_factory=lambda: _SliceGate(),
                               repr=False, compare=False)

    def contains(self, h: int) -> bool:
        return any(lo <= h < hi for lo, hi in self.ranges)

    def to_doc(self) -> dict:
        return {"sid": self.sid, "src": self.src, "dst": self.dst,
                "ranges": [list(r) for r in self.ranges],
                "state": self.state, "copy_rev": self.copy_rev,
                "replayed": self.replayed,
                "src_head": self.src_head,
                "src_cut": self.src_cut,
                "dst_cut": self.dst_cut}

    @classmethod
    def from_doc(cls, d: dict) -> "MovingSlice":
        return cls(sid=int(d["sid"]), src=int(d["src"]),
                   dst=int(d["dst"]),
                   ranges=tuple((int(lo), int(hi))
                                for lo, hi in d["ranges"]),
                   state=str(d["state"]), copy_rev=int(d["copy_rev"]),
                   replayed=int(d["replayed"]),
                   src_head=int(d.get("src_head", 0)),
                   src_cut=(None if d.get("src_cut") is None
                            else int(d["src_cut"])),
                   dst_cut=(None if d.get("dst_cut") is None
                            else int(d["dst_cut"])))


class _SliceGate:
    """A tiny writer/freezer gate: writes to a moving slice ``enter``
    (shared — unbounded concurrency), the cutover ``freeze``s (waits
    out in-flight writers, blocks new ones) for the atomic flip, then
    ``thaw``s. Writes to NON-moving slices never touch a gate, so the
    freeze costs only the moving slice's traffic."""

    def __init__(self):
        self._cv = threading.Condition()
        self._writers = 0
        self._frozen = False

    def enter(self) -> None:
        with self._cv:
            while self._frozen:
                self._cv.wait()
            self._writers += 1

    def exit(self) -> None:
        with self._cv:
            self._writers -= 1
            self._cv.notify_all()

    def freeze(self) -> None:
        with self._cv:
            self._frozen = True
            while self._writers:
                self._cv.wait()

    def thaw(self) -> None:
        with self._cv:
            self._frozen = False
            self._cv.notify_all()


def shrink_map(old_map: ShardMap, retire: Optional[int] = None,
               version: Optional[int] = None) -> ShardMap:
    """The one-group-smaller target map of a SHRINK transition:
    ``old_map`` minus the retiring group (default: the LAST one — the
    only index whose removal leaves every survivor's ring points, and
    so every survivor's data placement, untouched)."""
    if old_map.n_groups < 2:
        raise RebalanceError("cannot shrink a single-group map")
    if retire is None:
        retire = old_map.n_groups - 1
    if not 0 <= retire < old_map.n_groups:
        raise RebalanceError(
            f"retire index {retire} out of range for a "
            f"{old_map.n_groups}-group map")
    groups = old_map.groups[:retire] + old_map.groups[retire + 1:]
    return ShardMap(
        version=old_map.version + 1 if version is None else int(version),
        groups=groups, virtual_nodes=old_map.virtual_nodes)


def plan_moves(old_map: ShardMap, new_map: ShardMap,
               retire: Optional[int] = None) -> list:
    """Diff two maps' ring assignments into the moving slice set:
    merge both rings' boundary points, sample each segment's owner
    under both maps, and coalesce adjacent segments with the same
    ``(src, dst)``. Group INDEX is identity across the transition —
    group *i* of the new map is the same logical group as group *i*
    of the old (new maps may append groups; surviving indices keep
    their data except for the diffed slices).

    A SHRINK diff names the ``retire``-d group: the new map has one
    fewer group and its indices renumber past the gap, so new-map
    owners translate back into the OLD index space (``ni`` -> ``ni``
    below the gap, ``ni + 1`` at or above it) — the emitted slices'
    ``src``/``dst`` always address the planner's CURRENT clients, and
    every slice the retiring group owned moves off it."""
    bounds = sorted(set(old_map.ring_points())
                    | set(new_map.ring_points()))
    if not bounds:
        return []

    def _dst(h: int) -> int:
        ni = new_map.owner_of_hash(h)
        if retire is not None and ni >= retire:
            return ni + 1
        return ni

    segs = []  # (lo, hi, src, dst) half-open over [0, HASH_SPACE)
    # segment starting at each boundary, up to the next one; the ring
    # wraps, so the last boundary's segment splits into [last, 2^32)
    # and [0, first)
    for i, lo in enumerate(bounds):
        hi = bounds[i + 1] if i + 1 < len(bounds) else HASH_SPACE
        src = old_map.owner_of_hash(lo)
        dst = _dst(lo)
        if src != dst:
            segs.append((lo, hi, src, dst))
    lo0 = bounds[0]
    if lo0 > 0:
        src = old_map.owner_of_hash(0)
        dst = _dst(0)
        if src != dst:
            segs.append((0, lo0, src, dst))
    segs.sort()
    # coalesce adjacent segments moving the same way into one slice,
    # then group every (src, dst) pair's ranges into ONE slice so the
    # protocol runs once per directed move, not once per ring fragment
    merged: dict[tuple, list] = {}
    for lo, hi, src, dst in segs:
        rs = merged.setdefault((src, dst), [])
        if rs and rs[-1][1] == lo:
            rs[-1] = (rs[-1][0], hi)
        else:
            rs.append((lo, hi))
    out = []
    for sid, ((src, dst), rs) in enumerate(sorted(merged.items())):
        out.append(MovingSlice(sid=sid, src=src, dst=dst,
                               ranges=tuple(rs)))
    return out


class MapTransition:
    """The versioned-transition state the planner routes through while
    a rebalance is live: which slices are moving, how far each has
    progressed, and the event-delivery filter that keeps merged watch
    streams exact across the flip. Thread-safe; every state change is
    persisted by the coordinator before it takes routing effect."""

    def __init__(self, old_map: ShardMap, new_map: ShardMap,
                 slices: list, retire: Optional[int] = None):
        if new_map.version <= old_map.version:
            raise RebalanceError(
                f"rebalance target map version {new_map.version} must "
                f"exceed the current version {old_map.version}")
        if retire is None and new_map.n_groups < old_map.n_groups:
            raise RebalanceError(
                "a transition to a smaller map must name the retiring "
                "group (plan it through begin_rebalance / shrink_map)")
        if retire is not None:
            if new_map.n_groups != old_map.n_groups - 1:
                raise RebalanceError(
                    "a shrink transition retires exactly ONE group per "
                    f"map version (old {old_map.n_groups} groups, new "
                    f"{new_map.n_groups})")
            if not 0 <= retire < old_map.n_groups:
                raise RebalanceError(
                    f"retire index {retire} out of range for a "
                    f"{old_map.n_groups}-group map")
        self.old_map = old_map
        self.new_map = new_map
        # SHRINK: the OLD-space index of the group this transition
        # empties and removes (None for grow/steady transitions). The
        # slices' src/dst stay in old index space throughout — the
        # planner renumbers only at commit.
        self.retire = retire
        # set at commit: the retiring group's final delivered revision
        # (max src-side cut over its outgoing slices) — the watermark
        # resumption-token translation checks before dropping the
        # component (a token below it missed src-era events that no
        # surviving group will ever re-deliver)
        self.retire_cut: Optional[int] = None
        self.slices = list(slices)
        self._lock = threading.Lock()
        # range index for slice_for: sorted (lo, hi, slice)
        ivals = []
        for sl in self.slices:
            for lo, hi in sl.ranges:
                ivals.append((lo, hi, sl))
        ivals.sort(key=lambda t: t[0])
        self._los = [t[0] for t in ivals]
        self._ivals = ivals
        # groups the NEW map adds (their stores start empty; the
        # coordinator seeds the replicated global tuples first)
        self.new_groups = tuple(range(old_map.n_groups,
                                      new_map.n_groups))
        # gi -> the group's revision after its global seed landed:
        # global-tuple events on an added group at or below this are
        # seed echoes of tuples every watcher already saw replicated
        # on the old groups — suppressed from merged streams
        self.seed_cuts: dict = {}
        self.globals_seeded = threading.Event()
        if not self.new_groups:
            self.globals_seeded.set()
        # True once the post-cutover GC finished: no source group holds
        # a moved copy anymore, so the planner's scatter-merge owner
        # filters have nothing left to guard against for this
        # transition (the watch-delivery era walk stays — history
        # replays still span the cutover)
        self.gc_complete = False

    def retire_watermark(self) -> Optional[int]:
        """The retiring group's final delivered revision: the max
        src-side cut over its outgoing slices (0 when it owned no
        moving slice). A resumption token whose retiring component is
        at or past this has consumed every event the group's eras will
        ever deliver — everything later lives in the destinations'
        histories past their dst cuts."""
        if self.retire is None:
            return None
        return max((int(sl.src_cut or 0) for sl in self.slices
                    if sl.src == self.retire), default=0)

    # -- membership ----------------------------------------------------------

    def slice_for(self, resource_type: str,
                  resource_id: str) -> Optional[MovingSlice]:
        ns, namespaced = split_resource(resource_id)
        if not namespaced:
            return None
        return self.slice_for_key(ns, resource_type)

    def slice_for_key(self, namespace: str,
                      resource_type: str) -> Optional[MovingSlice]:
        h = hash_key(namespace, resource_type)
        i = bisect_right(self._los, h) - 1
        if i >= 0:
            lo, hi, sl = self._ivals[i]
            if lo <= h < hi:
                return sl
        return None

    # -- slice state (locked) ------------------------------------------------

    def set_state(self, sl: MovingSlice, state: str, **fields) -> None:
        with self._lock:
            sl.state = state
            for k, v in fields.items():
                setattr(sl, k, v)

    def state_of(self, sl: MovingSlice) -> str:
        with self._lock:
            return sl.state

    def all_cut(self) -> bool:
        with self._lock:
            return all(sl.state == CUT for sl in self.slices)

    def any_cut(self) -> bool:
        with self._lock:
            return any(sl.state == CUT for sl in self.slices)

    def progress(self) -> dict:
        """The /readyz ``rebalance:`` line's numbers."""
        with self._lock:
            moving = len(self.slices)
            copied = sum(1 for sl in self.slices
                         if _STATE_ORDER.index(sl.state)
                         >= _STATE_ORDER.index(CATCHUP))
            cut = sum(1 for sl in self.slices if sl.state == CUT)
            # catch-up distance of the in-flight slices: the source
            # head last observed minus the replay watermark (copy_rev
            # is a floor the watermark starts AT, never ahead of)
            lag = max((sl.src_head - sl.replayed
                       for sl in self.slices
                       if sl.state in (COPYING, CATCHUP, DUAL)),
                      default=0)
        return {"to_version": self.new_map.version, "moving": moving,
                "copied": copied, "cut": cut, "lag": max(0, lag)}

    # -- routing -------------------------------------------------------------

    def read_owner(self, sl: MovingSlice) -> int:
        """Reads route at V until the slice's atomic flip, at V+1
        after."""
        with self._lock:
            return sl.dst if sl.state == CUT else sl.src

    def write_owners(self, sl: MovingSlice) -> tuple:
        """Writes route at V before the dual-write window opens,
        mirror to BOTH owners during it, and route at V+1 after the
        flip."""
        with self._lock:
            if sl.state == CUT:
                return (sl.dst,)
            if sl.state == DUAL:
                return (sl.src, sl.dst)
            return (sl.src,)

    # -- watch-event delivery filter -----------------------------------------
    # The read-owner-only delivery rule is evaluated by the PLANNER as
    # an era walk over the whole transition sequence (a slice can move
    # A->B in one transition and B->A in a later one — a single
    # transition's view would suppress the later era's legitimate
    # events). Each transition contributes its cut table via
    # ``cut_info`` and the group-local global-seed guard below.

    def cut_info(self, sl: MovingSlice) -> tuple:
        """(state, src_cut, dst_cut) snapshot for the era walk."""
        with self._lock:
            return sl.state, sl.src_cut, sl.dst_cut

    def deliver_global(self, gi: int, revision: int) -> bool:
        """A GLOBAL tuple's event on a transition-added group: the seed
        copy (and anything before it completed) is an echo of tuples
        every watcher already saw replicated on the old groups; genuine
        post-seed global writes replicate there like everywhere."""
        if gi not in self.new_groups:
            return True
        with self._lock:
            cut = self.seed_cuts.get(gi)
        return cut is not None and revision > cut

    # -- persistence ---------------------------------------------------------

    def to_doc(self, phase: str = "running") -> dict:
        with self._lock:
            seed_cuts = {str(k): v for k, v in self.seed_cuts.items()}
        return {"phase": phase,
                "old_version": self.old_map.version,
                "new_map": map_to_doc(self.new_map),
                "seed_cuts": seed_cuts,
                "retire": self.retire,
                "retire_cut": self.retire_cut,
                # shrink runs GC BEFORE commit (old indices must still
                # name the mover's clients), so the crash matrix needs
                # the GC watermark durably, not implied by the phase
                "gc_complete": bool(self.gc_complete),
                "slices": [sl.to_doc() for sl in self.slices]}

    @classmethod
    def from_doc(cls, doc: dict, old_map: ShardMap) -> "MapTransition":
        if int(doc["old_version"]) != old_map.version:
            raise RebalanceError(
                f"persisted transition left map version "
                f"{doc['old_version']}, but the planner booted with "
                f"version {old_map.version}; refusing to guess which "
                "placement is authoritative")
        new_map = map_from_doc(doc["new_map"])
        t = cls(old_map, new_map,
                [MovingSlice.from_doc(d) for d in doc["slices"]],
                retire=(None if doc.get("retire") is None
                        else int(doc["retire"])))
        t.retire_cut = (None if doc.get("retire_cut") is None
                        else int(doc["retire_cut"]))
        t.gc_complete = bool(doc.get("gc_complete", False))
        t.seed_cuts = {int(k): int(v)
                       for k, v in (doc.get("seed_cuts") or {}).items()}
        # a restart loses the in-memory seeded latch (the coordinator
        # re-seeds idempotently on resume anyway)
        if t.seed_cuts:
            t.globals_seeded.set()
        return t


class RebalanceCoordinator:
    """Drives one map transition end to end on a background thread.
    All data movement is idempotent (touch loads, last-per-key catch-up
    replays, delete GC), so every phase is safe to re-run after a crash
    of the coordinator or a failover inside either group."""

    def __init__(self, planner, transition: MapTransition, *,
                 batch_rows: int = 2048, pace_seconds: float = 0.0,
                 cut_lag: int = 8, poll_seconds: float = 0.05):
        self.planner = planner
        self.t = transition
        self.batch_rows = max(1, int(batch_rows))
        # optional pacing between copy/catch-up batches: stretches the
        # move so migration bandwidth stays a bounded fraction of the
        # hosts' capacity even before admission pushes back
        self.pace_seconds = max(0.0, float(pace_seconds))
        # catch-up converges to this lag (in src revisions) before the
        # freeze; the frozen drain then takes lag -> 0
        self.cut_lag = max(0, int(cut_lag))
        self.poll_seconds = max(0.005, float(poll_seconds))
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._pause = threading.Event()
        self._in_cutover = False
        self._done = threading.Event()
        self.error: Optional[BaseException] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "RebalanceCoordinator":
        self._thread = threading.Thread(target=self._run,
                                        name="shard-rebalance",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Ask the mover to park (the persisted state stays; a later
        coordinator — or the next boot — resumes or aborts by the
        crash matrix)."""
        self._stop.set()

    def pause(self) -> None:
        """Suspend data movement in place (operator lever: quiesce a
        migration during an incident without losing its progress).
        Routing keeps whatever state each slice already reached; the
        one non-pausable stretch is a cutover's frozen drain — it
        completes first, because pausing it would leave the slice's
        writers parked on the gate."""
        self._pause.set()

    def resume(self) -> None:
        self._pause.clear()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def _run(self) -> None:
        try:
            self.run_to_completion()
        except BaseException as e:  # noqa: BLE001 - surfaced via .error
            # the mover is a background maintenance loop: its failure
            # must park the transition VISIBLY (state persisted, routing
            # unchanged, /readyz still reporting the window) rather
            # than unwind the serving path. The crash matrix takes it
            # from here: resume-or-abort at the next coordinator/boot.
            self.error = e
            metrics.counter("scaleout_rebalance_transitions_total",
                            outcome="failed").inc()
            log.exception("rebalance to map v%d parked: %s",
                          self.t.new_map.version, e)
        finally:
            self._done.set()

    # -- helpers -------------------------------------------------------------

    def _client(self, gi: int):
        return self.planner.groups[gi]

    def _persist(self, phase: str = "running") -> None:
        j = self.planner.journal
        if j is not None:
            j.save_transition(self.t.to_doc(phase))

    def _backoff(self, e: AdmissionRejected) -> None:
        metrics.counter("scaleout_rebalance_shed_backoff_total").inc()
        self._sleep(min(5.0, max(0.05, float(e.retry_after or 0.25))))

    def _sleep(self, s: float) -> None:
        if s > 0:
            self._stop.wait(s)

    def _check_stop(self) -> None:
        if self._stop.is_set():
            raise RebalanceError("rebalance coordinator stopped")
        while self._pause.is_set() and not self._in_cutover:
            if self._stop.is_set():
                raise RebalanceError("rebalance coordinator stopped")
            time.sleep(0.02)

    def _call_shed_aware(self, fn):
        """Run one mover op; admission sheds back the mover off and
        retry — migration traffic yields to tenant traffic by design,
        it never fails the transition."""
        while True:
            self._check_stop()
            try:
                return fn()
            except AdmissionRejected as e:
                self._backoff(e)
                continue

    # -- slice data plane (native wire ops, or the in-process fallback) ------

    def _slice_read(self, gi: int, ranges, want_globals=False):
        """(src_revision, [Relationship...]) for the slice."""
        c = self._client(gi)
        if hasattr(c, "slice_read"):
            return self._call_shed_aware(
                lambda: c.slice_read(ranges, want_globals=want_globals))
        return _local_slice_read(c, ranges, want_globals=want_globals)

    def _slice_load(self, gi: int, rels) -> int:
        """Idempotent TOUCH import, chunked; returns rows loaded."""
        c = self._client(gi)
        n = 0
        for i in range(0, len(rels), self.batch_rows):
            chunk = rels[i:i + self.batch_rows]
            self._check_stop()
            if hasattr(c, "slice_load"):
                self._call_shed_aware(lambda _c=chunk: c.slice_load(_c))
            else:
                self._call_shed_aware(
                    lambda _c=chunk: _apply_po2_local(
                        c, [WriteOp("touch", r) for r in _c]))
            n += len(chunk)
            metrics.counter(
                "scaleout_rebalance_copied_rows_total").inc(len(chunk))
            self._sleep(self.pace_seconds)
        return n

    def _slice_drop(self, gi: int, ranges) -> int:
        """GC: delete the moved rows from the source through its
        ordinary journaled/replicated write path (the merged watch
        streams suppress these deletes past the slice's cut)."""
        c = self._client(gi)
        if hasattr(c, "slice_drop"):
            n = self._call_shed_aware(lambda: c.slice_drop(ranges))
        else:
            _, rows = _local_slice_read(c, ranges)
            n = 0
            for i in range(0, len(rows), self.batch_rows):
                chunk = rows[i:i + self.batch_rows]
                self._call_shed_aware(
                    lambda _c=chunk: _apply_po2_local(
                        c, [WriteOp("delete", r) for r in _c]))
                n += len(chunk)
        metrics.counter("scaleout_rebalance_gc_rows_total").inc(n)
        return n

    def _src_revision(self, gi: int) -> int:
        rev = self._call_shed_aware(
            lambda: self._client(gi).revision)
        return int(rev)

    _REPLAY_CHUNK = 2048

    def _catch_up_once(self, sl: MovingSlice,
                       frozen: bool = False) -> int:
        """Replay one round of src watch history above ``replayed``
        onto dst (slice-filtered, last-per-key); returns the remaining
        lag in src revisions. ``frozen`` marks the cutover drain (the
        slice's writers are parked on the gate until it ends)."""
        src = self._client(sl.src)
        if hasattr(src, "slice_watch_since"):
            events = self._call_shed_aware(
                lambda: src.slice_watch_since(int(sl.replayed)))
        else:
            events = self._call_shed_aware(
                lambda: src.watch_since(int(sl.replayed)))
        last = sl.replayed
        final: dict[tuple, tuple] = {}
        for e in events:
            rev = int(e.revision)
            last = max(last, rev)
            rel = e.relationship
            if not sl.contains(hash_key(
                    split_resource(rel.resource_id)[0],
                    rel.resource_type)):
                continue
            # the Engine surface (and the wire) stamp events with the
            # STRING op; the store's raw records carry the int code —
            # accept both, and treat only a positive delete as one (a
            # replayed delete mis-read as touch would resurrect the
            # revoked grant on the new owner)
            op = "delete" if e.operation in ("delete", OP_DELETE) \
                else "touch"
            final[rel.key()] = (op, rel)
        if final:
            # ONE write op per round when the backlog fits: the
            # destination pays per-OP cost (incremental device update),
            # so batching the round's backlog is strictly cheaper than
            # trickling it — the round CADENCE (poll_seconds) is the
            # politeness knob here, while replay bandwidth is inherently
            # 1:1 with the slice's own write rate, never a bulk copy
            ops = [WriteOp(op, rel) for op, rel in final.values()]
            dst = self._client(sl.dst)
            for i in range(0, len(ops), self._REPLAY_CHUNK):
                chunk = ops[i:i + self._REPLAY_CHUNK]
                if hasattr(dst, "slice_apply"):
                    self._call_shed_aware(
                        lambda _c=chunk: dst.slice_apply(_c))
                else:
                    self._call_shed_aware(
                        lambda _c=chunk: _apply_po2_local(dst, _c))
            metrics.counter(
                "scaleout_rebalance_replayed_events_total").inc(
                    len(ops))
        head = self._src_revision(sl.src)
        self.t.set_state(sl, sl.state, replayed=last,
                         src_head=int(head))
        lag = max(0, head - last)
        metrics.gauge("scaleout_rebalance_lag_revisions").set(lag)
        return lag

    # -- the protocol --------------------------------------------------------

    def run_to_completion(self) -> None:
        t0 = time.monotonic()
        metrics.gauge("scaleout_rebalance_active").set(1)
        metrics.gauge("scaleout_rebalance_slices_moving").set(
            len(self.t.slices))
        try:
            self._persist()
            self._seed_globals()
            for sl in self.t.slices:
                if self.t.state_of(sl) != CUT:
                    self._move_slice(sl)
            if self.t.retire is not None:
                # SHRINK: GC runs BEFORE commit — the slices' src/dst
                # are OLD-space indices, and commit removes the retiring
                # group from the planner's client list, so post-commit
                # the mover could no longer address the sources. Safe
                # ordering: every slice is cut (reads/writes route to
                # dst), the active transition keeps the scatter-merge
                # owner filter up, and the era filter suppresses the GC
                # deletes — exactly the grow-GC guarantees, one phase
                # earlier. A crash in here resumes via any/all-cut with
                # the persisted gc_complete deciding whether GC re-runs
                # (idempotent deletes either way).
                if not self.t.gc_complete:
                    self._gc()
                    self.t.gc_complete = True
                    self._persist()
                self.planner.commit_rebalance(self.t)
            else:
                self.planner.commit_rebalance(self.t)
                self._persist("committed")
                self._gc()
                self.t.gc_complete = True
            # the record flips to phase "done" instead of clearing:
            # a restart whose CLI flags still say --shard-map V
            # --rebalance-to V+1 must find durable proof that V+1 is
            # already authoritative — re-running the move against the
            # GC'd source would route the moved slices to empty groups
            # (an authorization outage). The record clears only when a
            # boot sees --shard-map naming the new version itself.
            self._persist("done")
            metrics.counter("scaleout_rebalance_transitions_total",
                            outcome="completed").inc()
            log.info("rebalance to map v%d complete in %.2fs",
                     self.t.new_map.version, time.monotonic() - t0)
        finally:
            metrics.gauge("scaleout_rebalance_active").set(0)
            metrics.gauge("scaleout_rebalance_lag_revisions").set(0)

    def _seed_globals(self) -> None:
        """A transition that ADDS groups first gives each new group the
        replicated global slice (idempotent TOUCH copy from group 0;
        concurrent global writes already mirror to new groups from the
        moment the transition installed)."""
        if not self.t.new_groups:
            return
        _, rows = self._slice_read(0, (), want_globals=True)
        for gi in self.t.new_groups:
            self._slice_load(gi, rows)
            # the seed cut: the group's revision once its global
            # replica is complete — merged streams suppress the seed's
            # echo events at or below it
            cut = self._src_revision(gi)
            with self.t._lock:
                self.t.seed_cuts[gi] = cut
        self.t.globals_seeded.set()
        self._persist()

    def _move_slice(self, sl: MovingSlice) -> None:
        t0 = time.monotonic()
        # resuming a crash-interrupted slice: the persisted ``replayed``
        # watermark is where delete coverage on the destination ENDS. A
        # re-copy reflects deletions only by absence — it never removes
        # the destination's stale copy of a tuple deleted between the
        # old watermark and the new copy cut — so catch-up must restart
        # from the OLD watermark, not the fresh copy revision (replay
        # is last-per-key idempotent; a trimmed watch history there
        # fails loud instead of resuming with a fail-open hole)
        resume_from = int(sl.replayed) if sl.copy_rev else None
        # copy = REPLACE: drop whatever the destination already holds
        # in the slice's ranges first. Stale leftovers (an earlier
        # transition aborted with the destination unreachable, a
        # crash-window mirror) are indistinguishable from live rows to
        # the load's touches — without the drop, a tuple REVOKED on the
        # source between that leftover and this copy would survive on
        # the new owner (the copy reflects deletions only by absence).
        self.t.set_state(sl, COPYING)
        self._persist()
        self._slice_drop(sl.dst, sl.ranges)
        # copy: revision FIRST, rows second — anything that lands
        # between the two shows up in the catch-up replay (touches are
        # idempotent, at-least-once)
        copy_rev, rows = self._slice_read(sl.src, sl.ranges)
        self._slice_load(sl.dst, rows)
        start = int(copy_rev) if resume_from is None \
            else min(resume_from, int(copy_rev))
        self.t.set_state(sl, CATCHUP, copy_rev=int(copy_rev),
                         replayed=start)
        self._persist()
        # catch-up until the replay is close to the src head
        while self._catch_up_once(sl) > self.cut_lag:
            self._sleep(self.poll_seconds)
        # dual-write window: new writes mirror to both owners from here
        # (through the split journal — a crash replays to completion);
        # one more catch-up pass covers the gap between the last replay
        # and the window opening
        self.t.set_state(sl, DUAL)
        self._persist()
        while self._catch_up_once(sl) > self.cut_lag:
            self._sleep(self.poll_seconds)
        # cutover: freeze the slice's writes, drain to lag zero, record
        # the cut revisions, persist CUT (the point of no return for
        # this slice), flip routing, thaw
        sl.gate.freeze()
        self._in_cutover = True
        try:
            while self._catch_up_once(sl, frozen=True) > 0:
                # the slice's writers are parked on the gate, so the
                # head stops advancing almost immediately; the tiny
                # sleep keeps this drain from spinning wire ops at the
                # source while it waits for that instant
                time.sleep(0.01)
            src_cut = self._src_revision(sl.src)
            dst_cut = self._src_revision(sl.dst)
            # persist CUT BEFORE it takes routing effect (the class
            # contract): the gate is frozen, so no writer can observe
            # the in-between — but a persist failure here must park the
            # coordinator with routing STILL at DUAL, never serve a
            # flip the durable record doesn't know about (a later boot
            # would route reads back to a source that missed dst-only
            # acked writes)
            doc = self.t.to_doc()
            for d in doc["slices"]:
                if d["sid"] == sl.sid:
                    d.update(state=CUT, src_cut=src_cut,
                             dst_cut=dst_cut)
            j = self.planner.journal
            if j is not None:
                j.save_transition(doc)
            self.t.set_state(sl, CUT, src_cut=src_cut, dst_cut=dst_cut)
            metrics.counter("scaleout_rebalance_cutovers_total").inc()
        finally:
            self._in_cutover = False
            sl.gate.thaw()
        metrics.gauge("scaleout_rebalance_slices_cut").set(
            sum(1 for s in self.t.slices if s.state == CUT))
        metrics.histogram("scaleout_rebalance_slice_seconds").observe(
            time.monotonic() - t0)

    def _gc(self) -> None:
        for sl in self.t.slices:
            self._slice_drop(sl.src, sl.ranges)
            # GC is pure cleanup — pace it like the copy so the
            # post-cutover deletes don't burst the source host
            self._sleep(self.pace_seconds)


# -- in-process fallbacks ------------------------------------------------------
# The coordinator drives remote groups through the slice_* wire ops
# (engine/remote.py); raw in-process Engines (tests, single-box
# deployments) get the same semantics computed client-side.


def _apply_po2_local(engine, ops):
    """In-process fallback apply — the SAME po2-chunked helper the
    slice wire ops run server-side (one owner: engine/remote.py)."""
    from ..engine.remote import _apply_po2

    _apply_po2(engine, ops, None)


def _local_slice_read(engine, ranges, want_globals: bool = False):
    """In-process fallback export — the SAME row filter the slice_read
    wire op runs server-side (one owner: engine/remote.py), with the
    revision read BEFORE the scan."""
    from ..engine.remote import _slice_rows

    rev = int(engine.revision)
    return rev, _slice_rows(engine, ranges, want_globals)


def abort_transition(planner, transition: MapTransition) -> None:
    """Cleanly abort a transition no slice of which has cut: drop the
    destination groups' partial copies (idempotent deletes) and clear
    the persisted record. Routing never left map V, so the abort is
    invisible to correctness — only the copy work is discarded."""
    if transition.any_cut():
        raise RebalanceError(
            "transition has cut slices — past the point of no return; "
            "it must be resumed to completion, not aborted")
    for sl in transition.slices:
        dst = None
        close_dst = False
        if sl.dst < len(planner.groups):
            dst = planner.groups[sl.dst]
        elif planner.client_factory is not None:
            # a transition-ADDED group the aborting planner never
            # installed: build a throwaway client from the target map's
            # endpoints — its partial copies would otherwise outlive
            # the abort (inert until a later move makes it an owner)
            try:
                dst = planner.client_factory(
                    transition.new_map.groups[sl.dst])
                close_dst = True
            except Exception as e:  # noqa: BLE001 - abort best-effort
                log.warning("abort: no client for added group %d: %s",
                            sl.dst, e)
        if dst is None:
            log.warning(
                "abort: slice %d copies on group %d unreachable; they "
                "stay inert until the next move's copy-replace drops "
                "them", sl.sid, sl.dst)
            continue
        try:
            if hasattr(dst, "slice_drop"):
                dst.slice_drop(sl.ranges)
            else:
                _, rows = _local_slice_read(dst, sl.ranges)
                if rows:
                    _apply_po2_local(
                        dst, [WriteOp("delete", r) for r in rows])
        except Exception as e:  # noqa: BLE001 - abort is best-effort
            # an unreachable dst keeps its (inert) copies; the next
            # transition's copy-replace drops them before any load
            log.warning("abort: could not drop slice %d copies on "
                        "group %d: %s", sl.sid, sl.dst, e)
        finally:
            if close_dst:
                try:
                    dst.close()
                except Exception:  # noqa: BLE001 - teardown best effort
                    pass
    if planner.journal is not None:
        planner.journal.clear_transition()
    metrics.counter("scaleout_rebalance_transitions_total",
                    outcome="aborted").inc()


__all__ = [
    "CATCHUP", "COPYING", "CUT", "DUAL", "PLANNED",
    "MapTransition", "MovingSlice", "RebalanceCoordinator",
    "RebalanceError", "abort_transition", "plan_moves", "shrink_map",
]
