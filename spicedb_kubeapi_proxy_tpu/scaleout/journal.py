"""Durable intent journal for cross-shard split writes.

A write whose tuples span shards loses single-store atomicity: the
planner applies one per-shard sub-write at a time through each group's
ordinary WAL/ack path. This journal is the dtx-style safety net around
that split — the same event-sourced idea as ``dtx/runner.py``'s workflow
log, specialized to the one deterministic workflow a split write is:

1. ``begin()`` records the FULL per-shard plan (ops + preconditions +
   map version) durably BEFORE the first shard is touched;
2. ``mark_applied()`` records each shard's completion as its group acks;
3. ``finish()`` deletes the entry once every shard has applied.

A crash mid-split leaves the entry with a partial ``applied`` set; the
next planner over the same journal replays the REMAINING shards to
completion (``pending()``), with creates degraded to touches so the
replay is idempotent against a shard that applied but crashed before
``mark_applied`` landed. Fail-closed direction: a split is either
completed or still visibly pending — never silently half-applied.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
import uuid

from ..utils.metrics import metrics


class SplitJournal:
    """SQLite-backed (same durability story as the dtx workflow DB —
    and defaulting to the same directory). Thread-safe: the planner's
    scatter pool shares one connection under a lock."""

    def __init__(self, db_path: str):
        self.db_path = db_path
        d = os.path.dirname(os.path.abspath(db_path))
        os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._db = sqlite3.connect(db_path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS split_writes ("
            " id TEXT PRIMARY KEY,"
            " created REAL NOT NULL,"
            " map_version INTEGER NOT NULL,"
            " plan TEXT NOT NULL,"       # JSON {shard: [op dicts...]}
            " preconditions TEXT NOT NULL,"
            " applied TEXT NOT NULL,"    # JSON [shard, ...]
            # rebalance dual-writes are journaled under BOTH versions:
            # the map the split routed by and the transition's target
            # (NULL outside a rebalance window) — so replay after a
            # mid-window crash knows the recorded owners are already
            # the union of both placements, not a stale single-map plan
            " map_version_to INTEGER)")
        self._migrate()
        # the live-rebalance transition record: at most ONE row — the
        # tuple mover persists every slice-state change here before it
        # takes routing effect (the crash matrix's source of truth)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS rebalance_transition ("
            " id INTEGER PRIMARY KEY CHECK (id = 0),"
            " updated REAL NOT NULL,"
            " doc TEXT NOT NULL)")
        # the planner's coordinated schema-migration record: same
        # single-row persist-before-effect discipline on the schema axis
        # (migration/migrator.py holds each group's per-engine record;
        # this one holds the cross-group cut decision)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS schema_migration ("
            " id INTEGER PRIMARY KEY CHECK (id = 0),"
            " updated REAL NOT NULL,"
            " doc TEXT NOT NULL)")
        self._db.commit()

    def _migrate(self) -> None:
        """Journals created before the rebalance PR lack the
        ``map_version_to`` column; add it in place (NULL for every
        pre-existing entry — exactly the "no transition" meaning)."""
        cols = {r[1] for r in self._db.execute(
            "PRAGMA table_info(split_writes)").fetchall()}
        if "map_version_to" not in cols:
            self._db.execute("ALTER TABLE split_writes "
                             "ADD COLUMN map_version_to INTEGER")

    # -- write path ----------------------------------------------------------

    def begin(self, plan: dict, preconditions: list,
              map_version: int,
              map_version_to: "int | None" = None) -> str:
        """Durably record the split BEFORE any shard applies; returns
        the entry id. ``plan`` maps shard index -> serialized op list.
        ``map_version_to`` tags splits planned inside a rebalance
        window with the transition's target version."""
        sid = uuid.uuid4().hex
        with self._lock:
            self._db.execute(
                "INSERT INTO split_writes VALUES (?,?,?,?,?,?,?)",
                (sid, time.time(), map_version,
                 json.dumps({str(k): v for k, v in plan.items()}),
                 json.dumps(preconditions), json.dumps([]),
                 map_version_to))
            self._db.commit()
        metrics.counter("scaleout_split_writes_total").inc()
        return sid

    def mark_applied(self, sid: str, shard: int) -> None:
        with self._lock:
            row = self._db.execute(
                "SELECT applied FROM split_writes WHERE id=?",
                (sid,)).fetchone()
            if row is None:
                return
            applied = set(json.loads(row[0]))
            applied.add(int(shard))
            self._db.execute(
                "UPDATE split_writes SET applied=? WHERE id=?",
                (json.dumps(sorted(applied)), sid))
            self._db.commit()

    def finish(self, sid: str) -> None:
        with self._lock:
            self._db.execute("DELETE FROM split_writes WHERE id=?",
                             (sid,))
            self._db.commit()

    # -- recovery ------------------------------------------------------------

    def pending(self) -> list[dict]:
        """Every unfinished split, oldest first: ``{id, map_version,
        map_version_to, plan: {shard int: [op dicts]}, preconditions,
        applied: set}``."""
        with self._lock:
            rows = self._db.execute(
                "SELECT id, map_version, plan, preconditions, applied, "
                "map_version_to FROM split_writes "
                "ORDER BY created").fetchall()
        out = []
        for sid, ver, plan, pcs, applied, ver_to in rows:
            out.append({
                "id": sid,
                "map_version": int(ver),
                "map_version_to": (None if ver_to is None
                                   else int(ver_to)),
                "plan": {int(k): v
                         for k, v in json.loads(plan).items()},
                "preconditions": json.loads(pcs),
                "applied": set(json.loads(applied)),
            })
        return out

    def pending_count(self) -> int:
        with self._lock:
            (n,) = self._db.execute(
                "SELECT COUNT(*) FROM split_writes").fetchone()
        return int(n)

    # -- rebalance transition record -----------------------------------------

    def save_transition(self, doc: dict) -> None:
        """Upsert THE transition record (one live transition at a
        time); called before every slice-state change takes routing
        effect, so a crash recovers to the exact persisted phase."""
        with self._lock:
            self._db.execute(
                "INSERT INTO rebalance_transition (id, updated, doc) "
                "VALUES (0, ?, ?) ON CONFLICT(id) DO UPDATE SET "
                "updated=excluded.updated, doc=excluded.doc",
                (time.time(), json.dumps(doc)))
            self._db.commit()

    def load_transition(self) -> "dict | None":
        with self._lock:
            row = self._db.execute(
                "SELECT doc FROM rebalance_transition WHERE id=0"
            ).fetchone()
        return None if row is None else json.loads(row[0])

    def clear_transition(self) -> None:
        with self._lock:
            self._db.execute("DELETE FROM rebalance_transition")
            self._db.commit()

    # -- coordinated schema-migration record ---------------------------------

    def save_migration(self, doc: dict) -> None:
        """Upsert THE cross-group migration record (one live migration
        at a time): persisted before the planner issues any
        routing-effect change to the groups, so a planner crash
        recovers to the exact coordination phase."""
        with self._lock:
            self._db.execute(
                "INSERT INTO schema_migration (id, updated, doc) "
                "VALUES (0, ?, ?) ON CONFLICT(id) DO UPDATE SET "
                "updated=excluded.updated, doc=excluded.doc",
                (time.time(), json.dumps(doc)))
            self._db.commit()

    def load_migration(self) -> "dict | None":
        with self._lock:
            row = self._db.execute(
                "SELECT doc FROM schema_migration WHERE id=0"
            ).fetchone()
        return None if row is None else json.loads(row[0])

    def clear_migration(self) -> None:
        with self._lock:
            self._db.execute("DELETE FROM schema_migration")
            self._db.commit()

    def close(self) -> None:
        with self._lock:
            self._db.close()
