"""Durable intent journal for cross-shard split writes.

A write whose tuples span shards loses single-store atomicity: the
planner applies one per-shard sub-write at a time through each group's
ordinary WAL/ack path. This journal is the dtx-style safety net around
that split — the same event-sourced idea as ``dtx/runner.py``'s workflow
log, specialized to the one deterministic workflow a split write is:

1. ``begin()`` records the FULL per-shard plan (ops + preconditions +
   map version) durably BEFORE the first shard is touched;
2. ``mark_applied()`` records each shard's completion as its group acks;
3. ``finish()`` deletes the entry once every shard has applied.

A crash mid-split leaves the entry with a partial ``applied`` set; the
next planner over the same journal replays the REMAINING shards to
completion (``pending()``), with creates degraded to touches so the
replay is idempotent against a shard that applied but crashed before
``mark_applied`` landed. Fail-closed direction: a split is either
completed or still visibly pending — never silently half-applied.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
import uuid

from ..utils.metrics import metrics


class SplitJournal:
    """SQLite-backed (same durability story as the dtx workflow DB —
    and defaulting to the same directory). Thread-safe: the planner's
    scatter pool shares one connection under a lock."""

    def __init__(self, db_path: str):
        self.db_path = db_path
        d = os.path.dirname(os.path.abspath(db_path))
        os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._db = sqlite3.connect(db_path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS split_writes ("
            " id TEXT PRIMARY KEY,"
            " created REAL NOT NULL,"
            " map_version INTEGER NOT NULL,"
            " plan TEXT NOT NULL,"       # JSON {shard: [op dicts...]}
            " preconditions TEXT NOT NULL,"
            " applied TEXT NOT NULL)")   # JSON [shard, ...]
        self._db.commit()

    # -- write path ----------------------------------------------------------

    def begin(self, plan: dict, preconditions: list,
              map_version: int) -> str:
        """Durably record the split BEFORE any shard applies; returns
        the entry id. ``plan`` maps shard index -> serialized op list."""
        sid = uuid.uuid4().hex
        with self._lock:
            self._db.execute(
                "INSERT INTO split_writes VALUES (?,?,?,?,?,?)",
                (sid, time.time(), map_version,
                 json.dumps({str(k): v for k, v in plan.items()}),
                 json.dumps(preconditions), json.dumps([])))
            self._db.commit()
        metrics.counter("scaleout_split_writes_total").inc()
        return sid

    def mark_applied(self, sid: str, shard: int) -> None:
        with self._lock:
            row = self._db.execute(
                "SELECT applied FROM split_writes WHERE id=?",
                (sid,)).fetchone()
            if row is None:
                return
            applied = set(json.loads(row[0]))
            applied.add(int(shard))
            self._db.execute(
                "UPDATE split_writes SET applied=? WHERE id=?",
                (json.dumps(sorted(applied)), sid))
            self._db.commit()

    def finish(self, sid: str) -> None:
        with self._lock:
            self._db.execute("DELETE FROM split_writes WHERE id=?",
                             (sid,))
            self._db.commit()

    # -- recovery ------------------------------------------------------------

    def pending(self) -> list[dict]:
        """Every unfinished split, oldest first: ``{id, map_version,
        plan: {shard int: [op dicts]}, preconditions, applied: set}``."""
        with self._lock:
            rows = self._db.execute(
                "SELECT id, map_version, plan, preconditions, applied "
                "FROM split_writes ORDER BY created").fetchall()
        out = []
        for sid, ver, plan, pcs, applied in rows:
            out.append({
                "id": sid,
                "map_version": int(ver),
                "plan": {int(k): v
                         for k, v in json.loads(plan).items()},
                "preconditions": json.loads(pcs),
                "applied": set(json.loads(applied)),
            })
        return out

    def pending_count(self) -> int:
        with self._lock:
            (n,) = self._db.execute(
                "SELECT COUNT(*) FROM split_writes").fetchone()
        return int(n)

    def close(self) -> None:
        with self._lock:
            self._db.close()
