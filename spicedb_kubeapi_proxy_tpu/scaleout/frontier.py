"""Cross-shard frontier exchange: iterative joins over shard boundaries.

PR 11's scale-out contract kept every query closure shard-local by
REPLICATING reference data: any type a cross-namespace walk passes
through (groups, namespaces) had to be cluster-scoped — present on
every group — or checks anchored on one shard could not see membership
tuples living on another. That caps the "millions of users" story at
whatever replicates everywhere.

This module lifts the restriction the TrieJax way (PAPERS.md):
multi-hop graph closure decomposes into a SEQUENCE of bounded
relational joins where only BOUNDARY tuples ride the wire — the
distributed analog of the mesh halo exchange in ``parallel/sharded.py``,
one level up. The planner runs a membership-expansion fixpoint:

1. the frontier starts as the query's subject descriptor
   ``(type, id, relation?)``;
2. each round, every group expands the frontier against its LOCAL
   tuples — for every schema *reference pair* ``(type, relation)``
   whose relation admits userset subjects, one
   ``lookup_resources(type, relation, ...)`` per frontier descriptor,
   so multi-hop paths WITHIN the group fold into one local fixpoint
   (each leg is just another ``semiring.propagate`` dispatch: the
   engine hot path needs no new kernel);
3. the planner gathers the groups' newly-resolved userset descriptors
   (the boundary tuples — nothing else moves), dedupes against the
   visited set, and scatters the residue as the next round's seeds;
4. fixpoint when a round resolves nothing new; the round budget is
   HARD — an exhausted budget stops expanding and the caller proceeds
   with the partial closure, which can only UNDER-approximate
   (frontier checks may deny and lookups may under-list, never
   over-grant: fail closed), with the exhaustion counted.

The planner then re-checks denied items on the resource's owner with
each closure descriptor as the subject — the owner holds the
``resource -> userset`` tuple, the closure proved ``subject ∈
userset``, and the engine's userset-subject seeding does the rest.

**Supported schema class.** The decomposition is exact for MONOTONE
(union/arrow/nil) permission graphs: adding membership facts can only
add grants, so per-descriptor re-checks compose by union. Intersection
and exclusion break that composition (a subject can satisfy ``A & B``
through two DIFFERENT membership paths no single descriptor re-check
sees), so :func:`reference_pairs` REFUSES such schemas at enable time
— fail closed, loudly, instead of silently wrong answers.

Wire accounting: :func:`encode_frontier` is the canonical byte form
both the wire op ships and the ``scaleout_frontier_wire_bytes_total``
counter measures — the counter is definitionally the boundary mass,
which is what the bench pins to prove no bulk replication happened.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Optional

from ..models.schema import Exclude, Intersect, Schema, Union
from .shardmap import ShardMapError


class FrontierError(ShardMapError):
    """A schema or configuration the frontier exchange must refuse."""


@dataclass(frozen=True)
class FrontierConfig:
    """Planner-side enablement: ``pairs`` is the reference-pair set
    (``None`` = discover from group 0's schema on first use);
    ``max_rounds`` bounds the exchange — exhaustion fails closed."""

    pairs: Optional[tuple] = None
    max_rounds: int = 8


def _non_monotone(expr) -> bool:
    if isinstance(expr, (Intersect, Exclude)):
        return True
    if isinstance(expr, Union):
        return any(_non_monotone(op) for op in expr.operands)
    return False


def reference_pairs(schema: Schema) -> tuple:
    """The schema's *reference pairs*: every ``(type, relation)``
    REFERENCED as a userset subject somewhere (``team#member`` in
    ``relation owner: team#member`` yields ``("team", "member")``).
    Those usersets are the only subjects tuples can name beyond plain
    objects, so they are exactly the memberships a closure must prove
    — and so the only relations the frontier exchange expands.

    Raises :class:`FrontierError` when the schema pairs usersets with
    intersection or exclusion anywhere: per-descriptor re-checks only
    compose by union (module docstring), so a non-monotone schema must
    keep the cluster-scoped replication contract instead of getting
    silently wrong cross-shard answers."""
    pairs = set()
    for d in schema.definitions.values():
        for rel in d.relations.values():
            for a in rel.allowed:
                if a.relation:
                    pairs.add((a.type, a.relation))
    if not pairs:
        return ()
    for d in schema.definitions.values():
        for p in d.permissions.values():
            if _non_monotone(p.expr):
                raise FrontierError(
                    f"frontier exchange requires a monotone schema, but "
                    f"{d.name}#{p.name} uses intersection/exclusion: a "
                    "per-descriptor re-check cannot see that a subject "
                    "satisfies the branches through different membership "
                    "paths — keep this schema's reference types "
                    "cluster-scoped (replicated) instead")
    return tuple(sorted(pairs))


def encode_frontier(descs) -> bytes:
    """Canonical wire payload of one frontier batch: sorted JSON of
    ``[type, id, relation]`` descriptors. The SAME bytes the wire op
    ships and the wire-bytes counter counts — so the counter provably
    measures boundary mass, not an estimate of it."""
    return json.dumps(
        sorted(([d[0], d[1], d[2]] for d in descs),
               key=lambda d: (d[0], d[1], d[2] or "")),
        separators=(",", ":")).encode("utf-8")


def decode_frontier(raw) -> set:
    """Inverse of :func:`encode_frontier` (also accepts the already-
    parsed list form the JSON wire hands handlers)."""
    if isinstance(raw, (bytes, str)):
        raw = json.loads(raw)
    return {(str(t), str(i), None if r is None else str(r))
            for t, i, r in raw}


def expand_local(engine, descs, pairs, now=None, context=None) -> set:
    """One group's expansion leg, computed against its LOCAL tuples
    (the in-process fallback the ``frontier_expand`` wire op runs
    server-side — one owner for the semantics): for every reference
    pair and frontier descriptor, the userset objects the descriptor
    reaches on this engine. Multi-hop paths through locally-held
    tuples fold into each ``lookup_resources`` fixpoint; paths that
    leave the group surface here as boundary descriptors for the next
    round."""
    out = set()
    for t, rel in pairs:
        for st, sid, srel in descs:
            ids = engine.lookup_resources(
                t, rel, st, sid, subject_relation=srel,
                now=now, context=context)
            out.update((t, str(i), rel) for i in ids)
    return out


__all__ = [
    "FrontierConfig", "FrontierError", "decode_frontier",
    "encode_frontier", "expand_local", "reference_pairs",
]
