"""The proxy-side scatter-gather planner over hash-partitioned shards.

``ShardedEngine`` exposes the exact engine surface the authz middleware
consumes (check_bulk, lookup_resources[_mask], lookup_subjects, write/
delete/read relationships, watch streams, store.exists) and plans each
operation against the :class:`~.shardmap.ShardMap`:

- **single-shard ops** — a check, write, or anchored read whose closure
  lives on one group — route DIRECTLY to the owning group (no scatter;
  ``scaleout_ops_total{mode="single"}`` counts them per group);
- **scatter ops** — LookupResources / list-prefilter masks /
  LookupSubjects / watch streams — fan out to every group
  (``shard_fanout`` span) and gather CLIENT-SIDE (``shard_merge``
  span): namespaced slices are disjoint so the union is exact, global
  objects are replicated so duplicates dedupe;
- **cross-shard writes** — tuples spanning groups (including every
  global-tuple write, which replicates) — split per shard, journaled
  durably BEFORE the first shard applies (:mod:`.journal`), and
  replayed to completion after a mid-split crash;
- **per-shard admission** — each scatter leg passes its own group's
  engine-host admission; ONE overloaded group sheds only its slice, and
  the partial-shed scatter fails CLOSED with ``Retry-After`` = the max
  over the shedding shards (never a half answer).

Consistency is a **revision vector** (one component per group): gathers
merge at the vector of the per-shard revisions they observed, the
optional client-side decision cache keys entries by the vector and
refuses to serve once ANY component advances, and watch resumption
tokens are vectors, never scalars.
"""

from __future__ import annotations

import json
import queue as _queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import asdict
from typing import Optional

import numpy as np

from ..admission import (
    AdmissionRejected,
    LOOKUP_PREFILTER,
    WATCH_RECOMPUTE,
)
from ..engine.engine import CheckItem, SchemaViolation, WatchEvent
from ..engine.remote import (
    NotLeaderError,
    RemoteInterner,
    TRANSPORT_ERRORS,
)
from ..engine.store import PreconditionFailed, StoreError
from ..utils.resilience import BreakerOpen
from ..engine.store import RelationshipFilter, WriteOp
from ..models.tuples import Relationship
from ..obs.trace import tracer
from ..utils.metrics import metrics
from .journal import SplitJournal
from .rebalance import (
    CUT as CUT_STATE,
    MapTransition,
    RebalanceCoordinator,
    RebalanceError,
    abort_transition,
    plan_moves,
)
from .shardmap import (
    RevisionVector,
    ShardMap,
    ShardMapError,
    split_resource,
)

import logging

log = logging.getLogger("sdbkp.scaleout")

# classes whose proxy-side admission cost scales with the scatter width
_SCATTER_CLASSES = frozenset({LOOKUP_PREFILTER.name,
                              WATCH_RECOMPUTE.name})

# failures that PROVE a write never applied: the engine answered with a
# rejection (precondition/schema/store), the role gate refused it
# pre-dispatch (not_leader), admission shed it before any side effect,
# or the breaker never let an attempt reach the wire. Everything else —
# transport deaths, exhausted deadlines, protocol errors — is AMBIGUOUS
# (bytes may have reached a store that applied them), and a split-write
# journal entry must then stay pending rather than close half-applied.
_PROVABLY_NOT_APPLIED = (PreconditionFailed, SchemaViolation,
                         StoreError, NotLeaderError, AdmissionRejected,
                         BreakerOpen)


def _op_counter(group: int, op: str, mode: str):
    return metrics.counter("scaleout_ops_total", group=str(group),
                           op=op, mode=mode)


def _rel_to_dict(r: Relationship) -> dict:
    return asdict(r)


def _rel_from_dict(d: dict) -> Relationship:
    return Relationship(**d)


class ShardVectorCache:
    """Decision cache keyed by ``(query key, revision vector)``: an
    entry filled at vector V serves ONLY while the planner's tracked
    vector still equals V — the moment any component shard advances,
    every V-keyed entry is unreachable (the satellite pin: an old-vector
    entry never serves after any component advances). Bounded LRU.

    Entries are additionally TIME-BOUNDED (``ttl`` seconds): the
    planner cannot see the engine-side expiration/caveat verdict-flip
    watermarks, so a time-window grant could otherwise serve from here
    past its revocation instant while no write advances the vector.
    The TTL caps that staleness class; the per-group host-side caches
    stay exact regardless."""

    def __init__(self, max_entries: int = 8192, ttl: float = 5.0,
                 clock=time.monotonic):
        from collections import OrderedDict

        self.max_entries = max_entries
        self.ttl = ttl
        self._clock = clock
        self._lock = threading.Lock()
        self._map: "OrderedDict" = OrderedDict()

    def get(self, key, vector: RevisionVector):
        with self._lock:
            got = self._map.get((key, vector))
            if got is not None and \
                    self._clock() - got[1] > self.ttl:
                del self._map[(key, vector)]
                got = None
            if got is None:
                metrics.counter("scaleout_cache_misses_total").inc()
                return None
            self._map.move_to_end((key, vector))
            metrics.counter("scaleout_cache_hits_total").inc()
            return got[0]

    def put(self, key, vector: RevisionVector, value) -> None:
        with self._lock:
            self._map[(key, vector)] = (value, self._clock())
            self._map.move_to_end((key, vector))
            while len(self._map) > self.max_entries:
                self._map.popitem(last=False)

    def retire_below(self, vector: RevisionVector) -> None:
        """Drop entries whose vector is dominated by (and not equal to)
        ``vector`` — they can never serve again."""
        with self._lock:
            dead = [k for k in self._map
                    if k[1] != vector and vector.dominates(k[1])]
            for k in dead:
                del self._map[k]


class _ShardedStoreShim:
    """The sliver of Store the proxy touches (idempotency/lock existence
    probes), routed through the planner."""

    def __init__(self, planner: "ShardedEngine"):
        self._p = planner

    def exists(self, f: RelationshipFilter) -> bool:
        return self._p.exists(f)


class ShardedWatchStream:
    """Merged server-push watch subscription over every group: one
    reader thread per group feeds a shared queue; ``next_batch()``
    returns each group's batches as they land, with every event's
    revision REWRITTEN to the planner's running revision vector (join of
    everything seen so far with that shard's component advanced) — so
    consumers that track "the latest revision seen" hold a resumption
    token that is exact per shard and monotone across the merge."""

    _POLL = 0.25

    def __init__(self, planner: "ShardedEngine",
                 from_vector: RevisionVector):
        self._p = planner
        self._q: _queue.Queue = _queue.Queue(maxsize=1024)
        self._closed = threading.Event()
        self._streams: list = []
        self._streams_lock = threading.Lock()
        self._threads: list = []
        self._vec_lock = threading.Lock()
        from_vector = from_vector.extend(len(planner.groups))
        self.revision = from_vector
        self._n_pumps = 0
        for gi, client in enumerate(planner.groups):
            self._start_pump(gi, client, int(from_vector[gi]))

    def _start_pump(self, gi: int, client, from_rev: int) -> None:
        t = threading.Thread(
            target=self._pump, args=(gi, client, from_rev),
            name=f"shard-watch-g{gi}", daemon=True)
        self._threads.append(t)
        self._n_pumps = max(self._n_pumps, gi + 1)
        t.start()

    def _ensure_pumps(self) -> None:
        """A rebalance may ADD groups after this stream opened: start a
        pump for each (from revision 0 — a new group's history is
        nothing but moved tuples, and the delivery filter suppresses
        everything below its slices' cut revisions)."""
        groups = self._p.groups
        if len(groups) <= self._n_pumps:
            return
        with self._vec_lock:
            self.revision = self.revision.extend(len(groups))
        for gi in range(self._n_pumps, len(groups)):
            self._start_pump(gi, groups[gi], 0)

    def _register_stream(self, s) -> bool:
        """Track an opened per-group stream; closes it immediately if
        close() already ran (a pump mid-connect must not leak the
        socket and park its thread until the heartbeat timeout)."""
        with self._streams_lock:
            if not self._closed.is_set():
                self._streams.append(s)
                return True
        try:
            s.close()
        except Exception:  # noqa: BLE001 - teardown best effort
            pass
        return False

    def _put(self, item) -> bool:
        """Bounded-queue put that re-checks ``close()``: a pump thread
        whose consumer stopped draining must unpark when the stream
        closes, not sit in ``Queue.put`` forever."""
        while not self._closed.is_set():
            try:
                self._q.put(item, timeout=0.5)
                return True
            except _queue.Full:
                continue
        return False

    def _pump(self, gi: int, client, from_rev: int) -> None:
        try:
            if hasattr(client, "watch_push_stream"):
                s = client.watch_push_stream(from_rev)
                if not self._register_stream(s):
                    return
                while not self._closed.is_set():
                    events = s.next_batch()
                    if events and not self._put((gi, events, None)):
                        return
            else:
                # in-process engines: blocking wait_events loop
                rev = from_rev
                while not self._closed.is_set():
                    events = client.wait_events(rev, self._POLL)
                    if events:
                        rev = max(e.revision for e in events)
                        if not self._put((gi, events, None)):
                            return
        except Exception as e:  # noqa: BLE001 - surfaced to next_batch
            if not self._closed.is_set():
                self._put((gi, None, e))

    def next_batch(self) -> list:
        """Blocks for the next batch from ANY group; ``[]`` means the
        wait timed out (liveness heartbeat semantics). Events pass the
        planner's rebalance delivery filter — the resumption token
        still advances past suppressed mover echoes, so a consumer
        resuming from ``self.revision`` never sees them either."""
        if len(self._p.groups) < self._n_pumps:
            # a SHRINK committed under this stream: its per-component
            # indexing (and the consumer's resumption arithmetic) no
            # longer matches the contracted group space — fail closed
            # with re-list semantics instead of mis-stamping events
            raise StoreError(
                "shard-group space shrank beneath this watch stream; "
                "re-list and re-watch")
        self._ensure_pumps()
        try:
            gi, events, err = self._q.get(timeout=self._p.PUSH_WAIT)
        except _queue.Empty:
            return []
        if err is not None:
            raise err
        with self._vec_lock:
            if gi >= len(self.revision):
                self.revision = self.revision.extend(gi + 1)
            out = []
            for e in events:
                self.revision = self.revision.bump(gi, e.revision)
                if self._p._deliver_event(gi, e.relationship,
                                          e.revision):
                    out.append(WatchEvent(self.revision, e.operation,
                                          e.relationship))
            self._p._observe_revision(gi, max(
                e.revision for e in events))
        return out

    def close(self) -> None:
        with self._streams_lock:
            self._closed.set()
            streams = list(self._streams)
        for s in streams:
            try:
                s.close()
            except Exception:  # noqa: BLE001 - teardown best effort
                pass


class ShardedEngine:
    """See module docstring. ``groups`` are engine clients with the
    remote-engine surface (RemoteEngine / FailoverEngine — or in-process
    Engines in tests: the planner only calls the shared surface)."""

    PUSH_WAIT = 15.0

    def __init__(self, shard_map: ShardMap, groups: list,
                 journal: Optional[SplitJournal] = None,
                 cache: Optional[ShardVectorCache] = None,
                 recover: bool = True, retry_budget=None,
                 client_factory=None, frontier=None):
        if len(groups) != shard_map.n_groups:
            raise ValueError(
                f"shard map names {shard_map.n_groups} groups, got "
                f"{len(groups)} clients")
        self.map = shard_map
        self.groups = list(groups)
        # clients a SHRINK commit removed from routing: ownership
        # parks here until close() — the planner may not own a test's
        # in-process engine, but a factory-built remote client's
        # sockets/heartbeats must not leak past teardown
        self._retired_clients: list = []
        self.journal = journal
        self.cache = cache
        # cross-shard frontier exchange (scaleout/frontier.py): a
        # FrontierConfig enables the planner-coordinated iterative
        # join for closures that cross shard boundaries; None keeps
        # the classic shard-local closure contract
        self.frontier = frontier
        self._frontier_pairs = (None if frontier is None
                                else frontier.pairs)
        # the SAME RetryBudget instance the group clients hold
        # (utils/resilience.py): the planner's scatter-leg re-issues
        # draw from it too, so a browned-out shard sees one bounded
        # retry stream instead of per-layer multiplication
        self.retry_budget = retry_budget
        # builds an engine client for a group's endpoint list — how a
        # restarted planner reconstructs clients for groups a persisted
        # rebalance transition ADDED beyond the booted map
        self.client_factory = client_factory
        self.store = _ShardedStoreShim(self)
        self.dependency = "engine-shards"
        self._pool = ThreadPoolExecutor(
            max_workers=max(4, 2 * len(groups)),
            thread_name_prefix="shard-scatter")
        self._vec_lock = threading.Lock()
        self._vector = shard_map.zero_vector()
        # live tuple mover (rebalance.py): at most one ACTIVE map
        # transition routes reads/writes/watches; completed ones stay
        # archived for token translation and watch-event filtering
        self._active_transition: Optional[MapTransition] = None
        self._archived_transitions: list = []
        self._coordinator: Optional[RebalanceCoordinator] = None
        # coordinated live schema migration (migration/migrator.py per
        # group + this planner's cross-group cut): at most one in
        # flight; the dict is the aggregate status surface
        self._migration: Optional[dict] = None
        self._migration_thread: Optional[threading.Thread] = None
        metrics.gauge("scaleout_groups").set(shard_map.n_groups)
        metrics.gauge("scaleout_map_version").set(shard_map.version)
        if journal is not None:
            # BEFORE split recovery and before any request: a persisted
            # transition with cut slices changes routing — serving
            # without it would misroute the cut slices' tuples
            self._recover_transition()
            # schema-migration crash matrix: a persisted "cutting"
            # record means some group may already serve the new schema
            # — finish the coordinated cut (idempotent per group);
            # anything earlier aborts cleanly (no group cut yet)
            self._recover_migration()
        if recover and journal is not None:
            try:
                self.recover_splits()
            except Exception as e:  # noqa: BLE001 - boot must not gate
                # an unreachable shard must not turn a one-slice outage
                # into a full-proxy outage: the entries stay PENDING
                # (visible as /readyz pending_splits and the counter),
                # and replay retries on the next recover_splits call —
                # lazily before the next split write, or the next boot
                log.warning("deferred split-write recovery (%d pending "
                            "entries): %s",
                            self.journal.pending_count(), e)
                metrics.counter(
                    "scaleout_split_replay_deferred_total").inc()

    # -- revision vector -----------------------------------------------------

    def _observe_revision(self, shard: int, revision) -> None:
        """Advance the tracked vector; retires cache entries that can
        never serve again."""
        try:
            revision = int(revision)
        except (TypeError, ValueError):
            return
        with self._vec_lock:
            if shard >= len(self.groups):
                # a RETIRED group's straggler (a watch pump or probe
                # that outlived its shrink): the group's history is
                # closed — extending the contracted vector back out
                # would resurrect the dropped component
                return
            if shard >= len(self._vector):
                # a rebalance-added group: grow the tracked vector
                self._vector = self._vector.extend(shard + 1)
            self._vector = self._vector.bump(shard, revision)
        # no eager cache sweep: dominated entries are already
        # unreachable (get() matches the exact vector) and the TTL
        # ages them out — an O(entries) retire_below per revision
        # advance would put a full-scan under the cache lock on every
        # write and every watch batch

    @property
    def vector(self) -> RevisionVector:
        with self._vec_lock:
            return self._vector

    def revision_vector(self, refresh: bool = True) -> RevisionVector:
        """The per-shard revision vector; ``refresh`` scatters a
        revision probe so the answer reflects every group NOW."""
        if refresh:
            revs = self._scatter("revision",
                                 lambda gi, c: c.revision)
            for gi, r in revs.items():
                self._observe_revision(gi, r)
        return self.vector

    @property
    def revision(self) -> RevisionVector:
        """The engine-surface revision property: a VECTOR (consumers
        that only order tokens — the watch hub — work unchanged; the
        decision audit's ``isinstance(int)`` guard skips it). Serves
        the TRACKED vector once any traffic has flowed — the dtx
        activity reads this after every dual-write, and an
        unconditional refresh would add n_groups round trips per kube
        write for a token _observe_revision already holds. Only a
        never-observed (all-zero) vector pays the scatter, so a fresh
        planner's first watch still starts from the current state
        instead of replaying every shard's history."""
        return self.revision_vector(refresh=not any(self.vector))

    # -- online rebalance (scaleout/rebalance.py) ----------------------------

    def begin_rebalance(self, new_map: ShardMap,
                        new_clients: Optional[dict] = None,
                        retire: Optional[int] = None,
                        **coordinator_cfg) -> RebalanceCoordinator:
        """Start a live map transition V -> ``new_map.version`` on a
        background mover thread (``--rebalance-to``). ``new_clients``
        maps ADDED group indices to their engine clients (or a
        ``client_factory`` builds them from the map's endpoints).
        Returns the coordinator; routing changes take effect per slice
        as the protocol advances — no drain, ever.

        A target with FEWER groups is a SHRINK: the ``retire``-d group
        (default: the last one) is emptied through the same
        copy/catch-up/dual-write/cutover machinery, GC'd, and removed
        at commit. Only the LAST group may retire — every survivor's
        ring points are keyed by group index, so retiring the tail
        leaves their placement untouched; to retire a middle group,
        rebalance its slices onto the tail first."""
        if self._active_transition is not None:
            raise RebalanceError(
                "a rebalance is already in flight (to map version "
                f"{self._active_transition.new_map.version})")
        if new_map.n_groups < self.map.n_groups:
            if retire is None:
                retire = self.map.n_groups - 1
            if retire != self.map.n_groups - 1:
                raise RebalanceError(
                    "only the LAST group can retire (group indices are "
                    "identity across a transition: removing a middle "
                    "index would silently renumber every later group's "
                    "ring points); move its slices to the tail first")
            if any(not past.gc_complete
                   for past in self._archived_transitions):
                raise RebalanceError(
                    "cannot shrink while an earlier transition's GC is "
                    "incomplete: its lingering copies are filtered by "
                    "group index, and the shrink renumbers the index "
                    "space out from under that filter — re-run GC (it "
                    "resumes at boot) and retry")
            t = MapTransition(self.map, new_map,
                              plan_moves(self.map, new_map,
                                         retire=retire),
                              retire=retire)
        else:
            t = MapTransition(self.map, new_map,
                              plan_moves(self.map, new_map))
        self._install_transition(t, new_clients)
        coord = RebalanceCoordinator(self, t, **coordinator_cfg)
        self._coordinator = coord
        return coord.start()

    def _install_transition(self, t: MapTransition,
                            new_clients: Optional[dict] = None,
                            persist: bool = True) -> None:
        """Extend the group/vector space with the transition's added
        groups and make the transition route — persisted before any
        data moves."""
        for gi in t.new_groups:
            if gi < len(self.groups):
                continue
            if gi != len(self.groups):
                raise RebalanceError(
                    f"transition adds group {gi} but only "
                    f"{len(self.groups)} groups exist")
            client = (new_clients or {}).get(gi)
            if client is None and self.client_factory is not None:
                client = self.client_factory(t.new_map.groups[gi])
            if client is None:
                raise RebalanceError(
                    f"no client for rebalance-added group {gi}; pass "
                    "new_clients or a client_factory")
            with self._vec_lock:
                self.groups.append(client)
                self._vector = self._vector.extend(len(self.groups))
        self._active_transition = t
        if persist and self.journal is not None:
            self.journal.save_transition(t.to_doc())

    def commit_rebalance(self, t: MapTransition) -> None:
        """Every slice cut: map V+1 becomes THE map (atomic swap); the
        transition is archived — its cut table keeps filtering watch
        replays and translating V-minted resumption tokens. A SHRINK
        commit additionally removes the retiring group from the routing
        space: its client leaves ``groups`` (closed at planner
        teardown — the planner may not own its lifecycle mid-test) and
        the tracked vector drops its component; the translation
        watermark is recorded first."""
        if not t.all_cut():
            raise RebalanceError(
                "commit before every slice cut would misroute the "
                "uncut slices")
        if t.retire is not None:
            if t.retire_cut is None:
                t.retire_cut = t.retire_watermark()
            with self._vec_lock:
                self.map = t.new_map
                retired = self.groups.pop(t.retire)
                self._vector = self._vector.drop_component(t.retire)
            self._retired_clients.append(retired)
        else:
            with self._vec_lock:
                self.map = t.new_map
        self._active_transition = None
        self._archived_transitions.append(t)
        # bound the era-walk/translation memory: resumption tokens old
        # enough to predate the 8 most recent transitions get re-list
        # semantics (their groups' watch logs have long been trimmed
        # past those cut revisions anyway)
        del self._archived_transitions[:-8]
        self._retire_stale_archives()
        metrics.gauge("scaleout_groups").set(t.new_map.n_groups)
        metrics.gauge("scaleout_map_version").set(t.new_map.version)

    def _retire_stale_archives(self) -> None:
        """Drop archived transitions that reference a group index
        OUTSIDE today's group space (beyond their own retiree). They
        accumulate across grow→shrink cycles and pin two stale filters:
        a ``gc_complete=False`` archive holds ``_copies_may_linger``
        open forever (per-row owner filtering on every scatter, and the
        ``exists`` probe degraded to full row gathers) even though the
        shrink that removed the group already copy-REPLACED the ranges
        its GC owed; and their era tables make every watch-delivery
        walk compare today's group indices against a dead index space.
        Safe to drop: ``begin_rebalance`` refuses to shrink past
        incomplete GC, and a dropped archive's resumption tokens get
        re-list semantics (exactly what tokens older than the 8-ring
        already get)."""
        n = len(self.groups)
        kept = []
        for past in self._archived_transitions:
            refs = ({sl.src for sl in past.slices}
                    | {sl.dst for sl in past.slices}
                    | set(past.new_groups))
            if past.retire is not None:
                # its own retiree is the one out-of-space index an
                # archive may keep: the era walk and token translation
                # for the shrink itself live there
                refs.discard(past.retire)
            if any(gi >= n for gi in refs):
                metrics.counter(
                    "scaleout_archives_retired_total").inc()
                continue
            kept.append(past)
        self._archived_transitions = kept

    def _recover_transition(self) -> None:
        """Boot-time crash matrix (see rebalance.py): committed or
        all-cut -> finish the commit; some-cut -> install + RESUME (the
        flip already moved data authoritatively); none-cut -> clean
        abort (routing never left V)."""
        doc = self.journal.load_transition()
        if doc is None:
            return
        if doc.get("phase") == "done":
            # a COMPLETED transition's durable marker (see
            # run_to_completion): the doc's target map is authoritative
            done_ver = int(doc["new_map"]["version"])
            if done_ver == self.map.version:
                # the operator rolled --shard-map to the new version:
                # the marker has served its purpose
                self.journal.clear_transition()
                return
            t = MapTransition.from_doc(doc, self.map)
            t.gc_complete = True
            # persist=False: installing must not clobber the durable
            # "done" marker with a "running" record
            self._install_transition(t, persist=False)
            self.commit_rebalance(t)
            log.warning(
                "booted with --shard-map v%d but rebalance to v%d "
                "already completed — serving the completed map (update "
                "the flag to clear this)", doc["old_version"], done_ver)
            return
        t = MapTransition.from_doc(doc, self.map)
        if t.retire is not None and t.any_cut():
            # SHRINK crash matrix, collapsed: GC runs BEFORE commit (it
            # must address the sources in OLD index space), so any
            # post-cut crash — mid-move, mid-GC, or between GC and
            # commit — resumes the coordinator, which skips cut slices,
            # re-runs GC only if the persisted gc_complete says it owes
            # one (idempotent deletes), then commits and renumbers
            log.warning(
                "resuming interrupted shrink to map v%d (%d/%d slices "
                "cut, gc_complete=%s)", t.new_map.version,
                sum(1 for s in t.slices if s.state == "cut"),
                len(t.slices), t.gc_complete)
            self._install_transition(t)
            self._coordinator = RebalanceCoordinator(self, t).start()
            metrics.counter("scaleout_rebalance_transitions_total",
                            outcome="resumed").inc()
            return
        if doc.get("phase") == "committed" or t.all_cut():
            # raises if rebalance-added groups have no clients: serving
            # without them would misroute every cut slice (fail closed)
            self._install_transition(t)
            self.commit_rebalance(t)
            coord = RebalanceCoordinator(self, t)

            def _finish_gc():
                # OFF the boot path: the GC is a full source scan plus
                # batched deletes — leftover copies are inert until it
                # lands (the scatter-merge owner filter guards them)
                try:
                    coord._gc()
                    t.gc_complete = True
                    self.journal.save_transition(t.to_doc("done"))
                except Exception as e:  # noqa: BLE001 - re-runnable
                    log.warning(
                        "rebalance GC after recovered commit "
                        "incomplete (leftover source copies are inert "
                        "and re-dropped at the next boot): %s", e)

            threading.Thread(target=_finish_gc, daemon=True,
                             name="rebalance-gc").start()
            metrics.counter("scaleout_rebalance_transitions_total",
                            outcome="recovered").inc()
        elif t.any_cut():
            log.warning(
                "resuming interrupted rebalance to map v%d (%d/%d "
                "slices already cut)", t.new_map.version,
                sum(1 for s in t.slices if s.state == "cut"),
                len(t.slices))
            self._install_transition(t)
            self._coordinator = RebalanceCoordinator(self, t).start()
            metrics.counter("scaleout_rebalance_transitions_total",
                            outcome="resumed").inc()
        else:
            log.warning("aborting interrupted rebalance to map v%d "
                        "(no slice had cut — routing never left "
                        "v%d)", t.new_map.version, self.map.version)
            try:
                # drain pending dual-write splits FIRST: their
                # destination mirror legs would otherwise be re-created
                # AFTER the abort's copy drop and linger as stale rows
                self.recover_splits()
            except Exception as e:  # noqa: BLE001 - deferred like boot
                log.warning("split replay before rebalance abort "
                            "deferred: %s", e)
            abort_transition(self, t)

    def _read_anchor(self, resource_type: str, resource_id: str) -> int:
        """The ONE group answering reads anchored at this object right
        now: the moving-slice read owner during a transition (src until
        the slice's cut, dst after), the map owner otherwise. Global
        anchors keep the CURRENT map's deterministic anchor — every
        group in it holds the replicated globals throughout."""
        t = self._active_transition
        if t is not None:
            sl = t.slice_for(resource_type, resource_id)
            if sl is not None:
                return t.read_owner(sl)
        return self.map.anchor_shard(resource_type, resource_id)

    def _copies_may_linger(self) -> bool:
        """True while ANY transition's mover copies can still exist
        off-owner: an active transition, or an archived one whose GC
        has not finished. Once every transition is GC-complete the
        per-row owner filters have nothing to guard and the scatter
        fast paths return."""
        if self._active_transition is not None:
            return True
        return any(not t.gc_complete
                   for t in self._archived_transitions)

    def _admit_gathered(self, gi: int, resource_type: str,
                        resource_id: str) -> bool:
        """Scatter-merge filter while moved copies exist anywhere: a
        namespaced row is accepted only from its current read owner —
        a destination's not-yet-caught-up copy (or a source's
        not-yet-GC'd leftover) can never leak a stale grant into the
        union (fail-open)."""
        if not self._copies_may_linger():
            return True
        _, namespaced = split_resource(resource_id)
        if not namespaced:
            return True
        return self._read_anchor(resource_type, resource_id) == gi

    def _transitions(self) -> list:
        """Archived transitions in completion order, plus the active
        one last."""
        ts = list(self._archived_transitions)
        if self._active_transition is not None:
            ts.append(self._active_transition)
        return ts

    def _deliver_event(self, gi: int, rel, revision) -> bool:
        """Watch-event filter: read-owner-only delivery keeps merged
        streams gap- and duplicate-free across cutovers. Evaluated as
        an ERA WALK over the whole transition sequence: a key's
        ownership history is a chain of (owner, revision-window) eras
        bounded by each transition's cut revisions, and an event is
        delivered iff it falls inside one of ITS group's eras — which
        silences copy/catch-up touches and dual-write mirrors on a
        destination (below its cut), GC deletes on a source (above its
        cut), and still delivers a group's events again when a LATER
        transition moves the slice back to it."""
        ts = self._transitions()
        if not ts:
            return True
        try:
            rev = int(revision)
        except (TypeError, ValueError):
            return True
        ns, namespaced = split_resource(rel.resource_id)
        if not namespaced:
            for t in ts:
                if not t.deliver_global(gi, rev):
                    return False
            return True
        affecting = []
        for t in ts:
            sl = t.slice_for_key(ns, rel.resource_type)
            if sl is not None:
                affecting.append((t, sl))
        if not affecting:
            return True
        # walk the eras: cur = the owner of the open era, low = the
        # era's lower revision bound IN cur's OWN revision space
        ok = False
        cur = affecting[0][1].src
        low = None
        for t, sl in affecting:
            state, src_cut, dst_cut = t.cut_info(sl)
            if state != CUT_STATE:
                # the era is still open at the source; pre-cut copies
                # and mirrors on the destination are echoes
                break
            if gi == sl.src and src_cut is not None \
                    and (low is None or rev > low) and rev <= src_cut:
                ok = True
            cur, low = sl.dst, dst_cut
        if gi == cur and (low is None or rev > low):
            ok = True
        return ok

    def _known_map_versions(self) -> set:
        out = {self.map.version}
        t = self._active_transition
        if t is not None:
            out.add(t.old_map.version)
            out.add(t.new_map.version)
        for past in self._archived_transitions:
            out.add(past.old_map.version)
            out.add(past.new_map.version)
        return out

    def _resolve_token(self, revision) -> RevisionVector:
        """Watch resumption token -> a vector over TODAY's group space.
        A token minted under a different map that recorded transitions
        connect to today's is TRANSLATED step by step along the chain:
        a GROW extends it with zero components (the rebalance event
        filter suppresses the pre-cut records there); a SHRINK drops
        the retired component — but only when the token already sits
        at or past the transition's retire watermark (a token below it
        missed retiring-group events no surviving group re-delivers:
        StoreError, re-list semantics). A token from an unknown map
        version, or with a component count no transition explains, is
        REJECTED instead of misindexed. Version-tagged tokens enter
        the chain at their minting epoch; untagged ones at the first
        length match (exact for tagged, best-effort for raw vectors)."""
        if isinstance(revision, RevisionVector):
            vec, ver = revision, None
        elif isinstance(revision, int):
            vec, ver = RevisionVector(
                (int(revision),) * len(self.groups)), None
        else:
            vec, ver = RevisionVector.parse_versioned(revision)
        if ver is not None and ver not in self._known_map_versions():
            raise ShardMapError(
                f"watch token was minted under shard-map version {ver},"
                f" which this planner has no transition for (current: "
                f"{self.map.version}); re-list and re-watch")
        n = len(self.groups)
        if len(vec) == n and (ver is None or ver == self.map.version):
            return vec
        # committed transitions in commit order; the active one joins
        # only while it still GROWS the space (its added groups already
        # route) — an uncommitted shrink keeps the old space routing,
        # so its tokens bind directly above
        chain = list(self._archived_transitions)
        act = self._active_transition
        if act is not None and act.retire is None:
            chain.append(act)
        if ver is not None and ver != self.map.version:
            start = next((i for i, t in enumerate(chain)
                          if t.old_map.version == ver), None)
        else:
            start = next((i for i, t in enumerate(chain)
                          if t.old_map.n_groups == len(vec)), None)
        if start is not None:
            for t in chain[start:]:
                if t.old_map.n_groups != len(vec):
                    continue  # a retired archive left a gap; skip
                if t.retire is not None:
                    cut = (t.retire_cut if t.retire_cut is not None
                           else t.retire_watermark())
                    if vec[t.retire] < int(cut or 0):
                        raise StoreError(
                            "watch token predates the shrink to map "
                            f"v{t.new_map.version}: its component for "
                            f"retired group {t.retire} stops at "
                            f"{vec[t.retire]} but the group delivered "
                            f"through {cut}; re-list and re-watch")
                    vec = vec.drop_component(t.retire)
                else:
                    vec = vec.extend(t.new_map.n_groups)
            if len(vec) == n:
                return vec
        raise ShardMapError(
            f"watch token has {len(vec)} components but the planner "
            f"routes {n} groups and no recorded transition maps "
            "between them; re-list and re-watch")

    def _enter_write_gates(self, ops) -> tuple:
        """Cutover gates for every moving slice a write touches (sid
        order — no lock-order inversions); non-moving writes never
        wait. The cutover freeze drains these before the atomic flip."""
        t = self._active_transition
        if t is None:
            return ()
        slices = {}
        for op in ops:
            sl = t.slice_for(op.rel.resource_type, op.rel.resource_id)
            if sl is not None:
                slices[sl.sid] = sl
        gates = []
        for sid in sorted(slices):
            slices[sid].gate.enter()
            gates.append(slices[sid].gate)
        return tuple(gates)

    def rebalance_status(self) -> Optional[dict]:
        t = self._active_transition
        return None if t is None else t.progress()

    # -- coordinated live schema migration (migration/migrator.py) -----------
    # Every group runs its own SchemaMigrator with ``hold_at_dual``; the
    # planner journals the cross-group decision and releases every group
    # into its cut only after ALL of them sit at dual with zero lag — so
    # no request ever scatters across groups evaluating different
    # schemas past the cut point.

    MIGRATION_POLL = 0.05

    @staticmethod
    def _mig_begin(client, schema_text: str, **cfg) -> dict:
        if hasattr(client, "migrate_begin"):
            return client.migrate_begin(schema_text, hold_at_dual=True,
                                        **cfg)
        return client.begin_schema_migration(schema_text,
                                             hold_at_dual=True, **cfg)

    @staticmethod
    def _mig_status(client) -> Optional[dict]:
        if hasattr(client, "migrate_status"):
            return client.migrate_status()
        return client.migration_status()

    @staticmethod
    def _mig_cut(client) -> dict:
        if hasattr(client, "migrate_cut"):
            return client.migrate_cut(wait=True)
        return client.cut_schema_migration(wait=True)

    @staticmethod
    def _mig_abort(client) -> None:
        try:
            if hasattr(client, "migrate_abort"):
                client.migrate_abort()
            else:
                client.abort_schema_migration()
        except Exception:  # noqa: BLE001 - abort fan-out best-effort
            pass

    def begin_schema_migration(self, schema_text: str,
                               wait: bool = False,
                               timeout: float = 600.0,
                               **cfg) -> dict:
        """Coordinated migration of EVERY group to ``schema_text``.
        Group 0 classifies first — an incompatible change raises its
        typed :class:`SchemaError` on this stack before any other group
        changes state. Returns the aggregate status; ``wait=True``
        blocks through the coordinated cut."""
        m = self._migration
        if m is not None and m.get("phase") not in ("done", "aborted",
                                                    "failed"):
            raise StoreError("a coordinated schema migration is "
                             "already running")
        doc = {"phase": "begin", "schema_text": schema_text,
               "groups": len(self.groups)}
        if self.journal is not None:
            self.journal.save_migration(doc)
        begun: list = []
        try:
            for gi, c in enumerate(self.groups):
                self._mig_begin(c, schema_text, **cfg)
                begun.append(gi)
        except BaseException:
            # typed refusal (or a group begin failing): no group has cut
            # — roll every begun group back and clear the record so the
            # journal never claims a migration that is not running
            for gi in begun:
                self._mig_abort(self.groups[gi])
            if self.journal is not None:
                self.journal.clear_migration()
            raise
        self._migration = {"phase": "dual-wait",
                           "groups": len(self.groups), "at_dual": 0,
                           "error": None}
        if self.journal is not None:
            doc["phase"] = "dual-wait"
            self.journal.save_migration(doc)
        t = threading.Thread(target=self._coordinate_cut,
                             args=(time.monotonic() + timeout,),
                             name="schema-migration", daemon=True)
        self._migration_thread = t
        t.start()
        if wait:
            t.join(timeout)
        return dict(self._migration)

    def _coordinate_cut(self, deadline: float) -> None:
        """Poll every group to dual/zero-lag, journal the cut decision,
        then release all groups (each group's own record makes its cut
        idempotent under re-issue)."""
        m = self._migration
        try:
            while True:
                sts = []
                for c in self.groups:
                    try:
                        sts.append(self._mig_status(c))
                    except Exception:  # noqa: BLE001 - transient
                        # a group mid-failover: treat as not-ready and
                        # keep polling (the deadline bounds this); only
                        # a group ANSWERING failed/aborted/None is a
                        # definitive coordination failure
                        sts.append({"phase": "unreachable"})
                bad = [s for s in sts
                       if s is None or s.get("phase") in ("failed",
                                                          "aborted")]
                if bad:
                    raise RuntimeError(
                        f"{len(bad)} group(s) failed/aborted before "
                        "the coordinated cut")
                ready = sum(1 for s in sts
                            if s.get("phase") == "dual"
                            and not s.get("lag"))
                m["at_dual"] = ready
                if ready == len(self.groups):
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"groups at dual: {ready}/{len(self.groups)} "
                        "when the coordination deadline expired")
                time.sleep(self.MIGRATION_POLL)
            # the point of no return is PERSISTED before any group is
            # released: a planner crash after this line re-issues the
            # cuts at boot instead of aborting a half-cut fleet
            if self.journal is not None:
                self.journal.save_migration(
                    {"phase": "cutting", "groups": len(self.groups)})
            m["phase"] = "cutting"
            for c in self.groups:
                self._mig_cut(c)
            m["phase"] = "done"
            # the fleet now serves a NEW schema: re-derive the frontier
            # reference pairs on next use (config-pinned pairs stand)
            self._frontier_pairs = (None if self.frontier is None
                                    else self.frontier.pairs)
            if self.journal is not None:
                self.journal.clear_migration()
            metrics.counter("scaleout_schema_migrations_total",
                            outcome="done").inc()
        except BaseException as e:  # noqa: BLE001 - worker boundary
            m["phase"] = "failed"
            m["error"] = str(e)
            for c in self.groups:
                self._mig_abort(c)
            if self.journal is not None:
                self.journal.clear_migration()
            metrics.counter("scaleout_schema_migrations_total",
                            outcome="failed").inc()
            log.error("coordinated schema migration failed: %s", e)

    def _recover_migration(self) -> None:
        """Boot-time crash matrix for the COORDINATED record: "cutting"
        persisted -> some group may already serve S' — re-issue every
        cut (idempotent: an already-cut group just reports done);
        anything earlier -> no group cut, abort them all cleanly."""
        doc = self.journal.load_migration()
        if doc is None:
            return
        if doc.get("phase") == "cutting":
            log.warning("resuming interrupted coordinated schema "
                        "migration cut across %d groups",
                        len(self.groups))
            for c in self.groups:
                try:
                    self._mig_cut(c)
                except Exception as e:  # noqa: BLE001 - per-group
                    # the group's OWN persisted record finishes its cut
                    # at its next boot; this planner must still serve
                    log.warning("migration cut re-issue failed: %s", e)
            self._migration = {"phase": "done",
                               "groups": len(self.groups),
                               "recovered": True}
            metrics.counter("scaleout_schema_migrations_total",
                            outcome="boot-resumed").inc()
        else:
            log.warning("aborting interrupted coordinated schema "
                        "migration (phase %r, no cut persisted)",
                        doc.get("phase"))
            for c in self.groups:
                self._mig_abort(c)
            self._migration = {"phase": "aborted",
                               "groups": len(self.groups),
                               "recovered": True}
            metrics.counter("scaleout_schema_migrations_total",
                            outcome="boot-aborted").inc()
        self.journal.clear_migration()

    def migration_status(self) -> Optional[dict]:
        """Aggregate coordinated-migration status (or the per-group
        worst phase while one is in flight); None when this planner
        never migrated."""
        m = self._migration
        if m is None:
            return None
        out = dict(m)
        if m.get("phase") in ("dual-wait", "cutting"):
            lags = []
            for c in self.groups:
                try:
                    s = self._mig_status(c)
                except Exception:  # noqa: BLE001 - status best-effort
                    s = None
                if s is not None and s.get("lag") is not None:
                    lags.append(int(s["lag"]))
            out["lag"] = max(lags) if lags else None
        return out

    # -- scatter machinery ---------------------------------------------------

    def n_shards(self) -> int:
        return self.map.n_groups

    def admission_fanout(self, cls) -> int:
        """How many shards one op of ``cls`` will touch — the proxy-side
        admission multiplier (a scatter is charged once per touched
        shard)."""
        if cls is not None and cls.name in _SCATTER_CLASSES:
            # during a rebalance the scatter width includes the
            # transition-added groups
            return len(self.groups)
        return 1

    # scatter ops whose legs are PURE READS: a failed leg may be
    # re-issued once through the shared retry budget (writes/deletes
    # never — their at-least-once story is the journal's)
    _RETRYABLE_SCATTER = frozenset({
        "lookup_resources", "lookup_subjects", "read_relationships",
        "exists", "watch_since", "revision", "check_bulk",
        "frontier_expand",
    })

    def _scatter(self, op: str, fn,
                 shards: Optional[list] = None) -> dict:
        """Run ``fn(group_index, client)`` on the named shards (default:
        all) concurrently; returns {shard: result}. One shard shedding
        (AdmissionRejected) fails the WHOLE scatter closed with
        Retry-After = max over the shedding shards; a read leg dying on
        the transport gets ONE budget-gated re-issue (the group client
        already spent its own retries — this layer's re-issue draws
        from the SAME RetryBudget, so the stack stays bounded); any
        other error propagates after the fan-in."""
        targets = list(range(len(self.groups))) if shards is None \
            else sorted(set(shards))
        with tracer.span("shard_fanout", op=op, shards=len(targets)):
            if len(targets) == 1:
                gi = targets[0]
                _op_counter(gi, op, "scatter").inc()
                return {gi: fn(gi, self.groups[gi])}
            futs = {gi: self._pool.submit(fn, gi, self.groups[gi])
                    for gi in targets}
            results: dict = {}
            sheds: dict = {}
            first_err = None
            for gi, f in futs.items():
                _op_counter(gi, op, "scatter").inc()
                try:
                    results[gi] = f.result()
                except AdmissionRejected as e:
                    sheds[gi] = e
                except Exception as e:  # noqa: BLE001 - re-raised below
                    # re-issue only TRANSPORT deaths: an open breaker or
                    # a deadline-family rejection is deterministic on
                    # the immediate retry — withdrawing a token for it
                    # would drain the shared budget on attempts that
                    # cannot succeed (the group client already spent
                    # its own classified handling on those)
                    if op in self._RETRYABLE_SCATTER \
                            and isinstance(e, TRANSPORT_ERRORS) \
                            and self.retry_budget is not None \
                            and self.retry_budget.allow():
                        try:
                            results[gi] = fn(gi, self.groups[gi])
                            metrics.counter(
                                "scaleout_scatter_retries_total",
                                group=str(gi)).inc()
                            continue
                        except AdmissionRejected as e2:
                            sheds[gi] = e2
                            continue
                        except Exception as e2:  # noqa: BLE001
                            e = e2
                    if first_err is None:
                        first_err = e
        if sheds:
            # partial shed fails CLOSED: a gather missing one shard's
            # slice would silently hide that shard's resources (fail
            # open for list-prefilter denials). Retry-After is the max
            # over shards so a polite client outwaits the slowest one.
            metrics.counter("scaleout_partial_shed_total").inc()
            worst = max(sheds.values(), key=lambda e: e.retry_after)
            raise AdmissionRejected(
                worst.op_class,
                f"{len(sheds)}/{len(targets)} shards shed the scatter",
                retry_after=worst.retry_after,
                dependency="shard-admission")
        if first_err is not None:
            raise first_err
        return results

    def _single(self, gi: int, op: str, fn):
        _op_counter(gi, op, "single").inc()
        return fn(self.groups[gi])

    # -- checks --------------------------------------------------------------

    def _check_key(self, items: list, context: Optional[dict]):
        # context values include LISTS (the middleware's `groups`):
        # canonical JSON makes the key hashable and deterministic;
        # anything non-serializable simply bypasses the cache
        try:
            ctx = json.dumps(context, sort_keys=True,
                             separators=(",", ":")) if context else ""
        except (TypeError, ValueError):
            return None
        items_k = tuple(
            (it.resource_type, it.resource_id, it.permission,
             it.subject_type, it.subject_id, it.subject_relation)
            for it in items)
        return ("check", items_k, ctx)

    def try_cached_check(self, items: list,
                         context: Optional[dict] = None
                         ) -> Optional[list]:
        """Vector-keyed probe: the full verdict list only when a cached
        entry exists at EXACTLY the planner's current tracked vector."""
        if self.cache is None or not items:
            return None
        key = self._check_key(items, context)
        if key is None:
            return None
        return self.cache.get(key, self.vector)

    def check_bulk(self, items: list, now: Optional[float] = None,
                   context: Optional[dict] = None) -> list:
        """Plan the bulk: items grouped by their resource's owning
        shard; a single-shard bulk routes directly (NO scatter), a
        mixed bulk scatters only to the owning shards and reassembles
        in item order."""
        if not items:
            return []
        by_shard: dict[int, list] = {}
        for idx, it in enumerate(items):
            gi = self._read_anchor(it.resource_type, it.resource_id)
            by_shard.setdefault(gi, []).append(idx)
        cache_key = None
        if self.cache is not None and now is None:
            cache_key = self._check_key(items, context)
        vec_before = self.vector
        if len(by_shard) == 1:
            gi = next(iter(by_shard))
            out = self._single(
                gi, "check_bulk",
                lambda c: c.check_bulk(items, now=now, context=context))
        else:
            results = self._scatter(
                "check_bulk",
                lambda gi, c, _b=by_shard: c.check_bulk(
                    [items[i] for i in _b[gi]], now=now, context=context),
                shards=list(by_shard))
            out = [False] * len(items)
            with tracer.span("shard_merge", op="check_bulk"):
                for gi, idxs in by_shard.items():
                    for pos, verdict in zip(idxs, results[gi]):
                        out[pos] = bool(verdict)
        if self.frontier is not None and not all(out):
            # cross-shard closure pass for the locally-denied residue:
            # runs BEFORE the cache put so a frontier-granted verdict
            # caches at vec_before like any other (and a denial stays
            # a denial only after the exchange had its say)
            out = self._frontier_recheck(items, out, now, context)
        if cache_key is not None:
            # keyed at the vector observed BEFORE dispatch: any write
            # landing during the dispatch advances the tracked vector
            # and makes this entry unreachable (conservative, never
            # stale-serving)
            self.cache.put(cache_key, vec_before, list(out))
        return out

    def check(self, item: CheckItem, now: Optional[float] = None,
              context: Optional[dict] = None) -> bool:
        return self.check_bulk([item], now=now, context=context)[0]

    # -- lookups (scatter-gather) --------------------------------------------

    def lookup_resources(self, resource_type: str, permission: str,
                         subject_type: str, subject_id: str,
                         subject_relation: Optional[str] = None,
                         now: Optional[float] = None,
                         context: Optional[dict] = None) -> list:
        results = self._scatter(
            "lookup_resources",
            lambda gi, c: c.lookup_resources(
                resource_type, permission, subject_type, subject_id,
                subject_relation, now=now, context=context))
        with tracer.span("shard_merge", op="lookup_resources"):
            seen = set()
            out = []
            for gi in sorted(results):
                for rid in results[gi]:
                    if not self._admit_gathered(gi, resource_type, rid):
                        continue  # a mover copy, not the read owner
                    if rid not in seen:
                        seen.add(rid)
                        out.append(rid)
        if self.frontier is not None:
            # widen by the subject's cross-shard closure: each userset
            # the subject transitively belongs to is looked up as a
            # subject in its own right (exact for monotone schemas —
            # reference_pairs refused anything else)
            self._frontier_lookup_union(
                out, seen, resource_type, permission, subject_type,
                subject_id, subject_relation, now, context)
        metrics.histogram("scaleout_scatter_fanout").observe(
            len(results))
        return out

    def lookup_resources_mask(self, resource_type: str, permission: str,
                              subject_type: str, subject_id: str,
                              subject_relation: Optional[str] = None,
                              now: Optional[float] = None,
                              context: Optional[dict] = None):
        """Gathered mask: per-shard masks merge client-side into ONE
        (mask, id view) pair over the sorted union of allowed ids — the
        canonical gather form, independent of per-shard interner layout
        (so two deployments sharding the same tuples differently produce
        byte-identical masks)."""
        ids = self.lookup_resources(
            resource_type, permission, subject_type, subject_id,
            subject_relation, now=now, context=context)
        ids = sorted(ids)
        return (np.ones(len(ids), dtype=bool), RemoteInterner(ids))

    def lookup_subjects(self, resource_type: str, resource_id: str,
                        permission: str, subject_type: str,
                        subject_relation: Optional[str] = None,
                        now: Optional[float] = None,
                        context: Optional[dict] = None) -> list:
        """Anchored at ONE resource. A NAMESPACED anchor is exact on
        its owning shard alone: the resource's closure is shard-local
        (namespaced slice + replicated globals), and a subject whose
        tuples live only on OTHER shards has no path into that closure
        — so one direct call, not an n_groups scatter. GLOBAL anchors
        scatter and union: each shard's candidate subject universe
        covers its own namespaced slice, and a permitted subject must
        hold global tuples (visible to every shard), so the union is
        exact and mostly deduplicates."""
        _, namespaced = split_resource(resource_id)
        if namespaced:
            # owning shard under the CURRENT placement — a moving
            # slice's anchor follows the rebalance read owner
            owner = self._read_anchor(resource_type, resource_id)
            return self._single(
                owner, "lookup_subjects",
                lambda c: c.lookup_subjects(
                    resource_type, resource_id, permission,
                    subject_type, subject_relation, now=now,
                    context=context))
        results = self._scatter(
            "lookup_subjects",
            lambda gi, c: c.lookup_subjects(
                resource_type, resource_id, permission, subject_type,
                subject_relation, now=now, context=context))
        with tracer.span("shard_merge", op="lookup_subjects"):
            out = sorted({sid for got in results.values()
                          for sid in got})
        return out

    # -- cross-shard frontier exchange (scaleout/frontier.py) ----------------

    def _frontier_pair_set(self) -> tuple:
        """The schema's reference pairs, resolved lazily on first use:
        config-pinned, else asked of group 0 over the wire
        (``frontier_pairs`` op), else derived from its in-process
        schema. Every group serves the same schema (the coordinated
        migration guarantees it), so one group's answer is THE answer;
        the coordinated cut resets the cache so a migrated schema
        re-derives. A non-monotone schema raises FrontierError here —
        the exchange refuses to run rather than compose wrong."""
        if self.frontier is None:
            return ()
        pairs = self._frontier_pairs
        if pairs is None:
            c = self.groups[0]
            if hasattr(c, "frontier_pairs"):
                pairs = c.frontier_pairs()
            else:
                from .frontier import reference_pairs
                pairs = reference_pairs(c.schema)
            pairs = tuple(sorted((str(t), str(r)) for t, r in pairs))
            self._frontier_pairs = pairs
        return pairs

    def _frontier_leg(self, gi: int, c, descs, pairs, now, context):
        if hasattr(c, "frontier_expand"):
            return c.frontier_expand(descs, pairs, now=now,
                                     context=context)
        from .frontier import expand_local
        return expand_local(c, descs, pairs, now=now, context=context)

    def frontier_closure(self, subject_type: str, subject_id: str,
                         subject_relation: Optional[str] = None,
                         now: Optional[float] = None,
                         context: Optional[dict] = None) -> set:
        """The subject's cross-shard membership closure: every userset
        descriptor ``(type, id, relation)`` the subject transitively
        belongs to, computed by the iterative frontier exchange
        (scaleout/frontier.py module docstring). Each round scatters
        ONLY the newly-resolved boundary descriptors — the wire-bytes
        counters measure exactly the canonical encoding of what moved,
        in both directions. The round budget is HARD and fails CLOSED:
        an exhausted exchange returns the partial closure, which can
        only under-approximate (deny / under-list, never over-grant)."""
        pairs = self._frontier_pair_set()
        if not pairs:
            return set()
        from .frontier import encode_frontier
        max_rounds = max(1, int(self.frontier.max_rounds))
        seed = (str(subject_type), str(subject_id),
                None if subject_relation is None
                else str(subject_relation))
        visited = {seed}
        frontier = {seed}
        closure: set = set()
        rounds = 0
        outcome = "converged"
        with tracer.span("frontier_exchange",
                         subject=f"{seed[0]}:{seed[1]}"):
            while frontier:
                if rounds >= max_rounds:
                    outcome = "budget-exhausted"
                    log.warning(
                        "frontier exchange for %s:%s exhausted its "
                        "%d-round budget with %d descriptors still "
                        "unexpanded; proceeding with the partial "
                        "closure (fail-closed: may deny/under-list, "
                        "never over-grants)", seed[0], seed[1],
                        max_rounds, len(frontier))
                    break
                rounds += 1
                payload = encode_frontier(frontier)
                metrics.counter(
                    "scaleout_frontier_boundary_tuples_total").inc(
                        len(frontier))
                descs = sorted(
                    frontier, key=lambda d: (d[0], d[1], d[2] or ""))
                results = self._scatter(
                    "frontier_expand",
                    lambda gi, c, _d=descs: self._frontier_leg(
                        gi, c, _d, pairs, now, context))
                nxt: set = set()
                for gi in sorted(results):
                    got = results[gi]
                    metrics.counter(
                        "scaleout_frontier_wire_bytes_total",
                        direction="scatter").inc(len(payload))
                    metrics.counter(
                        "scaleout_frontier_wire_bytes_total",
                        direction="gather").inc(
                            len(encode_frontier(got)))
                    for d in got:
                        # mover copies filter here like any gather: a
                        # not-yet-cut destination (or un-GC'd source)
                        # must not smuggle a membership its read owner
                        # doesn't serve
                        if self._admit_gathered(gi, d[0], d[1]):
                            nxt.add(d)
                fresh = nxt - visited
                visited |= fresh
                closure |= fresh
                frontier = fresh
        metrics.histogram("scaleout_frontier_rounds").observe(rounds)
        metrics.counter("scaleout_frontier_exchanges_total",
                        outcome=outcome).inc()
        return closure

    def _frontier_recheck(self, items: list, out: list, now, context
                          ) -> list:
        """Second check pass for locally-denied items: compute each
        denied subject's closure once, then re-check the item on its
        resource's read owner with every closure descriptor as the
        subject — the owner holds the ``resource -> userset`` tuple and
        the engine seeds userset subjects natively, so ANY True means
        the cross-shard path exists and the item is granted. Monotone
        schemas only (enforced at pair derivation), so the union of
        verdicts is exact."""
        closures: dict = {}
        for pos, it in enumerate(items):
            if out[pos]:
                continue
            skey = (it.subject_type, it.subject_id,
                    it.subject_relation)
            if skey not in closures:
                closures[skey] = sorted(
                    self.frontier_closure(*skey, now=now,
                                          context=context),
                    key=lambda d: (d[0], d[1], d[2] or ""))
            descs = closures[skey]
            if not descs:
                continue
            gi = self._read_anchor(it.resource_type, it.resource_id)
            checks = [CheckItem(it.resource_type, it.resource_id,
                                it.permission, t, i, rel)
                      for (t, i, rel) in descs]
            verdicts = self._single(
                gi, "check_bulk",
                lambda c, _ck=checks: c.check_bulk(
                    _ck, now=now, context=context))
            if any(verdicts):
                out[pos] = True
        return out

    def _frontier_lookup_union(self, out: list, seen: set,
                               resource_type: str, permission: str,
                               subject_type: str, subject_id: str,
                               subject_relation, now, context) -> None:
        """Widen a gathered lookup by the subject's closure: each
        closure descriptor runs its own scatter as the subject, and the
        results union in (owner-filtered and deduped like the primary
        gather). Appends into ``out``/``seen`` in place."""
        closure = sorted(
            self.frontier_closure(subject_type, subject_id,
                                  subject_relation, now=now,
                                  context=context),
            key=lambda d: (d[0], d[1], d[2] or ""))
        for t, i, rel in closure:
            results = self._scatter(
                "lookup_resources",
                lambda gi, c, _t=t, _i=i, _r=rel: c.lookup_resources(
                    resource_type, permission, _t, _i, _r,
                    now=now, context=context))
            for gi in sorted(results):
                for rid in results[gi]:
                    if not self._admit_gathered(gi, resource_type,
                                                rid):
                        continue
                    if rid not in seen:
                        seen.add(rid)
                        out.append(rid)

    # -- relationship reads --------------------------------------------------

    def _filter_shards(self, f: RelationshipFilter) -> Optional[list]:
        """Owning shards of a filter, or None for "all" (scatter)."""
        if f.resource_type and f.resource_id:
            # namespaced: the current read owner (rebalance-aware);
            # global: replicated — ONE deterministic group
            return [self._read_anchor(f.resource_type, f.resource_id)]
        return None

    def read_relationships(self, f: RelationshipFilter) -> list:
        shards = self._filter_shards(f)
        if shards is not None and len(shards) == 1:
            return self._single(shards[0], "read_relationships",
                                lambda c: list(c.read_relationships(f)))
        results = self._scatter(
            "read_relationships",
            lambda gi, c: list(c.read_relationships(f)), shards=shards)
        with tracer.span("shard_merge", op="read_relationships"):
            seen = set()
            out = []
            for gi in sorted(results):
                for rel in results[gi]:
                    if not self._admit_gathered(gi, rel.resource_type,
                                                rel.resource_id):
                        continue  # a mover copy, not the read owner
                    k = rel.key()
                    if k not in seen:
                        seen.add(k)
                        out.append(rel)
        return out

    def exists(self, f: RelationshipFilter) -> bool:
        shards = self._filter_shards(f)
        if shards is not None and len(shards) == 1:
            return self._single(shards[0], "exists",
                                lambda c: c.store.exists(f))
        if self._copies_may_linger():
            # an UNANCHORED probe during/after a move: a bare boolean
            # from a group holding not-yet-caught-up (or not-yet-GC'd)
            # copies could answer True for a tuple its read owner
            # already deleted — gather the rows instead, so the
            # per-row owner filter applies (fail-closed, never stale)
            return bool(self.read_relationships(f))
        results = self._scatter("exists",
                                lambda gi, c: c.store.exists(f),
                                shards=shards)
        return any(results.values())

    # -- writes --------------------------------------------------------------

    def _plan_write(self, ops: list) -> dict[int, list]:
        """shard -> [WriteOp...]: namespaced tuples go to their owner,
        global tuples replicate to EVERY group (including rebalance-
        added ones — their global replica stays complete from the
        moment the transition installs). A moving slice in its
        dual-write window MIRRORS to both owners; a cut slice routes
        to the new owner only."""
        t = self._active_transition
        plan: dict[int, list] = {}
        for op in ops:
            gi = self.map.shard_of(op.rel.resource_type,
                                   op.rel.resource_id)
            if gi is None:
                for g in range(len(self.groups)):
                    plan.setdefault(g, []).append(op)
                continue
            owners = (gi,)
            if t is not None:
                sl = t.slice_for(op.rel.resource_type,
                                 op.rel.resource_id)
                if sl is not None:
                    owners = t.write_owners(sl)
                    if len(owners) > 1:
                        metrics.counter(
                            "scaleout_rebalance_dual_writes_total"
                        ).inc()
            for g in owners:
                plan.setdefault(g, []).append(op)
        return plan

    def _route_preconditions(self, pcs: list, plan_shards) -> dict:
        """shard -> [Precondition...], with EVERY decision point at or
        before the FIRST shard's apply: once the first shard has
        applied, the only failures left are transport/availability —
        which recovery may replay to completion. A precondition that
        could reject on a LATER shard would make the journal replay a
        write its caller was told failed.

        - anchored GLOBAL (replicated — the dtx lock tuple): binds
          atomically on the FIRST split shard only; replicas agree, so
          shard 0's verdict is THE verdict, and concurrent lock races
          serialize on that one store's atomic check-and-write;
        - namespaced with its owner = the first shard: binds there
          atomically;
        - everything else (unanchored, owner later in the split, owner
          outside the split): one routed existence probe decides it up
          front — NOT atomic with the split (loss table)."""
        out: dict[int, list] = {gi: [] for gi in plan_shards}
        first = min(plan_shards)
        for pc in pcs:
            f = pc.filter
            anchored = bool(f.resource_type and f.resource_id)
            gi = None
            if anchored and split_resource(f.resource_id)[1]:
                # namespaced anchor: the CURRENT read owner (a moving
                # slice's pc binds where its data is served from)
                gi = self._read_anchor(f.resource_type, f.resource_id)
            if gi is None and anchored:
                out[first].append(pc)
            elif gi is not None and gi == first:
                out[gi].append(pc)
            else:
                holds = self.exists(f)
                if holds != pc.must_exist:
                    raise PreconditionFailed(
                        "cross-shard precondition on "
                        f"{f.resource_type or '*'}:"
                        f"{f.resource_id or '*'} failed")
        return out

    def write_relationships(self, ops: list,
                            preconditions: list = ()):
        # cutover gates for any moving slice this write touches: held
        # across planning AND dispatch, so the flip's freeze observes
        # a quiesced slice (non-moving writes never wait here)
        gates = self._enter_write_gates(ops)
        try:
            plan = self._plan_write(ops)
            if not plan:
                return self.vector
            if len(plan) == 1:
                gi = next(iter(plan))
                # preconditions route like the split path: ones this
                # shard can decide (its own slice, or a replicated
                # global) bind atomically; a namespaced pc owned
                # ELSEWHERE is probed through the planner — the target
                # shard's store simply doesn't hold it (a must_exist
                # would always fail, a must_not_exist would always
                # pass: fail open)
                pcs = self._route_preconditions(list(preconditions),
                                                [gi]).get(gi, [])
                rev = self._single(
                    gi, "write_relationships",
                    lambda c: c.write_relationships(plan[gi], pcs))
                self._observe_revision(gi, rev)
                return self.vector
            return self._split_write(plan, list(preconditions))
        finally:
            for g in gates:
                g.exit()

    def _split_write(self, plan: dict, preconditions: list):
        """Cross-shard split: journal the full plan durably, apply
        shard-by-shard in index order through each group's ordinary
        WAL/ack path, mark progress, delete the entry when complete. A
        crash between any two steps leaves a pending journal entry the
        next planner replays (creates degraded to touches: idempotent
        against a shard that applied before the crash)."""
        if self.journal is not None and self.journal.pending_count():
            # deferred recovery (an unreachable shard at boot): retry
            # BEFORE journaling new work so replays keep write order
            try:
                self.recover_splits()
            except Exception as e:  # noqa: BLE001 - still best-effort
                log.warning("split-write recovery still deferred: %s",
                            e)
        pcs_by_shard = self._route_preconditions(preconditions,
                                                 list(plan))
        sid = None
        if self.journal is not None:
            t = self._active_transition
            sid = self.journal.begin(
                {gi: [{"op": o.op, "rel": _rel_to_dict(o.rel)}
                      for o in plan[gi]] for gi in plan},
                [{"filter": asdict(p.filter),
                  "must_exist": p.must_exist}
                 for p in preconditions],
                self.map.version,
                # a dual-write window split is tagged with BOTH
                # versions: its recorded owners are already the union
                # of the two placements, so replay must not re-route it
                map_version_to=(t.new_map.version
                                if t is not None else None))
        with tracer.span("shard_fanout", op="split_write",
                         shards=len(plan)):
            first = True
            for gi in sorted(plan):
                try:
                    rev = self._single(
                        gi, "write_relationships",
                        lambda c, _gi=gi: c.write_relationships(
                            plan[_gi], pcs_by_shard.get(_gi, [])))
                except _PROVABLY_NOT_APPLIED:
                    if first and sid is not None:
                        # provably nothing applied anywhere — close
                        # the entry so recovery doesn't resurrect a
                        # write whose rejection the caller already
                        # saw. Later shards can only fail via the
                        # transport (every decision point is at the
                        # first shard — _route_preconditions), so a
                        # pending entry is always safe to complete.
                        self.journal.finish(sid)
                    raise
                # any OTHER failure is AMBIGUOUS (transport death,
                # exhausted deadline — FailoverEngine's own rule: 'an
                # exhausted deadline may have dispatched'): the write
                # MAY have applied even on the first shard, so the
                # entry STAYS pending and recovery touch-replays
                # everything — the caller's error means at-LEAST-once,
                # never silently half-applied
                first = False
                self._observe_revision(gi, rev)
                if sid is not None:
                    self.journal.mark_applied(sid, gi)
        if sid is not None:
            self.journal.finish(sid)
        return self.vector

    def delete_relationships(self, f: RelationshipFilter,
                             preconditions: list = ()) -> int:
        owner = None
        namespaced = False
        if f.resource_type and f.resource_id:
            _, namespaced = split_resource(f.resource_id)
            if namespaced:
                owner = self.map.shard_of(f.resource_type, f.resource_id)
        t = self._active_transition
        gates: tuple = ()
        if t is not None:
            # cutover gates: an anchored delete gates its own slice; an
            # unanchored/global delete may touch ANY moving slice, so it
            # gates them all — a delete slipping between the flip's
            # final drain and the cut record would vanish from the new
            # owner (stale allow after cutover)
            if namespaced:
                sl = t.slice_for(f.resource_type, f.resource_id)
                slices = [sl] if sl is not None else []
            else:
                slices = sorted(t.slices, key=lambda s: s.sid)
            for sl in slices:
                sl.gate.enter()
            gates = tuple(sl.gate for sl in slices)
        try:
            return self._delete_routed(f, preconditions, owner, t,
                                       namespaced)
        finally:
            for g in gates:
                g.exit()

    def _delete_routed(self, f: RelationshipFilter, preconditions,
                       owner, t, namespaced: bool) -> int:
        if owner is not None:
            # a namespaced anchor: ONE owning slice — mirrored to both
            # owners during its dual-write window; preconditions it
            # cannot decide locally probe through the planner (same
            # routing rule as writes). The first owner (= the read
            # owner) decides preconditions and the reported count.
            owners = (owner,)
            if t is not None:
                sl = t.slice_for(f.resource_type, f.resource_id)
                if sl is not None:
                    owners = t.write_owners(sl)
            first = owners[0]
            pcs = self._route_preconditions(list(preconditions),
                                            [first]).get(first, [])
            n = self._single(
                first, "delete_relationships",
                lambda c: c.delete_relationships(f, pcs))
            self._observe_revision(first, self._group_revision(first))
            for gi in owners[1:]:
                self._single(
                    gi, "delete_relationships",
                    lambda c: c.delete_relationships(f, []))
                self._observe_revision(gi, self._group_revision(gi))
            return n
        # global anchor or unanchored filter: every group holds matching
        # rows (replicas, or disjoint namespaced slices). Preconditions
        # bind once — on group 0, the deterministic decision shard, and
        # they are decided BEFORE any other leg deletes anything: group
        # 0's leg runs alone first, so a failed precondition aborts the
        # whole delete with every other shard untouched (concurrent
        # legs would otherwise delete while the caller is told the op
        # failed). Deletes are idempotent by construction, so a failed
        # non-decision leg is safe to re-issue (no journal needed).
        pcs0 = self._route_preconditions(list(preconditions),
                                         [0]).get(0, [])
        results = {0: self._single(
            0, "delete_relationships",
            lambda c: c.delete_relationships(f, pcs0))}
        self._observe_revision(0, self._group_revision(0))
        rest = [g for g in range(len(self.groups)) if g != 0]
        if rest:
            results.update(self._scatter(
                "delete_relationships",
                lambda gi, c: c.delete_relationships(f, []),
                shards=rest))
        for gi in rest:
            self._observe_revision(gi, self._group_revision(gi))
        if f.resource_type and f.resource_id and not namespaced:
            # replicated rows: every group deleted the SAME tuples —
            # report one copy, not n_groups copies
            return int(max(results.values()))
        # disjoint namespaced slices (plus possibly replicated global
        # rows, over-counted — documented in the loss table)
        return int(sum(results.values()))

    def _group_revision(self, gi: int):
        try:
            return self.groups[gi].revision
        except Exception:  # noqa: BLE001 - tracking is best-effort
            return None

    # -- split-write recovery ------------------------------------------------

    def recover_splits(self) -> int:
        """Replay every pending split to completion; returns how many
        entries were finished. Creates degrade to touches (idempotent
        re-application); preconditions are NOT re-checked — the split
        was already past its decision point when it journaled."""
        if self.journal is None:
            return 0
        done = 0
        known_versions = self._known_map_versions()
        for ent in self.journal.pending():
            # a dual-write window split carries BOTH versions; it is
            # valid as long as EITHER names a placement this planner
            # routes (the recorded owners are already the union), and
            # its shard indices address the extended group space
            rerouted = ((ent["map_version"] not in known_versions
                         and ent.get("map_version_to")
                         not in known_versions)
                        or any(gi >= len(self.groups)
                               for gi in ent["plan"]))
            if rerouted:
                # journaled under a DIFFERENT map (rebalance between
                # the crash and this boot, possibly with fewer groups):
                # the recorded shard indices no longer name today's
                # owners — collect every unapplied shard's ops and
                # re-plan them against the CURRENT map instead of
                # dereferencing stale indices (which would crash boot)
                log.warning(
                    "split %s journaled under map version %d (current "
                    "%d): re-routing the unapplied ops through the "
                    "current map", ent["id"], ent["map_version"],
                    self.map.version)
                ops = [WriteOp("touch" if d["op"] == "create"
                               else d["op"], _rel_from_dict(d["rel"]))
                       for gi, raw in sorted(ent["plan"].items())
                       if gi not in ent["applied"]
                       for d in raw]
                # dedupe (a global tuple appears once per old shard)
                seen = set()
                ops = [o for o in ops
                       if not (o.rel.key() in seen
                               or seen.add(o.rel.key()))]
                for gi, part in sorted(self._plan_write(ops).items()):
                    rev = self._single(
                        gi, "write_relationships",
                        lambda c, _o=part: c.write_relationships(_o,
                                                                 []))
                    self._observe_revision(gi, rev)
            else:
                for gi, raw_ops in sorted(ent["plan"].items()):
                    if gi in ent["applied"]:
                        continue
                    ops = [WriteOp("touch" if d["op"] == "create"
                                   else d["op"],
                                   _rel_from_dict(d["rel"]))
                           for d in raw_ops]
                    rev = self._single(
                        gi, "write_relationships",
                        lambda c, _o=ops: c.write_relationships(_o, []))
                    self._observe_revision(gi, rev)
                    self.journal.mark_applied(ent["id"], gi)
            self.journal.finish(ent["id"])
            done += 1
            metrics.counter("scaleout_split_replays_total").inc()
        return done

    # -- watch ---------------------------------------------------------------

    def watch_since(self, revision) -> list:
        """Events after a VECTOR resumption token (translated through
        recorded map transitions when minted under an older map),
        merged shard-by-shard with monotone vector stamps. Moving-slice
        events pass the rebalance delivery filter: read-owner-only, so
        the replay is gap- and duplicate-free across a cutover."""
        vec = self._resolve_token(revision)
        results = self._scatter(
            "watch_since",
            lambda gi, c: c.watch_since(int(vec[gi])))
        with tracer.span("shard_merge", op="watch_since"):
            out = []
            cur = vec
            for gi in sorted(results):
                for e in results[gi]:
                    # the stamp always advances past the record — a
                    # suppressed mover echo must move the resumption
                    # token forward, never be re-delivered
                    cur = cur.bump(gi, e.revision)
                    if self._deliver_event(gi, e.relationship,
                                           e.revision):
                        out.append(WatchEvent(cur, e.operation,
                                              e.relationship))
        return out

    def watch_push_stream(self, from_revision) -> ShardedWatchStream:
        return ShardedWatchStream(self,
                                  self._resolve_token(from_revision))

    def watch_gate(self, resource_type: str, name: str):
        """Schema-derived, identical on every group: ask the anchor
        shard of the named object."""
        gi = self.map.anchor_shard(resource_type, name or "")
        return self._single(gi, "watch_gate",
                            lambda c: c.watch_gate(resource_type, name))

    # -- status / lifecycle --------------------------------------------------

    STATUS_PROBE_TIMEOUT = 1.5

    def sharding_status(self) -> dict:
        """Per-group role/lag + map version for ``/readyz``'s
        ``sharding:`` info line — a degraded group is visible BEFORE it
        sheds. Probes fan out on the scatter pool with a SHORT bound:
        sequential per-group connect timeouts would stall the readiness
        probe past a kubelet's budget and unready the replica — the
        exact outcome the informational line exists to avoid."""
        def probe(c):
            if hasattr(c, "replication_status"):
                return c.replication_status() or {}
            if hasattr(c, "failover_state"):
                return c.failover_state() or {}
            return {"role": "local", "lag": 0}

        futs = [self._pool.submit(probe, c) for c in self.groups]
        groups = []
        for gi, f in enumerate(futs):
            try:
                st = f.result(timeout=self.STATUS_PROBE_TIMEOUT)
            except Exception:  # noqa: BLE001 - status is best-effort
                st = {"role": "unreachable", "lag": None}
            groups.append({"group": gi, "role": st.get("role"),
                           "term": st.get("term"),
                           "lag": st.get("lag")})
        return {
            "version": self.map.version,
            "groups": groups,
            "vector": self.vector.encode(
                map_version=self.map.version),
            "pending_splits": (self.journal.pending_count()
                               if self.journal is not None else 0),
            # the live tuple mover's progress, or None outside a
            # transition window (/readyz renders it as
            # `rebalance: moving=K copied=J lag=...`)
            "rebalance": self.rebalance_status(),
            # the coordinated schema migration's progress, or None
            # (/readyz renders it as `migration: phase=... lag=...`)
            "migration": self.migration_status(),
        }

    def fetch_traces(self, limit: int = 64) -> list:
        out: list = []
        for c in self.groups:
            try:
                if hasattr(c, "fetch_traces"):
                    out.extend(c.fetch_traces(limit))
            except Exception:  # noqa: BLE001 - diagnostics best-effort
                continue
        return out

    def close(self, close_journal: bool = True) -> None:
        """``close_journal=False`` leaves a SHARED journal open (e.g. a
        crashed planner's journal that a successor will replay)."""
        if self._coordinator is not None:
            # park the mover; its persisted state resumes or aborts by
            # the crash matrix at the next boot
            self._coordinator.stop()
        self._pool.shutdown(wait=False, cancel_futures=True)
        for c in list(self.groups) + list(self._retired_clients):
            try:
                if hasattr(c, "close"):
                    c.close()
            except Exception:  # noqa: BLE001 - teardown best effort
                pass
        if close_journal and self.journal is not None:
            self.journal.close()
