"""The shard map: consistent-hash partitioning of the relationship space.

Scale-out (ROADMAP item 4) partitions TUPLES, not replicas: each engine
*group* (its own failover set of engine hosts, reusing the ``--peers``
machinery) owns a slice of the relationship space, so capacity grows by
adding shards instead of mirrors. The partition key is
``(namespace, resource-type)``:

- **namespaced** tuples — resource ids of the kube ``ns/name`` shape —
  hash by the namespace portion plus the resource type onto exactly one
  group (the blocked decomposition RedisGraph/GraphBLAS applies to
  matrix tiles, applied here at the cluster level);
- **global** tuples — bare resource ids with no ``/`` (namespaces
  themselves, groups, dtx lock tuples, workflow markers) — REPLICATE to
  every group. They are the reference data cross-namespace reachability
  walks through (``pod -> namespace -> viewer``, ``viewer ->
  group#member``); replicating them keeps every query's closure inside
  one shard, which is what makes single-shard checks exact and
  scatter-gather a plain union.

The map is an EXPLICIT, versioned artifact the proxy loads from a flag
or file — routing is deterministic and testable, never discovered. A
rebalance is a new map version; the version rides ``/readyz`` and the
split-write journal so a mixed-version fleet is visible.

``RevisionVector`` is the consistency token of a sharded deployment: one
revision per group, totally ordered along any one planner's history
(components only advance). Decision-cache keys and watch resumption
carry the vector, never a scalar.
"""

from __future__ import annotations

import hashlib
import json
import os
from bisect import bisect_right
from dataclasses import dataclass
from typing import Optional


class ShardMapError(ValueError):
    pass


class RevisionVector(tuple):
    """One store revision per shard group. A plain-tuple subclass so it
    JSON-serializes (as a list), hashes (cache keys), and totally orders
    lexicographically — which agrees with the causal partial order along
    any monotone stream (components never go backward, so of two vectors
    observed on one stream the later one is component-wise >=)."""

    __slots__ = ()

    @classmethod
    def zero(cls, n: int) -> "RevisionVector":
        return cls((0,) * n)

    def bump(self, shard: int, revision: int) -> "RevisionVector":
        """This vector with ``shard``'s component advanced to
        ``revision`` (never regressed)."""
        return RevisionVector(
            max(int(revision), c) if i == shard else c
            for i, c in enumerate(self))

    def join(self, other) -> "RevisionVector":
        """Component-wise max — the merge point of two observations."""
        return RevisionVector(max(a, b) for a, b in zip(self, other))

    def dominates(self, other) -> bool:
        """True iff every component is >= ``other``'s."""
        return all(a >= b for a, b in zip(self, other))

    def extend(self, n: int) -> "RevisionVector":
        """This vector padded with zero components up to length ``n`` —
        the grow-transition translation: a brand-new group's history
        starts empty, so a token minted before the group existed resumes
        it from revision 0 (the rebalance event filter suppresses the
        copy/catch-up records below the cutover watermark)."""
        if n <= len(self):
            return self
        return RevisionVector(tuple(self) + (0,) * (n - len(self)))

    def drop_component(self, shard: int) -> "RevisionVector":
        """This vector with ``shard``'s component REMOVED — the
        shrink-transition translation, dual to :meth:`extend`: once a
        retiring group's slices have all cut over and its copies are
        GC'd, the group's history is closed and surviving components
        renumber down by one past the gap. Only valid when the dropped
        component's consumer has already observed everything the
        retiring group will ever produce (the planner checks the token
        against the transition's cut watermark before translating)."""
        if not 0 <= shard < len(self):
            raise ShardMapError(
                f"cannot drop component {shard} from a "
                f"{len(self)}-component revision vector")
        return RevisionVector(tuple(self)[:shard]
                              + tuple(self)[shard + 1:])

    def encode(self, map_version: Optional[int] = None) -> str:
        """``v1.2.3`` — or ``v1.2.3@m4`` when ``map_version`` is given:
        the shard-map version the component INDICES were minted under.
        Component *i* only names a group under one map; a token resumed
        against a different map must be translated (rebalance) or
        rejected, never silently re-bound to whatever group now sits at
        index *i*."""
        body = "v" + ".".join(str(int(c)) for c in self)
        if map_version is not None:
            return f"{body}@m{int(map_version)}"
        return body

    @classmethod
    def parse_versioned(cls, s) -> tuple["RevisionVector", Optional[int]]:
        """``(vector, minted_map_version-or-None)`` — the version a
        string token carries (``@m<V>`` suffix); sequences and untagged
        strings parse with version ``None`` (provenance unknown)."""
        if isinstance(s, RevisionVector):
            return s, None
        if isinstance(s, int):
            raise ShardMapError(
                "a scalar revision needs a shard count; use "
                "RevisionVector.zero(n).bump(...) or pass a vector")
        if isinstance(s, (list, tuple)):
            return cls(int(c) for c in s), None
        t = str(s).strip()
        ver = None
        if "@m" in t:
            t, _, vtext = t.partition("@m")
            try:
                ver = int(vtext)
            except ValueError:
                raise ShardMapError(
                    f"invalid revision vector {s!r}") from None
        if not t.startswith("v"):
            raise ShardMapError(f"invalid revision vector {s!r}")
        try:
            return cls(int(c) for c in t[1:].split(".")), ver
        except ValueError:
            raise ShardMapError(
                f"invalid revision vector {s!r}") from None

    @classmethod
    def parse(cls, s, map_version: Optional[int] = None
              ) -> "RevisionVector":
        """Accepts an ``encode()`` string, a sequence, or a plain int
        (a scalar resumption token: every component starts there).
        ``map_version`` is the consumer's CURRENT shard-map version:
        a token tagged with a different version is REJECTED instead of
        silently binding components to the wrong group index (a 2-group
        vector resumed against a 3-group map would misindex — re-list,
        or let the planner translate it through a known transition)."""
        vec, ver = cls.parse_versioned(s)
        if map_version is not None and ver is not None \
                and ver != int(map_version):
            raise ShardMapError(
                f"revision vector {s!r} was minted under shard-map "
                f"version {ver}, not the current version "
                f"{int(map_version)}; its components would bind to the "
                "wrong groups — re-list and re-watch")
        return vec


def split_resource(resource_id: str) -> tuple[str, bool]:
    """``(namespace, namespaced?)`` of a resource id: the kube
    ``ns/name`` convention carries the namespace before the first slash;
    a bare id is a GLOBAL object (cluster-scoped — replicated to every
    group)."""
    if "/" in resource_id:
        return resource_id.split("/", 1)[0], True
    return "", False


def _hash32(key: str) -> int:
    return int.from_bytes(
        hashlib.blake2s(key.encode("utf-8"), digest_size=4).digest(),
        "big")


HASH_SPACE = 1 << 32


def hash_key(namespace: str, resource_type: str) -> int:
    """The partition-key hash the ring routes by — exported so the
    rebalance planner and the engine-host slice ops agree byte-for-byte
    on slice membership."""
    return _hash32(f"{namespace}\x00{resource_type}")


@dataclass(frozen=True)
class ShardMap:
    """Versioned, deterministic tuple-space partition.

    ``groups`` is a tuple of endpoint lists — one list per engine group,
    each list the group's failover set in peer-id order (the same grammar
    as ``--engine-endpoint tcp://h1:p1,h2:p2``). ``virtual_nodes`` sets
    the ring granularity: more points smooth the key distribution at the
    cost of a bigger (still tiny) ring.
    """

    version: int
    groups: tuple  # tuple[tuple[(host, port), ...], ...]
    virtual_nodes: int = 64

    def __post_init__(self):
        if self.version < 1:
            raise ShardMapError("shard map version must be >= 1")
        if not self.groups:
            raise ShardMapError("shard map needs >= 1 group")
        if self.virtual_nodes < 1:
            raise ShardMapError("virtual_nodes must be >= 1")
        # the ring: virtual_nodes points per group, keyed by GROUP INDEX
        # (not endpoints) so replacing a dead host inside a group never
        # moves any data — only adding/removing whole groups does
        points = []
        for gi in range(len(self.groups)):
            for r in range(self.virtual_nodes):
                points.append((_hash32(f"group{gi}:vn{r}"), gi))
        points.sort()
        object.__setattr__(self, "_ring_keys",
                           tuple(p[0] for p in points))
        object.__setattr__(self, "_ring_groups",
                           tuple(p[1] for p in points))

    @property
    def n_groups(self) -> int:
        return len(self.groups)

    def shard_for(self, namespace: str, resource_type: str) -> int:
        """The owning group of a ``(namespace, resource-type)`` key —
        clockwise successor on the hash ring."""
        return self.owner_of_hash(hash_key(namespace, resource_type))

    def owner_of_hash(self, h: int) -> int:
        """Owning group of a raw partition-key hash (the rebalance
        planner diffs two maps' assignments segment-by-segment)."""
        keys = self._ring_keys
        i = bisect_right(keys, h)
        if i == len(keys):
            i = 0
        return self._ring_groups[i]

    def ring_points(self) -> tuple:
        """The sorted ring-point hashes (segment boundaries for the
        rebalance plan diff)."""
        return self._ring_keys

    def shard_of(self, resource_type: str, resource_id: str):
        """Owning group index for one tuple/query anchor, or ``None``
        when the object is GLOBAL (replicated to every group)."""
        ns, namespaced = split_resource(resource_id)
        if not namespaced:
            return None
        return self.shard_for(ns, resource_type)

    def anchor_shard(self, resource_type: str, resource_id: str) -> int:
        """A deterministic SINGLE group to answer a read anchored at one
        object: the owning shard for namespaced objects; for global
        objects (replicated everywhere) the hash of the bare id — so
        repeated reads of one object land on one group (warm caches)
        while distinct global objects spread the load."""
        owner = self.shard_of(resource_type, resource_id)
        if owner is not None:
            return owner
        return self.shard_for(resource_id, resource_type)

    def zero_vector(self) -> RevisionVector:
        return RevisionVector.zero(self.n_groups)

    def describe(self) -> str:
        return (f"version={self.version} groups={self.n_groups} "
                + " ".join(
                    f"g{i}={len(eps)}ep" for i, eps in
                    enumerate(self.groups)))


def map_to_doc(m: ShardMap) -> dict:
    """The JSON document form of a map (``map_from_doc`` is the exact
    inverse) — the rebalance transition persists its target map this
    way so a restarted planner reconstructs the same ring. Endpoints
    round-trip as raw ``[host, port]`` pairs, NOT the CLI ``host:port``
    grammar: the record is internal, and in-process test topologies
    legitimately carry port-0 placeholders the grammar rejects."""
    return {"version": m.version,
            "groups": [[[h, p] for h, p in g] for g in m.groups],
            "virtual_nodes": m.virtual_nodes}


def map_from_doc(doc: dict) -> ShardMap:
    """Inverse of :func:`map_to_doc` (internal round-trip; see there)."""
    try:
        return ShardMap(
            version=int(doc["version"]),
            groups=tuple(tuple((str(h), int(p)) for h, p in g)
                         for g in doc["groups"]),
            virtual_nodes=int(doc.get("virtual_nodes", 64)))
    except (KeyError, TypeError, ValueError):
        raise ShardMapError(
            f"malformed internal shard-map document: {doc!r}") from None


def parse_shard_map(text: str) -> ShardMap:
    """Parse the JSON shard-map document::

        {"version": 1,
         "groups": [["127.0.0.1:7001", "127.0.0.1:7002"],
                    ["127.0.0.1:7011"]],
         "virtual_nodes": 64}
    """
    try:
        doc = json.loads(text)
    except ValueError as e:
        raise ShardMapError(f"shard map is not valid JSON: {e}") from None
    return parse_shard_map_doc(doc)


def parse_shard_map_doc(doc) -> ShardMap:
    if not isinstance(doc, dict):
        raise ShardMapError("shard map must be a JSON object")
    try:
        version = int(doc["version"])
        raw_groups = doc["groups"]
    except (KeyError, TypeError, ValueError):
        raise ShardMapError(
            "shard map needs integer 'version' and list 'groups'"
        ) from None
    if not isinstance(raw_groups, list) or not raw_groups:
        raise ShardMapError("shard map 'groups' must be a non-empty list")
    from ..parallel.failover import FailoverError, parse_peers

    groups = []
    for gi, eps in enumerate(raw_groups):
        if isinstance(eps, str):
            eps = [eps]
        if not isinstance(eps, list) or not eps:
            raise ShardMapError(
                f"shard map group {gi} must be a non-empty endpoint list")
        try:
            # one owner for the host:port grammar (failover --peers /
            # --engine-endpoint already delegate here)
            groups.append(tuple(parse_peers(",".join(
                str(e) for e in eps))))
        except FailoverError as e:
            raise ShardMapError(
                f"shard map group {gi}: {e}") from None
    try:
        vnodes = int(doc.get("virtual_nodes", 64))
    except (TypeError, ValueError):
        raise ShardMapError(
            "shard map 'virtual_nodes' must be an integer") from None
    return ShardMap(version=version, groups=tuple(groups),
                    virtual_nodes=vnodes)


def load_shard_map(spec: str) -> ShardMap:
    """``--shard-map`` value: inline JSON (starts with ``{``) or a path
    to a JSON file."""
    spec = spec.strip()
    if spec.startswith("{"):
        return parse_shard_map(spec)
    if not os.path.exists(spec):
        raise ShardMapError(f"shard map file not found: {spec}")
    with open(spec) as f:
        return parse_shard_map(f.read())
