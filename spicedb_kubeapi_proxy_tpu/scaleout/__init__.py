"""Scale-out: hash-partitioned engine shards + scatter-gather planner.

ROADMAP item 4 — the "millions of users" story: replication (PR 4)
scales reads of ONE graph, the delta overlay (PR 8) scales one graph's
write path, but every engine group still held every tuple. This
subsystem partitions the relationship space itself:

- ``shardmap.py`` — the explicit, versioned :class:`ShardMap`:
  consistent-hash partitioning of tuples by ``(namespace,
  resource-type)`` onto N engine groups (each group its own failover
  set), global (cluster-scoped) tuples replicated to every group so
  query closures stay shard-local; plus :class:`RevisionVector`, the
  one-revision-per-shard consistency token.
- ``planner.py`` — :class:`ShardedEngine`, the proxy-side planner:
  single-shard checks/writes route directly, LookupResources /
  list-prefilters / LookupSubjects / watch streams scatter to every
  group and gather client-side at a revision vector; partial sheds
  fail closed with Retry-After = max over shards.
- ``journal.py`` — the dtx-style :class:`SplitJournal`: cross-shard
  writes are journaled durably before the first shard applies, so a
  mid-split crash replays to completion instead of leaving a silent
  half-write; also the durable home of the live-rebalance transition
  record.
- ``rebalance.py`` — the online tuple mover: map V -> V+1 without a
  drain, via plan / copy / catch-up / dual-write / per-slice cutover /
  GC (:class:`RebalanceCoordinator`), with read-owner-only watch
  delivery keeping merged streams exact across the flip — in BOTH
  directions: a shrink (:func:`shrink_map`) empties the retiring tail
  group through the same machinery, GCs it BEFORE commit, and drops
  its revision-vector component at commit.
- ``frontier.py`` — the cross-shard frontier exchange: iterative
  membership-closure joins where only boundary tuples ride the wire,
  lifting the cluster-scoped-only restriction on cross-namespace
  reference types (monotone schemas; fail-closed round budget).
"""

from .frontier import (  # noqa: F401
    FrontierConfig,
    FrontierError,
    decode_frontier,
    encode_frontier,
    expand_local,
    reference_pairs,
)
from .journal import SplitJournal  # noqa: F401
from .planner import (  # noqa: F401
    ShardedEngine,
    ShardedWatchStream,
    ShardVectorCache,
)
from .rebalance import (  # noqa: F401
    MapTransition,
    MovingSlice,
    RebalanceCoordinator,
    RebalanceError,
    abort_transition,
    plan_moves,
    shrink_map,
)
from .shardmap import (  # noqa: F401
    RevisionVector,
    ShardMap,
    ShardMapError,
    hash_key,
    load_shard_map,
    map_to_doc,
    parse_shard_map,
    split_resource,
)
