"""TPU-native Kubernetes authorizing proxy.

A brand-new framework with the capabilities of
``josephschorr/spicedb-kubeapi-proxy`` (reference at /root/reference — see
SURVEY.md): a reverse proxy in front of a kube-apiserver that

- authorizes every request against a Zanzibar-style relationship graph,
- filters responses (single objects, lists, tables, watch streams) down to
  what the caller may see, and
- durably dual-writes relationship updates + Kubernetes objects in one
  logical transaction,

with the authorization hot path (CheckPermission / LookupResources / list
filtering) executed on TPU: the relationship graph is compiled into a flat
slot-space of (type, relation, object) booleans plus one global
(dst, src) edge tensor, and permission evaluation is a jitted fixpoint of
gather/segment-max propagation + an elementwise userset-rewrite program
(see ops/reachability.py).

Subpackages
-----------
- ``models``   — schema DSL (definitions/relations/permissions) parser + IR
- ``engine``   — relationship store, snapshots, the query engine (the
                 embedded-SpiceDB replacement; reference pkg/spicedb)
- ``ops``      — JAX/XLA kernels for batched reachability
- ``parallel`` — device-mesh sharding of the edge tensors (shard_map + psum)
- ``rules``    — ProxyRule config + template/expression compiler
                 (reference pkg/rules, pkg/config/proxyrule)
- ``authz``    — per-request authorization middleware + response filtering
                 (reference pkg/authz)
- ``proxy``    — HTTP server, authn, reverse proxy, in-memory transport
                 (reference pkg/proxy, pkg/inmemory)
- ``dtx``      — durable dual-write workflow engine
                 (reference pkg/authz/distributedtx)
- ``persistence`` — store durability: segmented write-ahead log,
                 snapshot checkpoints, crash recovery (``--data-dir``)
- ``utils``    — failpoints, metrics, logging
"""

__version__ = "0.1.0"
