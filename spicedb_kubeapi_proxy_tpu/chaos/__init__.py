"""Chaos campaign: deterministic fault schedules, end-to-end safety
invariants, and the campaign runner that judges them together.

- :mod:`.schedule` — seeded fault schedules over the named fault space
  (utils/failpoints.py sites), armable locally or over the wire on
  subprocess engine hosts (``chaos_arm``, flag-gated);
- :mod:`.invariants` — never-fail-open, zero-acked-write-loss,
  no-stale-verdict, split-journal-completion, retry-amplification;
- :mod:`.campaign` — drives the loadgen open-loop schedule against a
  full topology (2 shard groups × 2-peer failover × the planner stack)
  under fault schedules and SIGKILL/restart cycles, checking every
  invariant after each episode (``make chaos-campaign``).
"""

from .invariants import (
    EpisodeEvidence,
    InvariantViolation,
    OpRecord,
    check_all,
    check_never_fail_open,
    check_no_stale_verdict,
    check_retry_amplification,
    check_split_journal_complete,
    check_zero_acked_write_loss,
    retry_amplification_bound,
)
from .schedule import (
    ChaosScheduleError,
    FaultSchedule,
    FaultSpec,
    brownout_schedule,
    parse_action,
)

__all__ = [
    "ChaosScheduleError", "EpisodeEvidence", "FaultSchedule",
    "FaultSpec", "InvariantViolation", "OpRecord", "brownout_schedule",
    "check_all", "check_never_fail_open", "check_no_stale_verdict",
    "check_retry_amplification", "check_split_journal_complete",
    "check_zero_acked_write_loss", "parse_action",
    "retry_amplification_bound",
]
