"""System-wide safety invariants checked against live campaign evidence.

Each checker consumes the campaign's op records (what the client tier
actually observed: verdicts, acks, sheds, errors, Retry-After hints) and
the post-episode system state (read-backs, journal, counters), and
returns :class:`InvariantViolation` records — an empty list is the only
acceptable outcome. The four invariants are the ones the README's
dual-write semantics and PRs 1/3/4/11 individually promised; here they
are judged TOGETHER, under combined faults:

- **never-fail-open** — an injected fault may cost availability, never
  authority: a probe for a permission the oracle denies must answer
  deny/error/shed, NEVER allow. Shed outcomes must carry a bounded
  Retry-After.
- **zero-acked-write-loss** — every write the client tier saw
  acknowledged is present after every crash/failover/split-replay in
  the episode (the PR 3/4 loss tables' "acked ⇒ durable" row, and the
  PR 11 split-journal replay-to-completion rule).
- **no-stale-verdict** — once a revocation is acknowledged and a deny
  has been observed for the revoked grant, no later probe may flip back
  to allow (a cached decision served from a fenced lineage or a dead
  vector would do exactly that).
- **split-journal-completion** — after recovery, no cross-shard write is
  left half-applied: the journal has no pending entries and every acked
  split write is visible on every shard it touched (covered jointly by
  this checker and zero-acked-write-loss's per-shard read-back).

Plus one LIVENESS bound that guards the guards: **retry amplification**
— under a browned-out shard, total retries observed against it stay
within the configured RetryBudget bound (burst + ratio × attempts),
counter-verified. Without it, the retry layers PR 1/4/11 added would
multiply a brownout into N_layers × N_retries load (the metastable-
failure shape this PR exists to prevent).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

# op-record kinds the campaign emits
KIND_CHECK = "check"
KIND_WRITE = "write"
KIND_DELETE = "delete"
KIND_LOOKUP = "lookup"
# steady-state probes issued through a live schema migration window:
# the campaign mutates NOTHING these probes depend on, so their
# verdicts carry a flap obligation the ordinary check records (whose
# tuples the campaign churns) cannot
KIND_MIGRATION_PROBE = "migration_probe"

OUTCOME_OK = "ok"
OUTCOME_SHED = "shed"
OUTCOME_ERROR = "error"

# a Retry-After outside (0, this] is unbounded for practical clients —
# the same cap the proxy stamps on its fail-closed 503s
RETRY_AFTER_BOUND_S = 60.0


@dataclass
class OpRecord:
    """One operation's observed fate, as the client tier saw it."""

    kind: str
    outcome: str  # ok | shed | error
    seq: int = 0  # campaign-global issue order (stale-verdict ordering)
    # checks/lookups
    key: str = ""  # the probe's identity (resource#perm@subject)
    verdict: Optional[bool] = None
    expected: Optional[bool] = None  # oracle expectation; None = unknown
    # writes
    rel: str = ""  # unique relationship key; acked iff outcome == ok
    shards: tuple = ()  # shard groups this write touched
    # sheds
    retry_after: Optional[float] = None
    error: str = ""


@dataclass(frozen=True)
class InvariantViolation:
    invariant: str
    detail: str

    def __str__(self) -> str:  # campaign logs read naturally
        return f"[{self.invariant}] {self.detail}"


@dataclass
class EpisodeEvidence:
    """Everything one episode hands the checkers."""

    name: str
    records: list = field(default_factory=list)  # [OpRecord]
    # rel-key -> True/False presence at read-back time (post-recovery)
    readback: dict = field(default_factory=dict)
    pending_splits: Optional[int] = None
    # retry-budget accounting for the faulted dependency (brownout)
    retries_observed: Optional[float] = None
    budget_ratio: Optional[float] = None
    budget_burst: Optional[float] = None
    attempts: Optional[int] = None
    # the journal's persisted rebalance-transition record AFTER the
    # episode's recovery completed (None when cleared — or when the
    # episode ran no rebalance and the field carries no obligation)
    rebalance_transition: Optional[dict] = None
    # live schema migration window (migration/migrator.py): the probe
    # keys the migration's diff marked AFFECTED (these may legitimately
    # change verdict across the cut; every other migration_probe key
    # must not), and the engine's migration status after the episode's
    # recovery completed (None = no migration ran)
    migration_affected: frozenset = frozenset()
    migration_status: Optional[dict] = None


def check_never_fail_open(records: list) -> list[InvariantViolation]:
    """No oracle-denied probe may be answered allow; shed outcomes must
    carry a bounded Retry-After (a shed without one strands polite
    clients in open-loop hammering — availability chaos of its own)."""
    out: list[InvariantViolation] = []
    for r in records:
        if r.kind in (KIND_CHECK, KIND_LOOKUP) and r.outcome == OUTCOME_OK \
                and r.expected is False and r.verdict is True:
            out.append(InvariantViolation(
                "never-fail-open",
                f"probe {r.key!r} (seq {r.seq}) answered ALLOW for a "
                "subject the oracle denies"))
        if r.outcome == OUTCOME_SHED:
            ra = r.retry_after
            if ra is None or not (0 < ra <= RETRY_AFTER_BOUND_S):
                out.append(InvariantViolation(
                    "never-fail-open",
                    f"shed of {r.kind} (seq {r.seq}) carried an "
                    f"unbounded Retry-After ({ra!r})"))
    return out


def check_zero_acked_write_loss(records: list, readback: dict
                                ) -> list[InvariantViolation]:
    """Every acked write's relationship is present at read-back. The
    read-back runs AFTER every crash/failover/replay of the episode, so
    a loss here is a durability-chain break, not a timing artifact.
    Unacked writes (errors, sheds, ambiguous transport deaths) carry no
    obligation either way — at-least-once is the contract."""
    out: list[InvariantViolation] = []
    for r in records:
        if r.kind != KIND_WRITE or r.outcome != OUTCOME_OK:
            continue
        present = readback.get(r.rel)
        if present is None:
            out.append(InvariantViolation(
                "zero-acked-write-loss",
                f"acked write {r.rel!r} (seq {r.seq}) was never "
                "read back — campaign bug, treated as a violation"))
        elif not present:
            out.append(InvariantViolation(
                "zero-acked-write-loss",
                f"acked write {r.rel!r} (seq {r.seq}) is MISSING after "
                "recovery"))
    return out


def check_no_stale_verdict(records: list) -> list[InvariantViolation]:
    """Per probe key, once (a) its revocation was acked and (b) a deny
    was observed after that ack, any LATER allow is a stale verdict —
    some cache tier served a decision from before the revocation."""
    out: list[InvariantViolation] = []
    by_key: dict[str, list] = {}
    revoked_at: dict[str, int] = {}
    for r in sorted(records, key=lambda r: r.seq):
        if r.kind == KIND_DELETE and r.outcome == OUTCOME_OK and r.key:
            revoked_at.setdefault(r.key, r.seq)
        if r.kind == KIND_CHECK and r.outcome == OUTCOME_OK and r.key:
            by_key.setdefault(r.key, []).append(r)
    for key, probes in by_key.items():
        rev = revoked_at.get(key)
        if rev is None:
            continue
        denied_seq = None
        for r in probes:
            if r.seq <= rev:
                continue
            if r.verdict is False and denied_seq is None:
                denied_seq = r.seq
            elif r.verdict is True and denied_seq is not None:
                out.append(InvariantViolation(
                    "no-stale-verdict",
                    f"probe {key!r} flipped back to ALLOW at seq "
                    f"{r.seq} after the revocation (seq {rev}) was "
                    f"already visible as a deny at seq {denied_seq}"))
                break
    return out


def check_split_journal_complete(pending_splits: Optional[int]
                                 ) -> list[InvariantViolation]:
    if pending_splits is None:
        return []
    if pending_splits > 0:
        return [InvariantViolation(
            "split-journal-completion",
            f"{pending_splits} cross-shard write(s) still pending after "
            "recovery — a half-applied split may be visible")]
    return []


def check_rebalance_converged(transition_doc: Optional[dict]
                              ) -> list[InvariantViolation]:
    """A crash-interrupted rebalance must land COMPLETED (every slice
    cut, map committed — recorded as the durable phase-"done" marker a
    stale-flag restart boots the committed map from) or CLEANLY
    ABORTED (record cleared with routing never having left V). Any
    other record still persisted after the episode's recovery finished
    means the placement is parked half-routed — cut slices served from
    the new map, uncut ones from the old, with nobody driving it
    forward."""
    if transition_doc is None or transition_doc.get("phase") == "done":
        return []
    slices = transition_doc.get("slices", [])
    cut = sum(1 for s in slices if s.get("state") == "cut")
    return [InvariantViolation(
        "rebalance-converged",
        f"rebalance transition (phase "
        f"{transition_doc.get('phase')!r}, {cut}/{len(slices)} slices "
        "cut) still persisted after recovery — neither completed nor "
        "cleanly aborted")]


def check_no_verdict_flap(records: list,
                          affected: frozenset = frozenset()
                          ) -> list[InvariantViolation]:
    """Through a live schema migration, a probe for a permission the
    migration's diff did NOT mark affected must answer the SAME verdict
    before, during, and after the cut — any flip means the cutover
    leaked schema-change effects outside the affected closure (a stale
    decision-cache entry surviving retire_affected, or the new graph
    disagreeing with the old on untouched reachability). Probes for
    AFFECTED keys are exempt: changing their verdict is the migration's
    entire point. Error/shed outcomes carry no obligation — a fault may
    cost availability, never verdict stability."""
    out: list[InvariantViolation] = []
    first: dict[str, "tuple[int, bool]"] = {}
    for r in sorted(records, key=lambda r: r.seq):
        if r.kind != KIND_MIGRATION_PROBE or r.outcome != OUTCOME_OK \
                or r.verdict is None or not r.key:
            continue
        if r.key in affected:
            continue
        seen = first.get(r.key)
        if seen is None:
            first[r.key] = (r.seq, r.verdict)
        elif r.verdict != seen[1]:
            out.append(InvariantViolation(
                "no-verdict-flap",
                f"unaffected probe {r.key!r} flipped "
                f"{seen[1]}->{r.verdict} at seq {r.seq} (first seen at "
                f"seq {seen[0]}) across the migration window"))
    return out


def check_migration_converged(status: Optional[dict]
                              ) -> list[InvariantViolation]:
    """A crash-interrupted schema migration must land DONE (cut
    persisted and finished) or CLEANLY ABORTED — the same all-or-
    nothing obligation the rebalance transition carries. A status still
    parked in a working phase after the episode's recovery finished
    means the engine serves with a half-applied schema change."""
    if status is None:
        return []
    phase = status.get("phase")
    if phase in ("done", "aborted"):
        return []
    return [InvariantViolation(
        "migration-converged",
        f"schema migration still in phase {phase!r} after recovery — "
        "neither completed nor cleanly aborted"
        + (f" (error: {status.get('error')})" if status.get("error")
           else ""))]


def retry_amplification_bound(ratio: float, burst: float,
                              attempts: int, slack: float = 2.0) -> float:
    """The budget's worst-case total-retry bound for ``attempts``
    logical calls: the full burst plus the per-attempt refill, with a
    small additive ``slack`` for in-flight races (a token deposited and
    withdrawn around the measurement edges)."""
    return burst + ratio * attempts + slack


def check_retry_amplification(retries_observed: Optional[float],
                              ratio: Optional[float],
                              burst: Optional[float],
                              attempts: Optional[int]
                              ) -> list[InvariantViolation]:
    if retries_observed is None or ratio is None or burst is None \
            or attempts is None:
        return []
    bound = retry_amplification_bound(ratio, burst, attempts)
    if retries_observed > bound:
        return [InvariantViolation(
            "retry-amplification",
            f"{retries_observed:.0f} retries observed at the faulted "
            f"dependency exceed the RetryBudget bound {bound:.0f} "
            f"(burst {burst:g} + {ratio:g} × {attempts} attempts)")]
    return []


def check_all(ev: EpisodeEvidence) -> list[InvariantViolation]:
    """Every checker over one episode's evidence (the campaign's
    per-episode gate)."""
    out: list[InvariantViolation] = []
    out += check_never_fail_open(ev.records)
    out += check_zero_acked_write_loss(ev.records, ev.readback)
    out += check_no_stale_verdict(ev.records)
    out += check_split_journal_complete(ev.pending_splits)
    out += check_retry_amplification(ev.retries_observed, ev.budget_ratio,
                                     ev.budget_burst, ev.attempts)
    out += check_rebalance_converged(ev.rebalance_transition)
    out += check_no_verdict_flap(ev.records, ev.migration_affected)
    out += check_migration_converged(ev.migration_status)
    return out


__all__ = [
    "EpisodeEvidence", "InvariantViolation", "OpRecord",
    "KIND_CHECK", "KIND_DELETE", "KIND_LOOKUP",
    "KIND_MIGRATION_PROBE", "KIND_WRITE",
    "OUTCOME_ERROR", "OUTCOME_OK", "OUTCOME_SHED",
    "check_all", "check_migration_converged", "check_never_fail_open",
    "check_no_stale_verdict", "check_no_verdict_flap",
    "check_rebalance_converged", "check_retry_amplification",
    "check_split_journal_complete", "check_zero_acked_write_loss",
    "retry_amplification_bound",
]
