"""The chaos campaign runner: seeded fault schedules × live topology ×
safety invariants.

One campaign = N seeds; one seed = three EPISODES against a full
topology (2 shard groups × 2-peer failover sets × the proxy-side client
stack — ShardedEngine planner → FailoverEngine → RemoteEngine, the same
four-deep stack the authz middleware consumes):

1. **baseline** — the loadgen open-loop schedule (mixed op classes:
   checks, bulk checks, scatter lookups, LookupSubjects, writes incl.
   journaled cross-shard splits, watch reads) with no faults armed —
   the control every degradation bound compares against;
2. **brownout** — a delay+drop fault schedule wire-armed on ONE shard
   group's hosts (``chaos_arm``, flag-gated server-side): the episode
   verifies fail-closed behavior under partial degradation, that total
   retries against the faulted group stay within the RetryBudget bound
   (counter-verified), and that the healthy group's goodput holds;
3. **crash** — the same load with a SIGKILL of group 0's leader
   mid-schedule, failover, restart of the victim, and split-journal
   recovery.

After every episode the invariant suite (chaos/invariants.py) runs over
the episode's op records plus a post-recovery read-back of every acked
write. ANY violation fails the campaign (exit 1 from ``main``).

Determinism: the arrival schedule and the fault schedule both derive
every decision from the seed up front (loadgen/schedule.py,
chaos/schedule.py), so one seed names one reproducible run —
``--seeds`` reports each seed's fault-schedule digest, and re-running a
seed re-arms byte-identical decision tables on every host.

``make chaos-campaign`` (CHAOS_SEEDS / CHAOS_EPISODES) runs the bounded
sweep; ``--inproc`` swaps the subprocess hosts for in-process engines
behind per-group fault sites (no crash episode — nothing to SIGKILL)
for the fast tier-1 smoke.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ..engine.engine import CheckItem
from ..engine.store import RelationshipFilter, WriteOp
from ..models.tuples import Relationship
from ..utils.failpoints import failpoints
from ..utils.metrics import metrics
from ..utils.resilience import RetryBudget
from ..admission import AdmissionRejected
from ..loadgen.driver import OpenLoopDriver
from ..loadgen.schedule import (
    OP_BULK_CHECK,
    OP_CHECK,
    OP_LIST_PREFILTER,
    OP_LOOKUP_SUBJECTS,
    OP_TABLE,
    OP_WATCH_OPEN,
    OP_WILDCARD,
    OP_WRITE,
    build_schedule,
    trace_shaped_config,
)
from .invariants import (
    EpisodeEvidence,
    InvariantViolation,
    KIND_CHECK,
    KIND_DELETE,
    KIND_LOOKUP,
    KIND_MIGRATION_PROBE,
    KIND_WRITE,
    OUTCOME_ERROR,
    OUTCOME_OK,
    OUTCOME_SHED,
    OpRecord,
    check_all,
)
from .schedule import FaultSchedule, FaultSpec, brownout_schedule

log = logging.getLogger("sdbkp.chaos")

# the schema every topology bootstraps (the test suite's 2-shard shape:
# namespaces are GLOBAL tuples that replicate, pods are namespaced)
SCHEMA_YAML = """\
schema: |-
  definition user {}

  definition namespace {
    relation creator: user
    relation viewer: user
    permission admin = creator
    permission view = viewer + creator
  }

  definition pod {
    relation namespace: namespace
    relation creator: user
    relation viewer: user
    permission edit = creator
    permission view = viewer + creator + namespace->view
  }
relationships: ""
"""

NS_COUNT = 8  # static namespaces the load spreads over
FAULT_GROUP = 1  # the browned-out group; group 0 takes the SIGKILL

# the live-migration episode's REWRITING target: the same schema with a
# caveat trait attached to pod.viewer (an allowed-subject gain on a live
# relation — the exact change class that forces dual-compile + backfill
# instead of a metadata-only flip). The affected closure is
# pod#viewer/pod#view; namespace#view and pod#edit stay outside it and
# carry the no-verdict-flap obligation through the cut.
MIGRATED_SCHEMA = """\
caveat probation(level int) {
  level < 3
}

definition user {}

definition namespace {
  relation creator: user
  relation viewer: user
  permission admin = creator
  permission view = viewer + creator
}

definition pod {
  relation namespace: namespace
  relation creator: user
  relation viewer: user | user with probation
  permission edit = creator
  permission view = viewer + creator + namespace->view
}
"""


def _migration_target_text() -> str:
    """The bootstrap path auto-appends the workflow definitions to every
    engine's schema (models/bootstrap.py); the migration target needs
    the same three or the diff would classify them as removed."""
    from ..models.bootstrap import WORKFLOW_DEFS

    return "\n".join([MIGRATED_SCHEMA]
                     + [WORKFLOW_DEFS[n]
                        for n in ("lock", "workflow", "activity")])

# episode shapes: (schedule seconds, baseline arrivals/second)
EPISODE_SHAPES = {"short": (1.2, 80.0), "standard": (4.0, 150.0)}


def rel(rt, rid, rl, st, sid) -> Relationship:
    return Relationship(rt, rid, rl, st, sid, None)


def _rel_key(r: Relationship) -> str:
    return f"{r.resource_type}:{r.resource_id}#{r.relation}" \
           f"@{r.subject_type}:{r.subject_id}"


# -- topologies ---------------------------------------------------------------


_HOST_WORKER = r"""
import os, sys
bootstrap = sys.argv[1]
peer_id, port0, port1, data_dir, repo = sys.argv[2:7]
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, repo)
import jax
jax.config.update("jax_platforms", "cpu")
from spicedb_kubeapi_proxy_tpu.engine.remote import main

print("HOST STARTING", flush=True)
sys.exit(main([
    "--bootstrap", bootstrap,
    "--peers", "127.0.0.1:%s,127.0.0.1:%s" % (port0, port1),
    "--peer-id", peer_id,
    "--bind-port", port0 if peer_id == "0" else port1,
    "--token", "chaos-tok", "--engine-insecure",
    "--data-dir", data_dir, "--wal-fsync", "always",
    # the DURABLE configuration the zero-acked-write-loss row of the
    # loss table is stated for: an ack requires the follower to hold
    # (and journal) the bytes, so no resurrection-era re-election can
    # rebase an acked write away. min-sync-replicas 0 (the availability
    # default) acks unreplicated while the peer is down — a documented
    # loss mode this campaign reproduced before pinning the floor.
    "--min-sync-replicas", "1",
    "--mirror-heartbeat-seconds", "0.3",
    "--failover-boot-grace", "30",
    "--enable-chaos-ops",
]))
"""


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class SubprocessTopology:
    """2 shard groups × 2-peer failover sets, each peer a real
    subprocess engine host with persistence, replication, and the
    flag-gated chaos plane. The planner in THIS process is the proxy
    tier under test."""

    n_groups = 2
    supports_crash = True

    def __init__(self, workdir: Optional[str] = None):
        from ..scaleout import ShardMap, SplitJournal

        self._tmp = None
        if workdir is None:
            self._tmp = tempfile.TemporaryDirectory(prefix="chaos-")
            workdir = self._tmp.name
        self.dir = workdir
        self.repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        self.script = os.path.join(workdir, "host_worker.py")
        with open(self.script, "w") as f:
            f.write(_HOST_WORKER)
        self.bootstrap = os.path.join(workdir, "bootstrap.yaml")
        with open(self.bootstrap, "w") as f:
            f.write(SCHEMA_YAML)
        # group g, peer p listens on self.ports[g][p]
        self.ports = [[_free_port(), _free_port()] for _ in range(2)]
        self.procs: dict[tuple[int, int], subprocess.Popen] = {}
        self.env = dict(os.environ)
        self.env.pop("XLA_FLAGS", None)
        self.env.pop("FAILPOINTS", None)
        for g in range(2):
            for p in range(2):
                self.procs[(g, p)] = self._boot(g, p)
        self.map = ShardMap(version=1, groups=tuple(
            tuple(("127.0.0.1", port) for port in self.ports[g])
            for g in range(2)))
        self.journal_path = os.path.join(workdir, "split-journal.sqlite")
        self._journal_cls = SplitJournal
        self.retry_budget = RetryBudget("engine-stack", ratio=0.1,
                                        burst=20.0)
        self.planner = None

    def _host_log(self, g: int, p: int) -> str:
        return os.path.join(self.dir, f"host-g{g}p{p}.log")

    def _boot(self, g: int, p: int) -> subprocess.Popen:
        # logs go to a FILE, never a pipe: failover churn logs freely
        # (reconnects, elections), and an undrained 64KiB pipe would
        # eventually block the host inside a log write — a wedged
        # topology indistinguishable from the hangs the campaign hunts
        logf = open(self._host_log(g, p), "ab")
        try:
            return subprocess.Popen(
                [sys.executable, self.script, self.bootstrap, str(p),
                 str(self.ports[g][0]), str(self.ports[g][1]),
                 os.path.join(self.dir, f"data-g{g}p{p}"), self.repo],
                stdout=logf, stderr=subprocess.STDOUT,
                env=self.env, cwd=self.repo)
        finally:
            logf.close()  # the child holds its own descriptor

    def _probe(self, port: int):
        from ..engine.remote import RemoteEngine

        return RemoteEngine("127.0.0.1", port, token="chaos-tok",
                            timeout=2.0, connect_timeout=2.0, retries=0)

    def group_leader(self, g: int) -> Optional[int]:
        """The peer index currently leading group ``g`` (None while
        electing)."""
        for p, port in enumerate(self.ports[g]):
            probe = self._probe(port)
            try:
                if probe.failover_state().get("role") == "leader":
                    return p
            except Exception:  # noqa: BLE001 - a dead peer is expected
                pass
            finally:
                probe.close()
        return None

    def wait_ready(self, budget: float = 120.0) -> None:
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            for key, proc in self.procs.items():
                if proc.poll() is not None:
                    try:
                        with open(self._host_log(*key), "rb") as f:
                            out = f.read()[-3000:].decode(
                                "utf-8", "replace")
                    except OSError:
                        out = "<no log>"
                    raise RuntimeError(
                        f"engine host {key} died at boot:\n{out}")
            if all(self.group_leader(g) is not None for g in range(2)):
                return
            time.sleep(0.3)
        raise RuntimeError("topology never became ready")

    def make_planner(self):
        from ..engine.remote import FailoverEngine
        from ..scaleout import ShardedEngine

        groups = [
            FailoverEngine(
                [("127.0.0.1", port) for port in self.ports[g]],
                token="chaos-tok", probe_timeout=2.0,
                resolve_deadline=15.0, connect_timeout=2.0, timeout=8.0,
                retries=2, retry_budget=self.retry_budget)
            for g in range(2)
        ]
        self.planner = ShardedEngine(
            self.map, groups,
            journal=self._journal_cls(self.journal_path),
            retry_budget=self.retry_budget)
        return self.planner

    # -- chaos plane ---------------------------------------------------------

    def arm(self, group: int, sched: FaultSchedule,
            budget: float = 15.0) -> dict:
        """Arm ``sched`` on EVERY host of the group and VERIFY each
        endpoint echoed the schedule's digest (byte-identical decision
        tables). A transiently unreachable peer retries within the
        budget; a persistent failure raises — an un-armed brownout
        episode would verify nothing and pass vacuously."""
        want = sched.digest()
        deadline = time.monotonic() + budget
        while True:
            got = self.planner.groups[group].chaos_arm(sched.encode())
            bad = {ep: r for ep, r in got.items()
                   if r.get("digest") != want}
            if not bad:
                return got
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"fault schedule never armed on group {group}: "
                    f"{bad}")
            time.sleep(0.5)

    def reset_faults(self) -> None:
        for g in self.planner.groups:
            g.chaos_reset()

    # -- elastic topology (grow/shrink episode) ------------------------------

    def add_group(self):
        """Boot a fresh 2-peer failover group (new ports, new data
        dirs) and return ``(endpoints, client)`` for a grow
        transition. The new hosts join ``procs``/``ports`` so the
        crash plane (kill/restart/wait) and teardown cover them."""
        from ..engine.remote import FailoverEngine

        g = len(self.ports)
        self.ports.append([_free_port(), _free_port()])
        for p in range(2):
            self.procs[(g, p)] = self._boot(g, p)
        self.wait_group_leader(g, budget=120.0)
        client = FailoverEngine(
            [("127.0.0.1", port) for port in self.ports[g]],
            token="chaos-tok", probe_timeout=2.0,
            resolve_deadline=15.0, connect_timeout=2.0, timeout=8.0,
            retries=2, retry_budget=self.retry_budget)
        return (tuple(("127.0.0.1", port)
                      for port in self.ports[g]), client)

    # -- crash/restart -------------------------------------------------------

    def kill_group_leader(self, g: int) -> tuple[int, int]:
        p = self.group_leader(g)
        if p is None:
            raise RuntimeError(f"group {g} has no leader to kill")
        proc = self.procs[(g, p)]
        proc.kill()
        proc.wait(timeout=15)
        log.info("SIGKILLed group %d leader (peer %d)", g, p)
        return g, p

    def restart(self, g: int, p: int) -> None:
        old = self.procs[(g, p)]
        if old.poll() is None:
            old.kill()
            old.wait(timeout=15)
        self.procs[(g, p)] = self._boot(g, p)

    def wait_group_leader(self, g: int, budget: float = 60.0) -> None:
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            if self.group_leader(g) is not None:
                return
            time.sleep(0.3)
        raise RuntimeError(f"group {g} never re-elected a leader")

    def faulted_dependencies(self) -> list[str]:
        return [f"engine:127.0.0.1:{port}"
                for port in self.ports[FAULT_GROUP]]

    def close(self) -> None:
        if self.planner is not None:
            self.planner.close()
        for proc in self.procs.values():
            if proc.poll() is None:
                proc.terminate()
        for proc in self.procs.values():
            try:
                proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                proc.kill()
        if self._tmp is not None:
            self._tmp.cleanup()


class _FaultableEngine:
    """In-process group engine behind NAMED per-group fault sites:
    ``engine.g<N>.dispatch`` hits BEFORE the op (delay browns it out,
    error fails it pre-application) and ``engine.g<N>.respond`` hits
    AFTER it (a drop there discards an already-applied result — the
    same caller-side ambiguity a dropped response frame produces on the
    wire). Together they model the full remote brownout schedule
    in-process."""

    def __init__(self, inner, gi: int):
        self._inner = inner
        self.site = f"engine.g{gi}.dispatch"
        self.respond_site = f"engine.g{gi}.respond"

    def __getattr__(self, name):
        val = getattr(self._inner, name)
        if name in ("check_bulk", "lookup_resources", "lookup_subjects",
                    "read_relationships", "watch_since",
                    "write_relationships", "delete_relationships"):
            def hooked(*a, _fn=val, **kw):
                failpoints.hit(self.site)
                out = _fn(*a, **kw)
                failpoints.hit(self.respond_site)
                return out

            return hooked
        return val

    @property
    def revision(self):
        return self._inner.revision

    @property
    def store(self):
        return self._inner.store


class InprocTopology:
    """2 in-process engine groups behind per-group fault sites — the
    fast smoke shape (tier-1): same schedules, same invariants, no
    subprocesses, so no crash episode."""

    n_groups = 2
    supports_crash = False

    def __init__(self, workdir: Optional[str] = None):
        from ..engine import Engine
        from ..scaleout import ShardMap

        self.engines = [Engine(bootstrap=SCHEMA_YAML) for _ in range(2)]
        self.map = ShardMap(version=1, groups=(
            (("127.0.0.1", 1),), (("127.0.0.1", 2),)))
        self.retry_budget = None
        self.planner = None

    def wait_ready(self) -> None:
        pass

    def make_planner(self):
        from ..scaleout import ShardedEngine

        self.planner = ShardedEngine(
            self.map,
            [_FaultableEngine(e, gi)
             for gi, e in enumerate(self.engines)])
        return self.planner

    # remote site -> the per-group local site _FaultableEngine hits
    _SITE_MAP = {"engine.dispatch": "dispatch", "engine.respond": "respond"}

    def arm(self, group: int, sched: FaultSchedule) -> dict:
        """Re-target EVERY spec at the group's local sites; a spec this
        topology cannot model raises instead of silently thinning the
        schedule (a delays-only 'brownout' would no longer exercise the
        error-path fail-closed handling the smoke claims to cover)."""
        specs = []
        for s in sched.specs:
            suffix = self._SITE_MAP.get(s.site)
            if suffix is None:
                raise RuntimeError(
                    f"inproc topology cannot arm site {s.site!r}")
            specs.append(FaultSpec(f"engine.g{group}.{suffix}",
                                   s.action, p=s.p, budget=s.budget))
        retargeted = FaultSchedule(sched.seed, specs)
        retargeted.arm()
        return {"armed": [s.site for s in specs],
                "digest": retargeted.digest()}

    def add_group(self):
        """A fresh in-process engine group for a grow transition; the
        placeholder endpoint only has to be unique within the map."""
        from ..engine import Engine

        gi = len(self.engines)
        e = Engine(bootstrap=SCHEMA_YAML)
        self.engines.append(e)
        return ((("127.0.0.1", gi + 1),), _FaultableEngine(e, gi))

    def reset_faults(self) -> None:
        failpoints.disable_all()

    def faulted_dependencies(self) -> list[str]:
        return []

    def close(self) -> None:
        if self.planner is not None:
            self.planner.close()


# -- the campaign -------------------------------------------------------------


@dataclass
class CampaignConfig:
    seeds: tuple = (0, 1, 2)
    episodes: str = "short"  # short | standard
    inproc: bool = False
    workdir: Optional[str] = None
    json_out: Optional[str] = None


@dataclass
class _SeedState:
    """Carried across a seed's episodes: everything acked so far is a
    durability obligation for EVERY later recovery point."""

    acked: dict = field(default_factory=dict)  # rel key -> Relationship
    seq: itertools.count = field(default_factory=itertools.count)


class Campaign:
    def __init__(self, cfg: CampaignConfig):
        self.cfg = cfg
        if cfg.episodes not in EPISODE_SHAPES:
            raise ValueError(f"unknown episode shape {cfg.episodes!r}")
        self.duration, self.rate = EPISODE_SHAPES[cfg.episodes]
        self.topology = (InprocTopology(cfg.workdir) if cfg.inproc
                         else SubprocessTopology(cfg.workdir))
        self.violations: list[InvariantViolation] = []
        self.result: dict = {"episodes": [], "seeds": {},
                             "violations": []}

    # -- op plumbing ---------------------------------------------------------

    def _ns(self, ns_key: int) -> str:
        return f"ns{ns_key % NS_COUNT}"

    def _write_acked(self, writes: list, budget: float = 45.0) -> bool:
        """Issue a write, retrying through fail-closed windows (election
        in progress, durability floor below min-sync while a follower
        rejoins) — the windows are the system refusing to lie, not an
        error. True iff acked within the budget."""
        planner = self.topology.planner
        deadline = time.monotonic() + budget
        while True:
            try:
                planner.write_relationships(list(writes))
                return True
            except Exception as e:  # noqa: BLE001 - bounded retry
                if time.monotonic() >= deadline:
                    log.warning("write never acked within %.0fs: %s",
                                budget, e)
                    return False
                time.sleep(0.4)

    def _seed_static(self) -> None:
        """The static grant set every oracle expectation derives from:
        per namespace, one owner with view on its pod via the namespace
        arrow and one direct pod viewer. ``intruder*`` subjects are
        NEVER granted anything, by any episode — an allow for one is
        fail-open, full stop."""
        writes = []
        for i in range(NS_COUNT):
            ns = f"ns{i}"
            writes.append(WriteOp("touch", rel(
                "namespace", ns, "viewer", "user", f"owner{i}")))
            writes.append(WriteOp("touch", rel(
                "pod", f"{ns}/p0", "namespace", "namespace", ns)))
            writes.append(WriteOp("touch", rel(
                "pod", f"{ns}/p0", "viewer", "user", f"direct{i}")))
        if not self._write_acked(writes):
            raise RuntimeError("static seed writes never acked")

    def _record(self, records, lock, rec: OpRecord) -> None:
        with lock:
            records.append(rec)

    def _ops(self, seed: int, episode: str, state: _SeedState,
             records: list, lock: threading.Lock) -> dict:
        """Loadgen op table: every callable records an OpRecord and
        re-raises sheds/errors so the driver's outcome accounting
        agrees with ours."""
        planner = self.topology.planner
        wseq = itertools.count()

        def classify(fn, shards_of=None):
            """``shards_of(a)`` names the shard(s) an arrival targets so
            FAILED ops still carry routing info — without it the
            healthy-shard goodput ratio would only ever see successes
            (ok == total by construction) and the <10%-degradation
            bound could never fail."""
            def run(a):
                seq = next(state.seq)
                shards = tuple(shards_of(a)) if shards_of else ()
                try:
                    fn(a, seq)
                except AdmissionRejected as e:
                    self._record(records, lock, OpRecord(
                        KIND_CHECK, OUTCOME_SHED, seq=seq,
                        shards=shards, retry_after=e.retry_after))
                    raise
                except Exception as e:  # noqa: BLE001 - accounted
                    ra = getattr(e, "retry_after", None)
                    self._record(records, lock, OpRecord(
                        KIND_CHECK, OUTCOME_ERROR, seq=seq,
                        shards=shards, retry_after=ra,
                        error=repr(e)[:200]))
                    raise
            return run

        def check_shard(a):
            ns_i = a.ns_key % NS_COUNT
            return (self.topology.map.anchor_shard("pod",
                                                   f"ns{ns_i}/p0"),)

        def probe_item(a) -> tuple[CheckItem, Optional[bool], str]:
            ns_i = a.ns_key % NS_COUNT
            ns = f"ns{ns_i}"
            if a.key % 2:
                # negative probe: intruders are never granted anything
                subject = f"intruder{a.key % 16}"
                expected = False
            else:
                subject = f"owner{ns_i}"
                expected = True
            item = CheckItem("pod", f"{ns}/p0", "view", "user", subject)
            key = f"pod:{ns}/p0#view@user:{subject}"
            return item, expected, key

        def do_check(a, seq):
            item, expected, key = probe_item(a)
            verdict = planner.check(item)
            gi = self.topology.map.anchor_shard("pod", item.resource_id)
            self._record(records, lock, OpRecord(
                KIND_CHECK, OUTCOME_OK, seq=seq, key=key,
                verdict=bool(verdict), expected=expected,
                shards=(gi,)))

        def do_bulk(a, seq):
            items, metas = [], []
            for j in range(3):
                shifted = type(a)(a.t, a.op, a.tenant, a.key + j,
                                  a.phase, a.burst, a.ns_key + j)
                item, expected, key = probe_item(shifted)
                items.append(item)
                metas.append((expected, key))
            verdicts = planner.check_bulk(items)
            for (expected, key), v in zip(metas, verdicts):
                self._record(records, lock, OpRecord(
                    KIND_CHECK, OUTCOME_OK, seq=seq, key=key,
                    verdict=bool(v), expected=expected))

        def do_lookup(a, seq):
            subject = f"intruder{a.key % 16}" if a.key % 2 \
                else f"owner{a.ns_key % NS_COUNT}"
            ids = planner.lookup_resources("pod", "view", "user",
                                           subject)
            if a.key % 2:
                self._record(records, lock, OpRecord(
                    KIND_LOOKUP, OUTCOME_OK, seq=seq,
                    key=f"pod#view@user:{subject}",
                    verdict=bool(ids), expected=False))

        def do_lookup_subjects(a, seq):
            ns = self._ns(a.ns_key)
            subs = planner.lookup_subjects("pod", f"{ns}/p0", "view",
                                           "user")
            leaked = [s for s in subs if s.startswith("intruder")]
            self._record(records, lock, OpRecord(
                KIND_LOOKUP, OUTCOME_OK, seq=seq,
                key=f"pod:{ns}/p0#view@user:*",
                verdict=bool(leaked), expected=False))

        def do_write(a, seq):
            i = next(wseq)
            ns_a = self._ns(a.ns_key)
            rels = [rel("pod", f"{ns_a}/cw-{seed}-{episode}-{i}",
                        "viewer", "user", f"w{i}")]
            if i % 5 == 4:
                # cross-shard split: a second namespaced leg in the
                # OTHER half of the namespace space (journal path when
                # the two land on different groups) — plus, every few,
                # a global tuple that replicates to every group
                ns_b = self._ns(a.ns_key + NS_COUNT // 2)
                rels.append(rel("pod", f"{ns_b}/cw-{seed}-{episode}-{i}",
                                "viewer", "user", f"w{i}"))
            if i % 11 == 10:
                rels.append(rel("namespace", f"gns-{seed}-{i}",
                                "viewer", "user", f"w{i}"))
            shards = tuple(sorted({
                self.topology.map.anchor_shard(r.resource_type,
                                               r.resource_id)
                for r in rels}))
            planner.write_relationships(
                [WriteOp("create", r) for r in rels])
            with lock:
                # one record PER relationship: the read-back is keyed by
                # rel, and every leg of a split carries the obligation
                for r in rels:
                    state.acked[_rel_key(r)] = r
                    records.append(OpRecord(
                        KIND_WRITE, OUTCOME_OK, seq=seq,
                        rel=_rel_key(r), shards=shards))

        def do_watch(a, seq):
            planner.watch_since(planner.vector)

        return {
            OP_CHECK: classify(do_check, check_shard),
            OP_WILDCARD: classify(do_check, check_shard),
            OP_TABLE: classify(do_check, check_shard),
            OP_BULK_CHECK: classify(do_bulk),
            OP_LIST_PREFILTER: classify(do_lookup),
            OP_LOOKUP_SUBJECTS: classify(do_lookup_subjects),
            OP_WRITE: classify(do_write),
            OP_WATCH_OPEN: classify(do_watch),
        }

    # -- episode machinery ---------------------------------------------------

    def _drive(self, seed: int, episode: str, state: _SeedState,
               records: list, mid_run=None) -> dict:
        lock = threading.Lock()
        cfg = trace_shaped_config(self.duration, self.rate, tenants=6,
                                  seed=seed)
        schedule = build_schedule(cfg)
        driver = OpenLoopDriver(
            self._ops(seed, episode, state, records, lock),
            max_workers=16, drain_timeout=60.0)
        killer = None
        if mid_run is not None:
            killer = threading.Timer(0.35 * self.duration, mid_run)
            killer.start()
        try:
            rep = driver.run(schedule, cfg.duration)
        finally:
            if killer is not None:
                killer.join()
        return {"scheduled": rep.scheduled_n, "fired": rep.fired_n,
                "outcomes": {k: dict(v) for k, v in
                             rep.per_class().items()}}

    def _probe_until(self, item: CheckItem, want: bool,
                     budget: float = 20.0) -> bool:
        """True iff the check settles at ``want`` within the budget
        (transport noise retries; a definitive opposite answer keeps
        retrying until the budget — replication/replay may lag)."""
        planner = self.topology.planner
        deadline = time.monotonic() + budget
        while True:
            try:
                if bool(planner.check(item)) == want:
                    return True
            except Exception:  # noqa: BLE001 - recovery window noise
                pass
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.25)

    def _drain_pending_splits(self, budget: float = 30.0) -> Optional[int]:
        """Run split-write recovery to completion (bounded): the
        invariant judges the state AFTER recovery — an ambiguous leg
        parked pending mid-episode is the journal working as specified,
        a pending entry that recovery cannot drain is the violation."""
        planner = self.topology.planner
        if planner.journal is None:
            return None
        deadline = time.monotonic() + budget
        while planner.journal.pending_count():
            try:
                planner.recover_splits()
            except Exception:  # noqa: BLE001 - a shard mid-election
                pass
            if not planner.journal.pending_count() \
                    or time.monotonic() >= deadline:
                break
            time.sleep(0.5)
        return planner.journal.pending_count()

    def _readback(self, state: _SeedState) -> dict:
        """Post-recovery presence of EVERY acked write so far (the
        zero-acked-write-loss evidence)."""
        out: dict = {}
        for key, r in state.acked.items():
            # campaign writes grant "viewer" on pods AND namespaces;
            # both types expose it through their "view" permission
            item = CheckItem(r.resource_type, r.resource_id, "view",
                             r.subject_type, r.subject_id)
            out[key] = self._probe_until(item, True, budget=25.0)
        return out

    def _revocation_probe(self, seed: int, episode: str,
                          state: _SeedState, records: list) -> None:
        """The no-stale-verdict sequence: grant → observe allow →
        revoke → observe deny → re-probe; a later allow is a stale
        verdict (invariants.check_no_stale_verdict orders by seq)."""
        planner = self.topology.planner
        ns = "ns0"
        subject = f"rev-{seed}-{episode}"
        r = rel("pod", f"{ns}/p0", "viewer", "user", subject)
        item = CheckItem("pod", f"{ns}/p0", "view", "user", subject)
        key = f"pod:{ns}/p0#view@user:{subject}"
        if not self._write_acked([WriteOp("touch", r)]):
            records.append(OpRecord(
                KIND_CHECK, OUTCOME_ERROR, seq=next(state.seq), key=key,
                error="revocation-probe grant never acked"))
            return
        if not self._probe_until(item, True):
            records.append(OpRecord(
                KIND_CHECK, OUTCOME_ERROR, seq=next(state.seq), key=key,
                error="grant never became visible"))
            return
        f = RelationshipFilter(
            resource_type="pod", resource_id=f"{ns}/p0",
            relation="viewer", subject_type="user", subject_id=subject)
        deadline = time.monotonic() + 30.0
        while True:
            try:
                planner.delete_relationships(f)
                break
            except Exception:  # noqa: BLE001 - idempotent, bounded
                if time.monotonic() >= deadline:
                    return  # revocation never acked: no obligation
                time.sleep(0.4)
        records.append(OpRecord(KIND_DELETE, OUTCOME_OK,
                                seq=next(state.seq), key=key))
        if not self._probe_until(item, False):
            # the deny never became visible within the budget
            # (replication/replay lag): the stale-verdict invariant is
            # defined as allow-AFTER-a-deny, so recording expected=False
            # allows here would misreport lag as fail-open — no deny
            # observed, no obligation this round
            return
        records.append(OpRecord(
            KIND_CHECK, OUTCOME_OK, seq=next(state.seq), key=key,
            verdict=False, expected=False))
        for _ in range(10):
            try:
                v = bool(planner.check(item))
            except Exception:  # noqa: BLE001 - noise tolerated
                continue
            records.append(OpRecord(
                KIND_CHECK, OUTCOME_OK, seq=next(state.seq), key=key,
                verdict=v, expected=False))

    def _healthy_goodput(self, records: list) -> tuple[int, int]:
        """(ok, total) over single-shard probes routed to HEALTHY
        groups (everything but FAULT_GROUP)."""
        ok = total = 0
        for r in records:
            if r.kind != KIND_CHECK or not r.shards:
                continue
            if FAULT_GROUP in r.shards:
                continue
            total += 1
            if r.outcome == OUTCOME_OK:
                ok += 1
        return ok, total

    def _retries_delta(self, before: dict) -> float:
        total = 0.0
        for dep in self.topology.faulted_dependencies():
            total += metrics.counter("proxy_dependency_retries_total",
                                     dependency=dep).value \
                - before.get(dep, 0.0)
        return total

    def _retries_snapshot(self) -> dict:
        return {dep: metrics.counter("proxy_dependency_retries_total",
                                     dependency=dep).value
                for dep in self.topology.faulted_dependencies()}

    def _finish_episode(self, ev: EpisodeEvidence, extra: dict) -> None:
        got = check_all(ev)
        self.violations.extend(got)
        self.result["episodes"].append({
            "episode": ev.name,
            "records": len(ev.records),
            "violations": [str(v) for v in got],
            **extra,
        })
        log.info("episode %s: %d records, %d violations", ev.name,
                 len(ev.records), len(got))

    # -- episodes ------------------------------------------------------------

    def run_seed(self, seed: int) -> None:
        topo = self.topology
        state = _SeedState()
        self._seed_static()
        sched = brownout_schedule(seed)
        self.result["seeds"][str(seed)] = {
            "fault_digest": sched.digest(),
        }

        # episode 1: baseline (no faults) — the control
        records: list = []
        stats = self._drive(seed, "baseline", state, records)
        self._revocation_probe(seed, "baseline", state, records)
        ev = EpisodeEvidence(
            name=f"seed{seed}/baseline", records=records,
            readback=self._readback(state),
            pending_splits=self._drain_pending_splits())
        base_ok, base_total = self._healthy_goodput(records)
        self._finish_episode(ev, {"load": stats})

        # episode 2: single-shard brownout, wire-armed, budget-verified
        armed = topo.arm(FAULT_GROUP, sched)
        budget = topo.retry_budget
        retries_before = self._retries_snapshot()
        # attempts are counted at the BUDGET (one deposit per transport
        # call, incl. one per scatter leg) — the exact denominator of
        # the bound, not the logical-op count, which undercounts
        # scatter deposits and would flag a correctly-behaving budget
        attempts_before = budget.attempts if budget is not None else 0
        records = []
        stats = self._drive(seed, "brownout", state, records)
        topo.reset_faults()
        pending = self._drain_pending_splits()
        ev = EpisodeEvidence(
            name=f"seed{seed}/brownout", records=records,
            readback=self._readback(state),
            pending_splits=pending,
            retries_observed=self._retries_delta(retries_before),
            budget_ratio=(budget.ratio if budget is not None else None),
            budget_burst=(budget.burst if budget is not None else None),
            attempts=(budget.attempts - attempts_before
                      if budget is not None else None))
        ok, total = self._healthy_goodput(records)
        goodput_ratio = None
        if base_total >= 20 and total >= 20 and base_ok:
            goodput_ratio = (ok / total) / (base_ok / base_total)
            if goodput_ratio < 0.9:
                self.violations.append(InvariantViolation(
                    "brownout-goodput",
                    f"healthy-shard goodput fell to {goodput_ratio:.2f}x"
                    " of the fault-free baseline (bound: 0.90)"))
        self._finish_episode(ev, {
            "load": stats, "armed": armed,
            "retries_at_faulted_group": ev.retries_observed,
            "healthy_goodput_ratio": goodput_ratio,
        })

        # episode 3: SIGKILL group 0's leader mid-schedule, failover,
        # restart, split-journal recovery
        if not topo.supports_crash:
            # the elastic + migration episodes still run (episodes 4-5
            # below) — their in-process shapes just have no
            # SIGKILL-mid-drain / SIGKILL-mid-backfill legs
            self.elastic_episode(seed, state)
            self.migration_episode(seed, state)
            return
        victim: list = []

        def kill():
            try:
                victim.append(topo.kill_group_leader(0))
            except Exception as e:  # noqa: BLE001 - surfaced below
                log.warning("mid-run kill failed: %s", e)

        records = []
        stats = self._drive(seed, "crash", state, records, mid_run=kill)
        topo.wait_group_leader(0)
        if victim:
            topo.restart(*victim[0])
        pending = self._drain_pending_splits()
        self._revocation_probe(seed, "crash", state, records)
        ev = EpisodeEvidence(
            name=f"seed{seed}/crash", records=records,
            readback=self._readback(state),
            pending_splits=pending)
        self._finish_episode(ev, {
            "load": stats,
            "killed": (f"group{victim[0][0]}/peer{victim[0][1]}"
                       if victim else None),
        })

        # episode 4: elastic grow -> shrink -> grow under load, SIGKILL
        # of the retiring group's leader mid-drain (BEFORE the
        # migration episode: a freshly booted group bootstraps the
        # original schema, so growing after a live migration would
        # split the fleet's schema)
        self.elastic_episode(seed, state)

        # episode 5: live schema migration under load, SIGKILL
        # mid-backfill, re-begin after the boot-abort
        self.migration_episode(seed, state)

    # -- elastic scale-out episode -------------------------------------------

    def elastic_episode(self, seed: int, state: _SeedState) -> None:
        """Grow -> shrink -> grow, each map transition begun MID-load
        through the same coordinator the autoscaler's apply mode
        drives. On crash-capable topologies the shrink is paced and
        the RETIRING group's leader takes a SIGKILL mid-drain: the
        drain must fail over to the group's surviving peer and
        converge. Every write acked anywhere in the cycle is a
        read-back obligation at the end, no probe may flip open, and a
        transition that never converges (including its GC) is itself a
        violation (``rebalance-converged``)."""
        from ..scaleout import ShardMap
        from ..scaleout.rebalance import shrink_map

        topo = self.topology
        planner = topo.planner
        crash = topo.supports_crash
        records: list = []
        transitions: list = []
        victim: list = []

        def _converged(want_version: int, want_groups: int,
                       budget: float = 120.0) -> bool:
            # converged = transition cleared, target map live, target
            # group count routing, AND no archived transition owing GC
            # (a shrink begun over pending GC would be refused, so an
            # unconverged GC stalls the elastic cycle for real)
            deadline = time.monotonic() + budget
            while time.monotonic() < deadline:
                if planner.rebalance_status() is None \
                        and planner.map.version >= want_version \
                        and len(planner.groups) == want_groups \
                        and all(t.gc_complete
                                for t in planner._archived_transitions):
                    return True
                time.sleep(0.1)
            return False

        def run_phase(name, mid_run, want_version, want_groups):
            stats = self._drive(seed, f"elastic-{name}", state, records,
                                mid_run=mid_run)
            ok = _converged(want_version, want_groups)
            if not ok:
                self.violations.append(InvariantViolation(
                    "rebalance-converged",
                    f"elastic {name} transition never converged: "
                    f"status={planner.rebalance_status()}, map "
                    f"v{planner.map.version} (want >= {want_version}), "
                    f"{len(planner.groups)} groups "
                    f"(want {want_groups})"))
            transitions.append({"phase": name, "converged": ok,
                                "map_version": planner.map.version,
                                "groups": len(planner.groups),
                                "load": stats})
            return ok

        # phase 1: grow — append a freshly booted group mid-load
        eps, client = topo.add_group()
        base = planner.map
        gi = len(base.groups)
        grown = ShardMap(version=base.version + 1,
                         groups=tuple(base.groups) + (tuple(eps),),
                         virtual_nodes=base.virtual_nodes)

        def begin_grow():
            try:
                planner.begin_rebalance(grown, new_clients={gi: client})
            except Exception as e:  # noqa: BLE001 - judged by converge
                log.warning("elastic grow begin failed: %s", e)

        ok = run_phase("grow", begin_grow, grown.version, gi + 1)

        # phase 2: shrink the group straight back out; crash shapes
        # pace the drain and SIGKILL the retiring group's leader in
        # the middle of it
        if ok:
            shrunk = shrink_map(planner.map)
            retiring = len(planner.groups) - 1

            def begin_shrink():
                try:
                    planner.begin_rebalance(
                        shrunk,
                        **({"batch_rows": 4, "pace_seconds": 0.15}
                           if crash else {}))
                except Exception as e:  # noqa: BLE001 - judged below
                    log.warning("elastic shrink begin failed: %s", e)
                    return
                if crash:
                    time.sleep(0.4)  # let the drain actually start
                    try:
                        victim.append(topo.kill_group_leader(retiring))
                    except Exception as e:  # noqa: BLE001 - surfaced
                        log.warning("mid-drain kill failed: %s", e)

            ok = run_phase("shrink", begin_shrink, shrunk.version,
                           retiring)

        # phase 3: grow again — the cycle must be repeatable (stale
        # archived owner filters from the first cycle are the
        # regression this phase pins at the campaign level)
        if ok:
            eps2, client2 = topo.add_group()
            base2 = planner.map
            gi2 = len(base2.groups)
            regrown = ShardMap(version=base2.version + 1,
                               groups=tuple(base2.groups)
                               + (tuple(eps2),),
                               virtual_nodes=base2.virtual_nodes)

            def begin_regrow():
                try:
                    planner.begin_rebalance(regrown,
                                            new_clients={gi2: client2})
                except Exception as e:  # noqa: BLE001 - judged above
                    log.warning("elastic re-grow begin failed: %s", e)

            run_phase("regrow", begin_regrow, regrown.version, gi2 + 1)

        self._revocation_probe(seed, "elastic", state, records)
        ev = EpisodeEvidence(
            name=f"seed{seed}/elastic", records=records,
            readback=self._readback(state),
            pending_splits=self._drain_pending_splits())
        self._finish_episode(ev, {
            "transitions": transitions,
            "killed": (f"group{victim[0][0]}/peer{victim[0][1]}"
                       if victim else None),
        })

    # -- live schema migration episode ---------------------------------------

    # (probe key, CheckItem, inside the migration's affected closure?)
    _MIGRATION_PROBES = (
        ("namespace:ns0#view@user:owner0",
         ("namespace", "ns0", "view", "user", "owner0"), False),
        ("namespace:ns1#view@user:intruder-mig",
         ("namespace", "ns1", "view", "user", "intruder-mig"), False),
        ("pod:ns2/p0#edit@user:direct2",
         ("pod", "ns2/p0", "edit", "user", "direct2"), False),
        ("pod:ns0/p0#view@user:direct0",
         ("pod", "ns0/p0", "view", "user", "direct0"), True),
    )

    def _migration_terminal(self, budget: float = 60.0) -> Optional[dict]:
        """Poll the planner's aggregate status to a terminal phase."""
        planner = self.topology.planner
        deadline = time.monotonic() + budget
        while True:
            st = planner.migration_status()
            if st is None or st.get("phase") in ("done", "failed",
                                                 "aborted"):
                return st
            if time.monotonic() >= deadline:
                return st
            time.sleep(0.1)

    def migration_episode(self, seed: int, state: _SeedState) -> None:
        """Episode 4: a REWRITING schema migration (caveat attached to
        the live pod.viewer relation) begun mid-load, with steady
        verdict probes running before/during/after the coordinated cut.
        On crash-capable topologies, group 0's leader takes a SIGKILL
        mid-backfill; the interrupted attempt must resolve by the crash
        matrix (boot-abort, no cut persisted) and a re-begin must then
        complete — with zero verdict flaps outside the affected closure
        across the WHOLE window, both attempts included."""
        topo = self.topology
        planner = topo.planner
        records: list = []
        lock = threading.Lock()
        stop = threading.Event()
        target = _migration_target_text()

        def probe_loop():
            while not stop.is_set():
                for key, args, _aff in self._MIGRATION_PROBES:
                    try:
                        v = bool(planner.check(CheckItem(*args)))
                        self._record(records, lock, OpRecord(
                            KIND_MIGRATION_PROBE, OUTCOME_OK,
                            seq=next(state.seq), key=key, verdict=v))
                    except Exception as e:  # noqa: BLE001 - availability
                        self._record(records, lock, OpRecord(
                            KIND_MIGRATION_PROBE, OUTCOME_ERROR,
                            seq=next(state.seq), key=key, error=str(e)))
                time.sleep(0.03)

        crash = topo.supports_crash
        victim: list = []

        def begin():
            try:
                # a paced backfill on crash topologies keeps the window
                # open long enough for the SIGKILL to land MID-backfill
                planner.begin_schema_migration(
                    target,
                    **({"batch": 4, "backfill_pause": 0.15}
                       if crash else {}))
            except Exception as e:  # noqa: BLE001 - judged below
                log.warning("migration begin failed: %s", e)
            if crash:
                try:
                    victim.append(topo.kill_group_leader(0))
                except Exception as e:  # noqa: BLE001 - surfaced below
                    log.warning("mid-backfill kill failed: %s", e)

        prober = threading.Thread(target=probe_loop, daemon=True)
        prober.start()
        try:
            stats = self._drive(seed, "migration", state, records,
                                mid_run=begin)
            st = self._migration_terminal()
            attempts = 1
            if crash:
                topo.wait_group_leader(0)
                if victim:
                    topo.restart(*victim[0])
                st = self._migration_terminal()
                if st is None or st.get("phase") != "done":
                    # the interrupted attempt boot-aborted by the crash
                    # matrix; the operator's re-begin must complete
                    attempts += 1
                    planner.begin_schema_migration(target, wait=True,
                                                   timeout=90.0)
                    st = self._migration_terminal()
        finally:
            stop.set()
            prober.join(timeout=10.0)
        pending = self._drain_pending_splits()
        affected = frozenset(k for k, _a, aff in self._MIGRATION_PROBES
                             if aff)
        ev = EpisodeEvidence(
            name=f"seed{seed}/migration", records=records,
            readback=self._readback(state),
            pending_splits=pending,
            migration_affected=affected,
            migration_status=st)
        self._finish_episode(ev, {
            "load": stats,
            "migration_phase": (st or {}).get("phase"),
            "migration_attempts": attempts,
            "killed": (f"group{victim[0][0]}/peer{victim[0][1]}"
                       if victim else None),
        })

    def run(self) -> dict:
        t0 = time.monotonic()
        try:
            self.topology.wait_ready()
            self.topology.make_planner()
            for seed in self.cfg.seeds:
                log.info("=== seed %d ===", seed)
                self.run_seed(seed)
        finally:
            self.topology.close()
        self.result["violations"] = [str(v) for v in self.violations]
        self.result["ok"] = not self.violations
        self.result["wall_s"] = round(time.monotonic() - t0, 2)
        self.result["seeds_run"] = list(self.cfg.seeds)
        self.result["episode_shape"] = self.cfg.episodes
        return self.result


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="chaos-campaign",
        description="seeded chaos campaign over the full proxy topology")
    ap.add_argument("--seeds", type=int, default=3,
                    help="how many seeds to sweep (0..N-1)")
    ap.add_argument("--episodes", default="short",
                    choices=sorted(EPISODE_SHAPES),
                    help="episode shape (schedule length × rate)")
    ap.add_argument("--inproc", action="store_true",
                    help="in-process topology (fast smoke: no "
                         "subprocesses, no crash episode)")
    ap.add_argument("--json", dest="json_out",
                    help="write the full result JSON here")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    cfg = CampaignConfig(seeds=tuple(range(args.seeds)),
                         episodes=args.episodes, inproc=args.inproc,
                         json_out=args.json_out)
    result = Campaign(cfg).run()
    if cfg.json_out:
        with open(cfg.json_out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
    print(json.dumps({k: v for k, v in result.items()
                      if k != "episodes"}, indent=2, sort_keys=True))
    if not result["ok"]:
        print("CHAOS CAMPAIGN FAILED: invariant violations:",
              file=sys.stderr)
        for v in result["violations"]:
            print(f"  {v}", file=sys.stderr)
        return 1
    print(f"chaos campaign OK: {len(result['episodes'])} episodes, "
          f"0 violations, {result['wall_s']}s", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
