"""Seeded, deterministic fault schedules over the named fault space.

A :class:`FaultSchedule` is to faults what loadgen/schedule.py is to
arrivals: every random decision is PRE-DRAWN from one seeded derivation
at construction time, so identical seeds produce identical fault
histories — across processes (the wire-armed engine hosts rebuild the
same decision tables from the same ``(seed, site, p)``) and across
re-runs (the campaign reproducibility pin). Nothing draws randomness at
hit time.

The fault space is the registry of named sites the production code
already carries (utils/failpoints.py): client-side transport sites
(``upstream.connect``/``upstream.read``, ``engine.connect``/
``engine.read``), server-side dispatch/response sites
(``engine.dispatch``, ``engine.respond``), and the mirror-stream sites
(``mirror.partition``, ``mirror.heartbeat``). Each :class:`FaultSpec`
names a site, an action — ``error`` | ``drop`` | ``delay:<ms>`` |
``crash`` — a per-hit probability, and a trigger budget.

Schedules are armable locally (:meth:`FaultSchedule.arm`) or over the
wire on subprocess engine hosts via the flag-gated ``chaos_arm`` op
(engine/remote.py, ``--enable-chaos-ops``): the host reconstructs the
schedule from its wire form and arms byte-identical decision tables —
:meth:`digest` fingerprints them, so the campaign can assert that every
process in a topology is executing the same fault plan.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass
from typing import Optional

from ..utils.failpoints import (
    ACTION_CRASH,
    ACTION_DELAY,
    ACTION_DROP,
    ACTION_ERROR,
    ACTIONS,
    DECISION_HORIZON,
    FaultRule,
    decision_sequence,
    failpoints,
)


class ChaosScheduleError(ValueError):
    pass


def parse_action(spec: str) -> tuple[str, float]:
    """``"error" | "drop" | "crash" | "delay:<ms>"`` -> (action,
    delay_seconds)."""
    if spec.startswith("delay:"):
        try:
            ms = float(spec.split(":", 1)[1])
        except ValueError:
            raise ChaosScheduleError(
                f"malformed delay action {spec!r} (want delay:<ms>)"
            ) from None
        if ms < 0:
            raise ChaosScheduleError("delay must be >= 0 ms")
        return ACTION_DELAY, ms / 1000.0
    if spec not in ACTIONS or spec == ACTION_DELAY:
        raise ChaosScheduleError(
            f"unknown fault action {spec!r} "
            f"(want error | drop | delay:<ms> | crash)")
    return spec, 0.0


def format_action(action: str, delay_s: float) -> str:
    if action == ACTION_DELAY:
        return f"delay:{delay_s * 1000.0:g}"
    return action


@dataclass(frozen=True)
class FaultSpec:
    """One site's plan: fire ``action`` with probability ``p`` on each
    hit, at most ``budget`` times total."""

    site: str
    action: str = "error"  # error | drop | delay:<ms> | crash
    p: float = 1.0
    budget: int = DECISION_HORIZON

    def __post_init__(self):
        act, delay_s = parse_action(self.action)  # validates
        if not 0.0 < self.p <= 1.0:
            raise ChaosScheduleError("fault probability must be in (0, 1]")
        if self.budget < 1:
            raise ChaosScheduleError("fault budget must be >= 1")
        object.__setattr__(self, "_act", act)
        object.__setattr__(self, "_delay_s", delay_s)

    @property
    def kind(self) -> str:
        return self._act  # type: ignore[attr-defined]

    @property
    def delay_s(self) -> float:
        return self._delay_s  # type: ignore[attr-defined]


class FaultSchedule:
    """A seeded plan over one or more sites (see module docstring)."""

    def __init__(self, seed: int, specs: list[FaultSpec]):
        self.seed = int(seed)
        self.specs = [s if isinstance(s, FaultSpec) else FaultSpec(**s)
                      for s in specs]
        seen = set()
        for s in self.specs:
            if s.site in seen:
                raise ChaosScheduleError(
                    f"site {s.site!r} appears twice in one schedule")
            seen.add(s.site)

    # -- determinism ---------------------------------------------------------

    def decisions(self, spec: FaultSpec) -> Optional[list[bool]]:
        """The pre-drawn decision table a host will arm for ``spec``
        (None for p=1 always-fire rules) — exposed so tests can pin that
        re-deriving from the same seed is byte-identical."""
        if spec.p >= 1.0:
            return None
        return decision_sequence(self.seed, spec.site, spec.p)

    def digest(self) -> str:
        """SHA-256 over the canonical JSON of every site's action,
        budget, and FULL decision table: two schedules with equal
        digests will perform identical fault decisions at every hit
        index, in any process."""
        doc = {
            "seed": self.seed,
            "sites": [
                {"site": s.site,
                 "action": format_action(s.kind, s.delay_s),
                 "p": round(s.p, 6), "budget": s.budget,
                 "decisions": self.decisions(s)}
                for s in sorted(self.specs, key=lambda s: s.site)
            ],
        }
        return hashlib.sha256(
            json.dumps(doc, separators=(",", ":"),
                       sort_keys=True).encode()).hexdigest()

    # -- arming --------------------------------------------------------------

    def rules(self) -> list[FaultRule]:
        return [
            FaultRule(s.site, s.kind, budget=s.budget, p=s.p,
                      seed=self.seed, delay_s=s.delay_s)
            for s in self.specs
        ]

    def arm(self, registry=failpoints) -> None:
        """Install every site's rule into ``registry`` (the process-
        global failpoint registry by default — the same one the
        production fault sites consult)."""
        for r in self.rules():
            registry.arm(r)

    def disarm(self, registry=failpoints) -> None:
        for s in self.specs:
            registry.disable(s.site)

    # -- wire form -----------------------------------------------------------

    def encode(self) -> dict:
        """JSON-able wire form for the ``chaos_arm`` op. Decision tables
        do NOT ride the wire: the receiving host re-derives them from
        ``(seed, site, p)`` — same derivation, same bytes — which keeps
        the frame tiny and makes tampering with the tables impossible
        without changing the digest."""
        return {
            "seed": self.seed,
            "faults": [
                {"site": s.site,
                 "action": format_action(s.kind, s.delay_s),
                 "p": s.p, "budget": s.budget}
                for s in self.specs
            ],
        }

    @classmethod
    def parse(cls, doc: dict) -> "FaultSchedule":
        if not isinstance(doc, dict):
            raise ChaosScheduleError("fault schedule must be an object")
        try:
            seed = int(doc["seed"])
            faults = doc["faults"]
        except (KeyError, TypeError, ValueError):
            raise ChaosScheduleError(
                "fault schedule needs {seed, faults: [...]}") from None
        specs = []
        for f in faults:
            try:
                specs.append(FaultSpec(
                    site=str(f["site"]),
                    action=str(f.get("action", ACTION_ERROR)),
                    p=float(f.get("p", 1.0)),
                    budget=int(f.get("budget", DECISION_HORIZON))))
            except (KeyError, TypeError, ValueError) as e:
                raise ChaosScheduleError(
                    f"malformed fault spec {f!r}: {e}") from None
        return cls(seed, specs)


def brownout_schedule(seed: int, delay_ms: float = 40.0,
                      delay_p: float = 0.5, error_p: float = 0.15,
                      budget: int = DECISION_HORIZON) -> FaultSchedule:
    """The stock single-shard brownout: dispatches slowed with
    probability ``delay_p`` plus a smaller rate of responses dropped on
    the floor — the mixed degradation mode that exercises retry
    amplification (delays time out, drops look like transport deaths,
    both trigger client retries at every layer)."""
    return FaultSchedule(seed, [
        FaultSpec("engine.dispatch", f"delay:{delay_ms:g}", p=delay_p,
                  budget=budget),
        FaultSpec("engine.respond", "drop", p=error_p, budget=budget),
    ])


__all__ = [
    "ACTION_CRASH", "ACTION_DELAY", "ACTION_DROP", "ACTION_ERROR",
    "ChaosScheduleError", "FaultSchedule", "FaultSpec",
    "brownout_schedule", "format_action", "parse_action",
]
