"""Open-loop, trace-shaped load generation (ROADMAP item 5).

Every earlier bench phase is closed-loop: each worker waits for its
response before issuing the next request, so the offered load collapses
to whatever the server can absorb and the tail you measure is the tail
of a system that is never actually behind. Production traffic is
open-loop — watch storms, fleet-wide ``kubectl get`` waves, operator
reconcile loops fire on their own schedule whether or not the proxy is
keeping up — and that is the regime where p99.9 and goodput-vs-offered-
load curves mean something.

- :mod:`.schedule` — arrival-time schedules: Poisson baseline modulated
  by named burst phases, Zipf-skewed tenants, one seeded RNG (identical
  seed ⇒ identical schedule, byte for byte).
- :mod:`.driver` — the open-loop driver: fires each arrival at its
  scheduled time and NEVER waits for a response before the next one;
  sheds/errors/lateness are recorded, not absorbed.
- :mod:`.sweep` — offered-load sweeps producing goodput and latency
  curves (p50/p99/p99.9 from windowed histogram snapshots), a knee
  estimate, burst-window tails, and per-stage tail attribution from the
  trace ring's always-kept slow/shed traces.
"""

from .driver import DriverReport, OpenLoopDriver, OpOutcome
from .schedule import (
    Arrival,
    BurstPhase,
    ScheduleConfig,
    build_schedule,
    trace_shaped_config,
)
from .sweep import SweepResult, knee_estimate, run_sweep

__all__ = [
    "Arrival",
    "BurstPhase",
    "DriverReport",
    "OpenLoopDriver",
    "OpOutcome",
    "ScheduleConfig",
    "SweepResult",
    "build_schedule",
    "knee_estimate",
    "run_sweep",
    "trace_shaped_config",
]
