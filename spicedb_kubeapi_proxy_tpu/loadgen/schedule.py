"""Arrival-schedule generation: Poisson baseline + trace-shaped bursts.

A schedule is a flat, time-ordered list of :class:`Arrival` records
computed ENTIRELY up front from one seeded RNG: the driver replays it,
it never draws randomness at fire time, so identical seeds produce
identical schedules (the reproducibility pin in the bench acceptance)
and two sweeps at different concurrency compare the same traffic.

The arrival process is piecewise-Poisson: a baseline rate, overridden
inside each :class:`BurstPhase` window by ``rate_multiplier`` and an
op-mix override. The three stock phases model the production shapes the
ROADMAP names:

- ``watch-storm`` — a controller restart: thousands of watch streams
  (re)open at once while normal traffic continues;
- ``get-wave`` — a fleet-wide ``kubectl get`` sweep: list-prefilter and
  Table-response traffic spikes several-fold;
- ``reconcile`` — an operator reconcile loop: interleaved checks,
  LookupSubjects sweeps, and write churn.

Tenant identity is Zipf-skewed (``p(rank r) ∝ 1/(r+1)^s``): a few noisy
tenants dominate, the long tail trickles — the distribution per-tenant
fair queueing exists to survive.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

# the op classes the mixed workload drives; driver op tables are keyed
# by these names
OP_CHECK = "check"
OP_BULK_CHECK = "bulk-check"
OP_LIST_PREFILTER = "list-prefilter"
OP_TABLE = "table-filter"
OP_LOOKUP_SUBJECTS = "lookup-subjects"
OP_WILDCARD = "wildcard-check"
OP_WRITE = "write"
OP_WATCH_OPEN = "watch-open"

DEFAULT_MIX = {
    OP_CHECK: 0.40,
    OP_BULK_CHECK: 0.12,
    OP_LIST_PREFILTER: 0.14,
    OP_TABLE: 0.08,
    OP_LOOKUP_SUBJECTS: 0.06,
    OP_WILDCARD: 0.08,
    OP_WRITE: 0.07,
    OP_WATCH_OPEN: 0.05,
}


@dataclass(frozen=True)
class Arrival:
    """One scheduled request: fire at ``t`` seconds after schedule
    start, no matter what happened to every arrival before it."""

    t: float
    op: str
    tenant: str
    key: int  # op-local variety selector (which resource/subject)
    phase: str  # "baseline" or the burst phase's name
    burst: bool
    # shard-aware namespace selector: each tenant owns a SMALL cluster
    # of namespaces (``ns_per_tenant`` of them), so the Zipf tenant skew
    # translates into namespace — and therefore SHARD — skew: the
    # macrobench's hot tenant hammers a hot shard instead of uniformly
    # spreading its storm across the keyspace. Derived from the tenant
    # rank and ``key`` (no extra RNG draws: identical seeds still
    # produce identical schedules).
    ns_key: int = 0


@dataclass(frozen=True)
class BurstPhase:
    """A named window where the arrival rate and mix change."""

    name: str
    start: float  # seconds from schedule start
    duration: float
    rate_multiplier: float
    mix: Optional[dict] = None  # None = keep the baseline mix


@dataclass
class ScheduleConfig:
    duration: float  # seconds
    rate: float  # baseline arrivals/second
    tenants: int = 8
    zipf_s: float = 1.1  # tenant-skew exponent (higher = more skew)
    seed: int = 0
    mix: dict = field(default_factory=lambda: dict(DEFAULT_MIX))
    bursts: tuple = ()
    key_space: int = 1 << 16  # op-local key variety
    # namespaces per tenant: the tenant -> namespace mapping honored by
    # the Zipf skew (Arrival.ns_key). Small on purpose — a hot tenant
    # should concentrate on a few namespaces (one or two shards), which
    # is the hot-shard shape per-shard admission exists to survive
    ns_per_tenant: int = 4


def trace_shaped_config(duration: float, rate: float, tenants: int = 8,
                        seed: int = 0,
                        burst_multiplier: float = 4.0) -> ScheduleConfig:
    """The stock trace shape: baseline Poisson with the three production
    burst phases at fixed fractions of the run (watch storm at 15%,
    get wave at 45%, reconcile loop at 70%)."""
    storm_mix = dict(DEFAULT_MIX)
    storm_mix[OP_WATCH_OPEN] = 0.45
    storm_mix[OP_CHECK] = 0.30
    wave_mix = dict(DEFAULT_MIX)
    wave_mix[OP_LIST_PREFILTER] = 0.40
    wave_mix[OP_TABLE] = 0.25
    # write churn is the reconcile loop's defining trait (operators
    # re-assert ownership tuples on every pass): the write share leads
    # the mix, so this burst is the phase that finds write-path
    # regressions — with the delta overlay each write is an O(write)
    # append; without it every write forces a graph re-encode before the
    # next fully-consistent read can dispatch (ISSUE 8)
    reconcile_mix = dict(DEFAULT_MIX)
    reconcile_mix[OP_CHECK] = 0.25
    reconcile_mix[OP_LOOKUP_SUBJECTS] = 0.12
    reconcile_mix[OP_WRITE] = 0.35
    return ScheduleConfig(
        duration=duration, rate=rate, tenants=tenants, seed=seed,
        bursts=(
            BurstPhase("watch-storm", 0.15 * duration, 0.12 * duration,
                       burst_multiplier, storm_mix),
            BurstPhase("get-wave", 0.45 * duration, 0.10 * duration,
                       burst_multiplier, wave_mix),
            BurstPhase("reconcile", 0.70 * duration, 0.15 * duration,
                       0.6 * burst_multiplier, reconcile_mix),
        ))


def _zipf_weights(n: int, s: float) -> np.ndarray:
    w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), s)
    return w / w.sum()


def _segments(cfg: ScheduleConfig):
    """[(t0, t1, rate, mix)] covering [0, duration) — bursts override
    the baseline inside their window; overlapping bursts are applied in
    declaration order (the later one wins from its own start)."""
    cuts = {0.0, cfg.duration}
    for b in cfg.bursts:
        cuts.add(max(0.0, min(b.start, cfg.duration)))
        cuts.add(max(0.0, min(b.start + b.duration, cfg.duration)))
    edges = sorted(cuts)
    segs = []
    for t0, t1 in zip(edges, edges[1:]):
        if t1 <= t0:
            continue
        rate, mix, phase, burst = cfg.rate, cfg.mix, "baseline", False
        mid = (t0 + t1) / 2
        for b in cfg.bursts:
            if b.start <= mid < b.start + b.duration:
                rate = cfg.rate * b.rate_multiplier
                mix = b.mix or cfg.mix
                phase, burst = b.name, True
        segs.append((t0, t1, rate, mix, phase, burst))
    return segs


def build_schedule(cfg: ScheduleConfig) -> list[Arrival]:
    """Materialize the whole arrival list. Deterministic in ``seed``:
    every random draw comes from one generator consumed in a fixed
    order (per-segment counts, then vectorized gap/op/tenant/key draws
    per segment)."""
    rng = np.random.default_rng(cfg.seed)
    tenant_p = _zipf_weights(cfg.tenants, cfg.zipf_s)
    tenant_names = [f"tenant{i}" for i in range(cfg.tenants)]
    out: list[Arrival] = []
    for t0, t1, rate, mix, phase, burst in _segments(cfg):
        span = t1 - t0
        n = rng.poisson(rate * span)
        if n <= 0:
            continue
        # conditioned on the count, Poisson arrival times are iid
        # uniform over the segment — one sort instead of a gap walk
        ts = np.sort(rng.uniform(t0, t1, size=n))
        ops = list(mix.keys())
        p = np.asarray(list(mix.values()), dtype=np.float64)
        p = p / p.sum()
        op_idx = rng.choice(len(ops), size=n, p=p)
        tn_idx = rng.choice(cfg.tenants, size=n, p=tenant_p)
        keys = rng.integers(0, cfg.key_space, size=n)
        npt = max(1, cfg.ns_per_tenant)
        out.extend(
            Arrival(float(ts[i]), ops[int(op_idx[i])],
                    tenant_names[int(tn_idx[i])], int(keys[i]),
                    phase, burst,
                    int(tn_idx[i]) * npt + int(keys[i]) % npt)
            for i in range(n))
    out.sort(key=lambda a: a.t)
    return out


def burst_windows(cfg: ScheduleConfig) -> list[tuple[str, float, float]]:
    """[(name, start, end)] of the config's burst phases, clamped to the
    schedule span — the sweep uses these to window burst-tail stats."""
    return [(b.name, max(0.0, min(b.start, cfg.duration)),
             max(0.0, min(b.start + b.duration, cfg.duration)))
            for b in cfg.bursts]
