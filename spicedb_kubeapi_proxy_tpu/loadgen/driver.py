"""The open-loop workload driver.

The dispatcher walks the precomputed schedule and fires each arrival at
its scheduled time into a worker pool — it NEVER waits for a response
before the next arrival, and the pool's submission queue is unbounded,
so a server that falls behind sees the backlog a real fleet would
produce instead of a politely self-throttling client. Consequences, by
design:

- offered load is a property of the SCHEDULE, not the server: shedding,
  slow responses, and errors change outcomes, never the arrival times
  (the "never closes the loop" acceptance pin);
- latency is measured from ``max(scheduled arrival, actual submit)``:
  worker-pool backlog counts against the server exactly the way
  coordinated-omission-free load generators (wrk2 et al.) count it,
  while GENERATOR drift (the dispatcher thread losing the GIL to busy
  workers — a CPython artifact, not server queueing) does not; drift is
  reported separately as the ``late`` count so a run whose generator
  could not keep its own schedule says so;
- a shed (``AdmissionRejected``) is an accounted outcome, not an error:
  the curves need goodput AND shed rate per offered-load point.

Per-op latencies land in ``loadgen_op_seconds{op=...}`` histograms (the
sweep reads p50/p99/p99.9 out of windowed snapshot deltas) and in raw
per-arrival records (burst windows are sliced from these, since a burst
is a time window within one run, finer than a histogram window).
"""

from __future__ import annotations

import concurrent.futures
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..utils.metrics import metrics
from .schedule import Arrival

OUTCOME_OK = "ok"
OUTCOME_SHED = "shed"
OUTCOME_ERROR = "error"

# an arrival submitted more than this far behind its scheduled time is
# "late": the DISPATCHER (not the server) failed to keep the schedule,
# and the run's offered-load claim must say so
LATE_SUBMIT_S = 0.010


@dataclass(frozen=True)
class OpOutcome:
    """One fired arrival's fate."""

    arrival: Arrival
    outcome: str  # ok | shed | error
    latency_s: float  # completion - max(scheduled arrival, submit)
    exec_s: float  # completion - execution start (op service time)


@dataclass
class DriverReport:
    scheduled_n: int = 0
    fired_n: int = 0
    late_n: int = 0
    abandoned_n: int = 0  # still running when the drain deadline hit
    duration_s: float = 0.0  # schedule span (per config, not wall)
    wall_s: float = 0.0  # actual wall time incl. drain
    start_epoch: float = 0.0  # epoch of schedule t=0 (trace correlation)
    records: list = field(default_factory=list)  # [OpOutcome]
    hist_before: dict = field(default_factory=dict)  # op -> snapshot
    hist_after: dict = field(default_factory=dict)
    error_samples: list = field(default_factory=list)

    @property
    def offered_rps(self) -> float:
        return self.fired_n / self.duration_s if self.duration_s else 0.0

    def per_class(self) -> dict:
        out: dict = {}
        for r in self.records:
            c = out.setdefault(r.arrival.op, {"n": 0, "ok": 0, "shed": 0,
                                              "error": 0})
            c["n"] += 1
            c[r.outcome] += 1
        return out

    def latencies(self, op: Optional[str] = None,
                  phase: Optional[str] = None,
                  outcome: str = OUTCOME_OK) -> list[float]:
        return [r.latency_s for r in self.records
                if (op is None or r.arrival.op == op)
                and (phase is None or r.arrival.phase == phase)
                and r.outcome == outcome]


class OpenLoopDriver:
    """Fires a schedule into op callables without ever closing the loop.

    ``ops`` maps op-class name -> ``callable(arrival)``; an op raising
    ``AdmissionRejected`` records a shed, any other exception an error.
    ``slo_s`` (op -> seconds) marks traces over-SLO when ``trace_ops``
    is on, so tail sampling keeps exactly the slow/shed evidence the
    sweep's attribution step reads back."""

    def __init__(self, ops: dict[str, Callable[[Arrival], None]],
                 max_workers: int = 32,
                 slo_s: Optional[dict] = None,
                 trace_ops: bool = False,
                 drain_timeout: float = 30.0,
                 trace_attrs: Optional[dict] = None):
        self.ops = dict(ops)
        self.max_workers = int(max_workers)
        self.slo_s = dict(slo_s or {})
        self.trace_ops = trace_ops
        self.drain_timeout = drain_timeout
        # extra attrs stamped on every macro_op root span — the sweep
        # tags each point so attribution can tell one run's traces from
        # another's in the shared ring
        self.trace_attrs = dict(trace_attrs or {})
        self._hists = {
            op: metrics.histogram("loadgen_op_seconds", op=op)
            for op in self.ops
        }

    def run(self, schedule: list[Arrival], duration: float,
            time_scale: float = 1.0) -> DriverReport:
        """Replay ``schedule`` (arrival times multiplied by
        ``time_scale``), wait up to ``drain_timeout`` for stragglers,
        and return the report. ``duration`` is the schedule's nominal
        span — the denominator of every rate this report makes."""
        import sys

        from ..admission import AdmissionRejected
        from ..obs.trace import tracer

        rep = DriverReport(scheduled_n=len(schedule),
                           duration_s=duration * time_scale)
        rep.hist_before = {op: h.snapshot()
                           for op, h in self._hists.items()}
        lock = threading.Lock()
        sealed = threading.Event()  # set at the drain deadline
        pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.max_workers,
            thread_name_prefix="loadgen")
        # tighten the GIL switch interval for the run: with a pool of
        # busy workers, the default 5ms quantum can starve the
        # dispatcher thread for tens of ms and wreck schedule fidelity
        prev_si = sys.getswitchinterval()
        sys.setswitchinterval(0.001)
        t0 = time.perf_counter()
        rep.start_epoch = time.time()
        futs = []

        def fire(a: Arrival, target: float):
            t_exec = time.perf_counter()
            outcome = OUTCOME_OK
            err: Optional[BaseException] = None
            # sched = schedule-relative arrival time: burst windows are
            # defined in SCHEDULE time, and a backlogged op executes
            # long after its arrival — attribution must window on when
            # the op was OFFERED, not when a free worker got to it
            span_cm = (tracer.start("macro_op", op=a.op, tenant=a.tenant,
                                    phase=a.phase, sched=round(a.t, 6),
                                    **self.trace_attrs)
                       if self.trace_ops else _NULL_CM)
            try:
                with span_cm as root:
                    if root is not None:
                        # the open-loop backlog (scheduled arrival ->
                        # execution start) is a tail stage of its own:
                        # spans can't time the past, so it rides as an
                        # attr and attribution folds it in as the
                        # "driver_backlog" stage
                        root.set("backlog_us",
                                 max(0, int((t_exec - target) * 1e6)))
                    try:
                        self.ops[a.op](a)
                    except AdmissionRejected:
                        outcome = OUTCOME_SHED
                        tracer.flag("shed")
                    finally:
                        end = time.perf_counter()
                        slo = self.slo_s.get(a.op)
                        if slo is not None and end - target > slo \
                                and outcome == OUTCOME_OK:
                            # over-SLO traces must survive tail sampling:
                            # they are the burst attribution evidence
                            tracer.flag("slow_slo")
            except BaseException as e:  # noqa: BLE001 - account, continue
                outcome = OUTCOME_ERROR
                err = e
            end = time.perf_counter()
            if sealed.is_set():
                # the report was finalized at the drain deadline: a
                # straggler completing now must not observe into the
                # NEXT run's histogram window or mutate a report the
                # sweep is already reading
                metrics.counter("loadgen_ops_total", op=a.op,
                                outcome="abandoned").inc()
                return
            lat = end - target
            if outcome == OUTCOME_OK:
                # completions only: the latency curve and the burst
                # tails must measure the same quantity — a microsecond
                # fast-fail shed would otherwise drag the per-class
                # percentiles DOWN exactly where the curve is supposed
                # to show degradation
                self._hists[a.op].observe(lat)
            metrics.counter("loadgen_ops_total", op=a.op,
                            outcome=outcome).inc()
            with lock:
                rep.records.append(OpOutcome(a, outcome, lat,
                                             end - t_exec))
                if err is not None and len(rep.error_samples) < 8:
                    rep.error_samples.append(f"{a.op}: {err!r:.200}")

        try:
            for a in schedule:
                target = t0 + a.t * time_scale
                now = time.perf_counter()
                if target > now:
                    time.sleep(target - now)
                    now = time.perf_counter()
                if now - target > LATE_SUBMIT_S:
                    rep.late_n += 1
                rep.fired_n += 1
                # latency basis: the later of schedule and submit —
                # pool backlog is the server's problem, dispatcher
                # drift is ours (counted in late_n, not in latency)
                futs.append(pool.submit(fire, a, max(target, now)))

            done, not_done = concurrent.futures.wait(
                futs, timeout=self.drain_timeout)
            rep.abandoned_n = len(not_done)
            sealed.set()
            pool.shutdown(wait=not not_done, cancel_futures=True)
        finally:
            sealed.set()
            sys.setswitchinterval(prev_si)
        rep.hist_after = {op: h.snapshot()
                          for op, h in self._hists.items()}
        rep.wall_s = time.perf_counter() - t0
        return rep


class _NullCM:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False


_NULL_CM = _NullCM()
