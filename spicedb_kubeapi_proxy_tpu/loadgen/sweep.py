"""Offered-load sweeps: goodput/latency curves, knee, burst tails.

One sweep point = one open-loop run of the trace-shaped schedule at a
multiple of the base rate. Per point, the curve records offered vs
completed vs within-SLO goodput plus p50/p99/p99.9 per op class — the
percentiles come from ``loadgen_op_seconds`` histogram snapshot DELTAS
(utils/metrics.snapshot_delta_quantile), the same windowed machinery the
bench stage breakdowns use, so a sweep can run against a shared live
registry without resetting anyone's metrics.

On top of the curve:

- :func:`knee_estimate` — the offered load where goodput stops tracking
  offered load (the capacity number every subsequent engine-scaling PR
  is judged against);
- burst windows — p99/p99.9 per op class measured over each burst
  phase's time window only (raw per-arrival records: a burst is finer
  than a histogram window), answering "what does a watch storm do to
  the p99.9 of everyone else";
- per-stage tail attribution — the slowest burst window's kept traces
  (tail sampling keeps slow/shed traces unconditionally) aggregated by
  span name into "where did the tail spend its time".
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from ..utils.metrics import snapshot_delta_quantile
from .driver import OUTCOME_OK, DriverReport, OpenLoopDriver
from .schedule import ScheduleConfig, build_schedule, burst_windows

# goodput tracks offered load until it doesn't: the knee is where the
# delivered fraction first drops below this
KNEE_GOOD_FRACTION = 0.85


@dataclass
class SweepPoint:
    multiplier: float
    offered_rps: float
    fired_n: int
    completed_n: int
    good_n: int  # completed within the op's SLO
    shed_n: int
    error_n: int
    late_n: int
    classes: dict = field(default_factory=dict)  # op -> quantiles/ms
    report: Optional[DriverReport] = None

    @property
    def completed_rps(self) -> float:
        d = self.report.duration_s if self.report else 0.0
        return self.completed_n / d if d else 0.0

    @property
    def goodput_rps(self) -> float:
        d = self.report.duration_s if self.report else 0.0
        return self.good_n / d if d else 0.0

    def to_dict(self) -> dict:
        return {
            "multiplier": self.multiplier,
            "offered_rps": round(self.offered_rps, 1),
            "completed_rps": round(self.completed_rps, 1),
            "goodput_rps": round(self.goodput_rps, 1),
            "shed": self.shed_n,
            "errors": self.error_n,
            "late": self.late_n,
            "classes": self.classes,
        }


@dataclass
class SweepResult:
    points: list  # [SweepPoint]
    knee_rps: Optional[float]
    knee_saturated: bool  # False = knee never reached (lower bound)
    bursts: dict = field(default_factory=dict)
    tail_attribution: dict = field(default_factory=dict)
    slo_attainment: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "curve": [p.to_dict() for p in self.points],
            "knee_rps": (None if self.knee_rps is None
                         else round(self.knee_rps, 1)),
            "knee_saturated": self.knee_saturated,
            "bursts": self.bursts,
            "tail_attribution": self.tail_attribution,
            "slo_attainment": self.slo_attainment,
        }


def _quantiles_ms(rep: DriverReport, op: str) -> dict:
    """p50/p99/p99.9 for one op class over this run's histogram window
    (snapshot deltas; None keys omitted — an op the mix never drew has
    no percentiles, not zero ones)."""
    b, a = rep.hist_before.get(op), rep.hist_after.get(op)
    out = {}
    for label, q in (("p50_ms", 0.5), ("p99_ms", 0.99),
                     ("p999_ms", 0.999)):
        v = snapshot_delta_quantile(b, a, q)
        if v is not None:
            out[label] = round(v * 1e3, 3)
    return out


def knee_estimate(points: list) -> tuple[Optional[float], bool]:
    """(knee offered-load rps, saturated?) from the curve: the first
    point whose goodput/offered drops below :data:`KNEE_GOOD_FRACTION`,
    linearly interpolated from the last healthy point. When every point
    is healthy the knee was never reached — the largest offered load is
    returned as a LOWER BOUND with ``saturated=False``."""
    healthy_frac = []
    for p in points:
        if p.offered_rps <= 0:
            continue
        healthy_frac.append((p.offered_rps,
                             p.goodput_rps / p.offered_rps))
    if not healthy_frac:
        return None, False
    healthy_frac.sort()
    prev = None
    for off, frac in healthy_frac:
        if frac < KNEE_GOOD_FRACTION:
            if prev is None:
                return off, True
            poff, pfrac = prev
            # interpolate the crossing between the two points
            t = (pfrac - KNEE_GOOD_FRACTION) / max(1e-9, pfrac - frac)
            return poff + t * (off - poff), True
        prev = (off, frac)
    return healthy_frac[-1][0], False


def _burst_stats(rep: DriverReport, cfg: ScheduleConfig) -> dict:
    """Per burst phase: p50/p99/p99.9 per op class over the window's
    completed arrivals (raw records — exact, not bucketized), plus
    shed/error counts and the window's epoch bounds (trace
    correlation)."""
    out = {}
    for name, w0, w1 in burst_windows(cfg):
        in_window = [r for r in rep.records
                     if w0 <= r.arrival.t < w1]
        by_op: dict = {}
        shed = err = 0
        for r in in_window:
            if r.outcome == OUTCOME_OK:
                by_op.setdefault(r.arrival.op, []).append(r.latency_s)
            elif r.outcome == "shed":
                shed += 1
            else:
                err += 1
        classes = {}
        for op, lats in sorted(by_op.items()):
            arr = np.asarray(lats)
            classes[op] = {
                "n": len(lats),
                "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 3),
                "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 3),
                "p999_ms": round(
                    float(np.percentile(arr, 99.9)) * 1e3, 3),
            }
        out[name] = {
            "n": len(in_window), "shed": shed, "errors": err,
            "window_epoch": [rep.start_epoch + w0, rep.start_epoch + w1],
            "window_rel": [w0, w1],
            "classes": classes,
        }
    return out


def _worst_burst(bursts: dict) -> Optional[str]:
    """The burst phase with the largest completed-op p99.9 across its
    op classes. A window whose arrivals were ALL shed/errored has no
    completions to rank by — and is the worst case by definition, so it
    outranks every completed window by its rejection count."""
    worst, worst_key = None, None
    for name, b in bursts.items():
        starved = b["n"] > 0 and not b["classes"]
        p999 = max((st["p999_ms"] for st in b["classes"].values()),
                   default=-1.0)
        key = (1, b["shed"] + b["errors"]) if starved else (0, p999)
        if worst_key is None or key > worst_key:
            worst, worst_key = name, key
    return worst


def tail_attribution(window_rel: list, limit: int = 1024,
                     point: Optional[float] = None) -> dict:
    """Aggregate the trace ring's kept traces whose arrival was
    SCHEDULED inside the window (the driver stamps the root ``macro_op``
    span with its schedule-relative ``sched`` attr — execution time is
    useless here, a backlogged op runs long after its burst) into
    per-stage totals. ``point`` restricts to traces stamped with that
    sweep point's ``point`` attr — every point replays the same seeded
    schedule, so without it a healthy 0.5x run's traces would fall
    inside the 3.5x run's burst windows and dilute the overload
    evidence. Tail sampling keeps slow/shed/error traces
    unconditionally, so what's in the ring for a burst window IS the
    tail evidence: the share of stage time answers "the p99.9 lives in
    which stage"."""
    from ..obs.trace import tracer

    t0, t1 = window_rel
    stages: dict = {}
    n = 0
    for t in tracer.recent(limit):
        root = next((s for s in t["spans"] if s["name"] == "macro_op"),
                    None)
        if root is None:
            continue
        if point is not None and root["attrs"].get("point") != point:
            continue
        sched = root["attrs"].get("sched")
        if sched is None or not (t0 <= sched < t1):
            continue
        if not (t["flags"].get("slow_slo") or t["flags"].get("shed")
                or t["flags"].get("error")):
            continue
        n += 1
        # the open-loop backlog (arrival -> execution) rides as a root
        # attr — a span can't time the past — and is folded in as a
        # first-class stage: under overload it IS the tail
        stages["driver_backlog"] = stages.get("driver_backlog", 0) \
            + int(root["attrs"].get("backlog_us", 0))
        for s in t["spans"]:
            if s["name"] == "macro_op":
                continue  # the root envelope, not a stage
            stages[s["name"]] = stages.get(s["name"], 0) \
                + s["duration_us"]
    total = sum(stages.values())
    return {
        "traces": n,
        "stages_us": dict(sorted(stages.items(),
                                 key=lambda kv: -kv[1])),
        "stage_share": {k: round(v / total, 3)
                        for k, v in sorted(stages.items(),
                                           key=lambda kv: -kv[1])}
        if total else {},
    }


def run_sweep(make_config: Callable[[float], ScheduleConfig],
              ops: dict, multipliers, slo_s: dict,
              max_workers: int = 32,
              trace_ops: bool = True,
              drain_timeout: float = 30.0,
              on_point: Optional[Callable] = None) -> SweepResult:
    """Run one open-loop point per multiplier and assemble the curves.

    ``make_config(multiplier)`` returns that point's schedule config
    (same seed across points ⇒ the same trace shape, scaled); burst and
    attribution stats come from the HIGHEST multiplier's run — the tail
    under the worst offered load is the one the capacity claims are
    judged on."""
    points: list[SweepPoint] = []
    last_cfg = None
    for m in sorted(multipliers):
        cfg = make_config(m)
        last_cfg = cfg
        schedule = build_schedule(cfg)
        driver = OpenLoopDriver(ops, max_workers=max_workers,
                                slo_s=slo_s, trace_ops=trace_ops,
                                drain_timeout=drain_timeout,
                                trace_attrs={"point": m})
        rep = driver.run(schedule, duration=cfg.duration)
        good = shed = err = comp = 0
        for r in rep.records:
            if r.outcome == OUTCOME_OK:
                comp += 1
                slo = slo_s.get(r.arrival.op)
                if slo is None or r.latency_s <= slo:
                    good += 1
            elif r.outcome == "shed":
                shed += 1
            else:
                err += 1
        pt = SweepPoint(
            multiplier=m, offered_rps=rep.offered_rps,
            fired_n=rep.fired_n, completed_n=comp, good_n=good,
            shed_n=shed, error_n=err, late_n=rep.late_n,
            classes={op: q for op in sorted(driver.ops)
                     if (q := _quantiles_ms(rep, op))},
            report=rep)
        points.append(pt)
        if on_point is not None:
            on_point(pt)
    knee, saturated = knee_estimate(points)
    result = SweepResult(points=points, knee_rps=knee,
                         knee_saturated=saturated)
    if points and last_cfg is not None:
        top = points[-1]
        result.bursts = _burst_stats(top.report, last_cfg)
        worst = _worst_burst(result.bursts)
        if worst is not None:
            result.tail_attribution = {
                "burst": worst,
                **tail_attribution(
                    result.bursts[worst]["window_rel"],
                    point=top.multiplier),
            }
        # end-of-sweep SLO attainment per op class at the top point
        att = {}
        per: dict = {}
        for r in top.report.records:
            c = per.setdefault(r.arrival.op, [0, 0])
            c[0] += 1
            if r.outcome == OUTCOME_OK:
                slo = slo_s.get(r.arrival.op)
                if slo is None or r.latency_s <= slo:
                    c[1] += 1
        for op, (n, g) in sorted(per.items()):
            att[op] = round(g / n, 4) if n else None
        result.slo_attainment = att
    return result
