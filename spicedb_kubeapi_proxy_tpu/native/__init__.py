"""ctypes loader for the native graph-builder core (graphcore.cpp).

The shared library is built on first use with the system toolchain and
cached next to the source. Every entry point degrades to a numpy fallback
when the toolchain or library is unavailable, and ``SDBKP_NATIVE=0``
disables the native path outright — the numpy and native implementations
are behaviorally identical (tests assert parity).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

log = logging.getLogger("sdbkp.native")

_SRC = os.path.join(os.path.dirname(__file__), "graphcore.cpp")
_LIB = os.path.join(os.path.dirname(__file__), "libgraphcore.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    # build to a private temp path and publish atomically: a killed or
    # concurrent compile must never leave a truncated .so that poisons the
    # mtime-based cache for every later process
    tmp = f"{_LIB}.tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-pthread",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.warning("native build failed (%s); using numpy fallbacks", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if os.environ.get("SDBKP_NATIVE", "1") == "0":
            _load_failed = True
            return None
        if not os.path.exists(_LIB) or (
            os.path.exists(_SRC)
            and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
        ):
            if not _build():
                _load_failed = True
                return None
        try:
            lib = _bind(ctypes.CDLL(_LIB))
        except OSError as e:
            log.warning("native load failed (%s); using numpy fallbacks", e)
            _load_failed = True
            return None
        except AttributeError:
            # a cached .so from an older source revision can be missing
            # newer symbols even when mtimes look fresh (archive/rsync -a
            # deploys preserve old source mtimes): rebuild once, then
            # degrade to numpy as documented instead of crashing callers
            log.warning("cached native library is stale; rebuilding")
            if not _build():
                _load_failed = True
                return None
            try:
                lib = _bind(ctypes.CDLL(_LIB))
            except (OSError, AttributeError) as e:
                log.warning("native reload failed (%s); using numpy "
                            "fallbacks", e)
                _load_failed = True
                return None
        _lib = lib
        return _lib


# bumped together with graphcore_abi_version() in graphcore.cpp on ANY
# exported-signature change; _bind refuses a mismatching cached .so (the
# rebuild path then fires) — binding by symbol NAME alone would let a
# stale library misread argument slots silently
_ABI_VERSION = 4


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    """Declare every entry point's signature; raises AttributeError when
    the library predates a symbol or its ABI version differs."""
    lib.graphcore_abi_version.restype = ctypes.c_int64
    lib.graphcore_abi_version.argtypes = []
    got = lib.graphcore_abi_version()
    if got != _ABI_VERSION:
        raise AttributeError(
            f"graphcore ABI {got} != expected {_ABI_VERSION}")
    lib.unique_inverse_fixed.restype = ctypes.c_int64
    lib.unique_inverse_fixed.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.sort_perm_i64.restype = None
    lib.sort_perm_i64.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.index_build_u64.restype = None
    lib.index_build_u64.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.json_list_spans.restype = ctypes.c_int64
    lib.json_list_spans.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
    ]
    lib.proto_list_spans.restype = ctypes.c_int64
    lib.proto_list_spans.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ]
    lib.proto_table_spans.restype = ctypes.c_int64
    lib.proto_table_spans.argtypes = lib.proto_list_spans.argtypes
    return lib


def available() -> bool:
    return _load() is not None


def unique_inverse(arr: np.ndarray):
    """Hash-based ``np.unique(arr, return_inverse=True)`` over a bytes ('S')
    column, except uniques come back in FIRST-OCCURRENCE order (callers never
    depend on ordering). Returns (uniq_rows int64[k], inv int32[n]) or None
    when the native path does not apply."""
    lib = _load()
    if lib is None or arr.dtype.kind != "S" or arr.ndim != 1:
        return None
    width = arr.dtype.itemsize
    n = len(arr)
    if width == 0 or n == 0:
        return None
    data = np.ascontiguousarray(arr)
    inv = np.empty(n, dtype=np.int32)
    uniq_rows = np.empty(n, dtype=np.int64)
    k = lib.unique_inverse_fixed(
        data.ctypes.data_as(ctypes.c_char_p), width, n,
        inv.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        uniq_rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return uniq_rows[:k], inv


def index_build(rt, rid, rl, st, sid, srl):
    """Row-key index build for the relationship store: hashes the six
    int32 key columns (same mix as store._hash_key_cols) and returns
    (sorted_hashes uint64[n], order int64[n]) via a multithreaded radix
    sort. None when the native path does not apply."""
    lib = _load()
    if lib is None:
        return None
    cols = [np.ascontiguousarray(c, dtype=np.int32)
            for c in (rt, rid, rl, st, sid, srl)]
    n = len(cols[0])
    hashes = np.empty(n, dtype=np.uint64)
    order = np.empty(n, dtype=np.int64)
    p32 = ctypes.POINTER(ctypes.c_int32)
    lib.index_build_u64(
        *(c.ctypes.data_as(p32) for c in cols), n,
        hashes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        order.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return hashes, order


def json_list_spans(body: bytes, items_key: bytes = b"items",
                    nested: bool = False):
    """One-pass scan of a kube List response body (graphcore.cpp
    json_list_spans): returns ``(kind, arr_span, item_spans, keys)`` —
    kind as bytes (b"" when absent), spans as int64 arrays of byte
    offsets into ``body`` (``arr_span[0] < 0`` when ``items_key`` is
    absent), and ``keys`` as one packed bytes buffer of per-item records
    ``[esc '0'|'1'] ns_raw 0x1f name_raw 0x1e`` (raw = undecoded string
    content; JSON forbids unescaped control bytes, so the separators
    cannot collide) — or None when the native path does not apply or the
    scanner bailed (caller falls back to json.loads; the scanner is
    strictly conservative). ``nested`` reads each item's metadata from
    ``item["object"]`` instead of the item itself (Table rows)."""
    lib = _load()
    if lib is None or not isinstance(body, bytes) or not body:
        return None
    # every object item contains at least one '{': a cheap upper bound
    max_items = body.count(b"{") + 1
    kind_span = np.empty(2, dtype=np.int64)
    arr_span = np.empty(2, dtype=np.int64)
    item_spans = np.empty(2 * max_items, dtype=np.int64)
    key_buf = ctypes.create_string_buffer(len(body) + 3 * max_items + 16)
    key_len = ctypes.c_int64(0)
    p64 = ctypes.POINTER(ctypes.c_int64)
    count = lib.json_list_spans(
        body, len(body), items_key,
        kind_span.ctypes.data_as(p64), arr_span.ctypes.data_as(p64),
        item_spans.ctypes.data_as(p64), key_buf,
        ctypes.byref(key_len), 1 if nested else 0, max_items)
    if count < 0:
        return None
    kind = body[kind_span[0]:kind_span[1]] if kind_span[0] >= 0 else b""
    return (kind, arr_span, item_spans[:2 * count].reshape(-1, 2),
            ctypes.string_at(key_buf, key_len.value))


def proto_list_spans(raw: bytes):
    """One-pass scan of a kube-protobuf *List MESSAGE (the Unknown
    envelope's raw field): returns ``(item_spans, keys)`` — full-chunk
    spans (tag included) of every repeated ``items`` element, and the
    same packed key-record buffer the JSON scanner emits
    (``'0' ns 0x1f name 0x1e``; first-occurrence field semantics like
    kubeproto._field) — or None when the native path does not apply or
    the scanner bailed (truncated wire data, control bytes or invalid
    utf-8 in a name: the Python walker keeps authority)."""
    return _proto_spans(raw, "proto_list_spans")


def proto_table_spans(raw: bytes):
    """Like :func:`proto_list_spans` but for a meta.k8s.io Table MESSAGE:
    spans of repeated ``rows`` (field 3), keys from each row's
    ``object`` RawExtension (nested magic-prefixed Unknown or bare
    PartialObjectMetadata — kubeproto.table_row_meta semantics). Bails
    when any row has no keyable object or an empty name (the Python
    walker raises ProtoError there and keeps authority)."""
    return _proto_spans(raw, "proto_table_spans")


def _proto_spans(raw: bytes, fn_name: str):
    lib = _load()
    if lib is None or not isinstance(raw, bytes) or not raw:
        return None
    # start with a realistic bound (items are tens of bytes) and grow on
    # the scanner's overflow code — a degenerate body of 2-byte items
    # would otherwise force a huge upfront allocation
    max_items = len(raw) // 64 + 1024
    p64 = ctypes.POINTER(ctypes.c_int64)
    fn = getattr(lib, fn_name)
    while True:
        item_spans = np.empty(2 * max_items, dtype=np.int64)
        key_buf = ctypes.create_string_buffer(
            len(raw) + 3 * max_items + 16)
        key_len = ctypes.c_int64(0)
        count = fn(
            raw, len(raw), item_spans.ctypes.data_as(p64), key_buf,
            ctypes.byref(key_len), max_items)
        if count == -2 and max_items < len(raw) // 2 + 2:
            max_items = min(max_items * 4, len(raw) // 2 + 2)
            continue
        if count < 0:
            return None
        return (item_spans[:2 * count].reshape(-1, 2),
                ctypes.string_at(key_buf, key_len.value))


def sort_perm(keys: np.ndarray) -> Optional[np.ndarray]:
    """Stable ascending argsort of non-negative int64 keys (LSD radix).
    Returns None when the native path does not apply."""
    lib = _load()
    if lib is None:
        return None
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    if keys.ndim != 1 or (len(keys) and keys.min() < 0):
        return None
    perm = np.empty(len(keys), dtype=np.int64)
    lib.sort_perm_i64(
        keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(keys),
        perm.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return perm
