// Host-side graph-builder core for the TPU engine.
//
// The reference is pure Go and delegates graph traversal to SpiceDB
// (SURVEY.md §2.5: no native components exist upstream); this library is the
// NEW native tier the rebuild mandates: the host-side hot path that turns
// relationship columns into device-ready edge tensors. Two operations
// dominate snapshot refresh at the 10M-relationship scale (BASELINE.md):
//
//   1. bulk string interning (unique + inverse over id columns)
//   2. the stable sort of edges by destination slot
//
// Both are pure functions over flat buffers so the Python side (ctypes, see
// __init__.py) keeps ownership of all state and falls back to numpy when the
// library is unavailable.
//
// Build: g++ -O3 -std=c++17 -fPIC -shared graphcore.cpp -o libgraphcore.so

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// FNV-1a over a fixed-width field (NUL padding participates on both sides of
// any comparison, so padded equality is exact equality).
static inline uint64_t hash_bytes(const char* p, int64_t len) {
  uint64_t h = 1469598103934665603ull;
  for (int64_t i = 0; i < len; ++i) {
    h ^= static_cast<unsigned char>(p[i]);
    h *= 1099511628211ull;
  }
  return h;
}

struct Slot {
  int64_t row;   // first-occurrence row index, -1 = empty
  uint64_t hash;
};

}  // namespace

extern "C" {

// Hash-based unique+inverse over a fixed-width string column (numpy 'S'
// layout: n rows of `width` bytes). Writes the inverse (id per row, dense in
// first-occurrence order) to inv_out[n] and first-occurrence row indices to
// uniq_rows_out (capacity n). Returns the unique count.
int64_t unique_inverse_fixed(const char* data, int64_t width, int64_t n,
                             int32_t* inv_out, int64_t* uniq_rows_out) {
  if (n <= 0) return 0;
  // open addressing, power-of-two capacity >= 2n
  uint64_t cap = 16;
  while (cap < static_cast<uint64_t>(n) * 2) cap <<= 1;
  std::vector<Slot> table(cap, Slot{-1, 0});
  const uint64_t mask = cap - 1;
  int64_t n_uniq = 0;
  for (int64_t i = 0; i < n; ++i) {
    const char* s = data + i * width;
    const uint64_t h = hash_bytes(s, width);
    uint64_t j = h & mask;
    for (;;) {
      Slot& slot = table[j];
      if (slot.row < 0) {
        slot.row = i;
        slot.hash = h;
        uniq_rows_out[n_uniq] = i;
        inv_out[i] = static_cast<int32_t>(n_uniq);
        ++n_uniq;
        break;
      }
      if (slot.hash == h &&
          std::memcmp(data + slot.row * width, s, width) == 0) {
        inv_out[i] = inv_out[slot.row];
        break;
      }
      j = (j + 1) & mask;
    }
  }
  return n_uniq;
}

// Stable ascending sort permutation of non-negative int64 keys (LSD radix,
// 16-bit digits). out_perm[n] receives row indices; equal keys keep input
// order — compile_graph relies on this to keep residual edges dst-sorted.
void sort_perm_i64(const int64_t* keys, int64_t n, int64_t* out_perm) {
  if (n <= 0) return;
  int64_t max_key = 0;
  for (int64_t i = 0; i < n; ++i) {
    out_perm[i] = i;
    if (keys[i] > max_key) max_key = keys[i];
  }
  std::vector<int64_t> tmp(n);
  int64_t* src = out_perm;
  int64_t* dst = tmp.data();
  for (int shift = 0; shift < 64 && (max_key >> shift) != 0; shift += 16) {
    int64_t counts[65536] = {0};
    for (int64_t i = 0; i < n; ++i)
      ++counts[(keys[src[i]] >> shift) & 0xffff];
    int64_t total = 0;
    for (int b = 0; b < 65536; ++b) {
      int64_t c = counts[b];
      counts[b] = total;
      total += c;
    }
    for (int64_t i = 0; i < n; ++i)
      dst[counts[(keys[src[i]] >> shift) & 0xffff]++] = src[i];
    std::swap(src, dst);
  }
  if (src != out_perm) std::memcpy(out_perm, src, n * sizeof(int64_t));
}

}  // extern "C"
