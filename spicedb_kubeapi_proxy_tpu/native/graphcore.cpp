// Host-side graph-builder core for the TPU engine.
//
// The reference is pure Go and delegates graph traversal to SpiceDB
// (SURVEY.md §2.5: no native components exist upstream); this library is the
// NEW native tier the rebuild mandates: the host-side hot path that turns
// relationship columns into device-ready edge tensors. Two operations
// dominate snapshot refresh at the 10M-relationship scale (BASELINE.md):
//
//   1. bulk string interning (unique + inverse over id columns)
//   2. the stable sort of edges by destination slot
//
// Both are pure functions over flat buffers so the Python side (ctypes, see
// __init__.py) keeps ownership of all state and falls back to numpy when the
// library is unavailable.
//
// Build: g++ -O3 -std=c++17 -fPIC -shared graphcore.cpp -o libgraphcore.so

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// FNV-1a over a fixed-width field (NUL padding participates on both sides of
// any comparison, so padded equality is exact equality).
static inline uint64_t hash_bytes(const char* p, int64_t len) {
  uint64_t h = 1469598103934665603ull;
  for (int64_t i = 0; i < len; ++i) {
    h ^= static_cast<unsigned char>(p[i]);
    h *= 1099511628211ull;
  }
  return h;
}

struct Slot {
  int64_t row;   // first-occurrence row index, -1 = empty
  uint64_t hash;
};

}  // namespace

extern "C" {

// Hash-based unique+inverse over a fixed-width string column (numpy 'S'
// layout: n rows of `width` bytes). Writes the inverse (id per row, dense in
// first-occurrence order) to inv_out[n] and first-occurrence row indices to
// uniq_rows_out (capacity n). Returns the unique count.
int64_t unique_inverse_fixed(const char* data, int64_t width, int64_t n,
                             int32_t* inv_out, int64_t* uniq_rows_out) {
  if (n <= 0) return 0;
  // open addressing, power-of-two capacity >= 2n
  uint64_t cap = 16;
  while (cap < static_cast<uint64_t>(n) * 2) cap <<= 1;
  std::vector<Slot> table(cap, Slot{-1, 0});
  const uint64_t mask = cap - 1;
  int64_t n_uniq = 0;
  for (int64_t i = 0; i < n; ++i) {
    const char* s = data + i * width;
    const uint64_t h = hash_bytes(s, width);
    uint64_t j = h & mask;
    for (;;) {
      Slot& slot = table[j];
      if (slot.row < 0) {
        slot.row = i;
        slot.hash = h;
        uniq_rows_out[n_uniq] = i;
        inv_out[i] = static_cast<int32_t>(n_uniq);
        ++n_uniq;
        break;
      }
      if (slot.hash == h &&
          std::memcmp(data + slot.row * width, s, width) == 0) {
        inv_out[i] = inv_out[slot.row];
        break;
      }
      j = (j + 1) & mask;
    }
  }
  return n_uniq;
}

// Stable ascending sort permutation of non-negative int64 keys (LSD radix,
// 16-bit digits). out_perm[n] receives row indices; equal keys keep input
// order — compile_graph relies on this to keep residual edges dst-sorted.
void sort_perm_i64(const int64_t* keys, int64_t n, int64_t* out_perm) {
  if (n <= 0) return;
  int64_t max_key = 0;
  for (int64_t i = 0; i < n; ++i) {
    out_perm[i] = i;
    if (keys[i] > max_key) max_key = keys[i];
  }
  std::vector<int64_t> tmp(n);
  int64_t* src = out_perm;
  int64_t* dst = tmp.data();
  for (int shift = 0; shift < 64 && (max_key >> shift) != 0; shift += 16) {
    int64_t counts[65536] = {0};
    for (int64_t i = 0; i < n; ++i)
      ++counts[(keys[src[i]] >> shift) & 0xffff];
    int64_t total = 0;
    for (int b = 0; b < 65536; ++b) {
      int64_t c = counts[b];
      counts[b] = total;
      total += c;
    }
    for (int64_t i = 0; i < n; ++i)
      dst[counts[(keys[src[i]] >> shift) & 0xffff]++] = src[i];
    std::swap(src, dst);
  }
  if (src != out_perm) std::memcpy(out_perm, src, n * sizeof(int64_t));
}

}  // extern "C"

// Row-key index build for the relationship store (engine/store.py
// StoreIndex): mix the six int32 key columns into 64-bit hashes — the
// arithmetic MUST match _hash_key_cols in store.py, which hashes single
// lookup keys against this output — then produce the ascending-hash
// permutation with a multithreaded LSD radix sort. Stability is
// irrelevant (collisions are verified against the columns at lookup), but
// LSD radix is stable anyway.
namespace {

static inline uint64_t mix_key(int32_t rt, int32_t rid, int32_t rl,
                               int32_t st, int32_t sid, int32_t srl) {
  const uint64_t M1 = 0x9E3779B97F4A7C15ull;
  const uint64_t M2 = 0xBF58476D1CE4E5B9ull;
  uint64_t h = static_cast<uint64_t>(rt);
  const int32_t cs[5] = {rid, rl, st, sid, srl};
  for (int i = 0; i < 5; ++i) {
    h = (h ^ static_cast<uint64_t>(cs[i])) * M1;
    h ^= h >> 29;
  }
  h *= M2;
  return h ^ (h >> 32);
}

static inline int pick_threads(int64_t n) {
  if (n < (1 << 20)) return 1;
  unsigned hw = std::thread::hardware_concurrency();
  int t = hw ? static_cast<int>(hw) : 4;
  return t > 16 ? 16 : t;
}

template <typename F>
static void parallel_ranges(int64_t n, int nt, F f) {
  if (nt <= 1) {
    f(0, 0, n);
    return;
  }
  std::vector<std::thread> ts;
  const int64_t step = (n + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    const int64_t lo = t * step;
    const int64_t hi = lo + step < n ? lo + step : n;
    if (lo >= hi) break;
    ts.emplace_back([=] { f(t, lo, hi); });
  }
  for (auto& th : ts) th.join();
}

}  // namespace

extern "C" void index_build_u64(
    const int32_t* rt, const int32_t* rid, const int32_t* rl,
    const int32_t* st, const int32_t* sid, const int32_t* srl, int64_t n,
    uint64_t* hashes_out, int64_t* order_out) {
  if (n <= 0) return;
  const int nt = pick_threads(n);
  std::vector<uint64_t> keys_a(n), keys_b(n);
  std::vector<int64_t> perm_b(n);
  parallel_ranges(n, nt, [&](int, int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      keys_a[i] = mix_key(rt[i], rid[i], rl[i], st[i], sid[i], srl[i]);
      order_out[i] = i;
    }
  });
  uint64_t* ksrc = keys_a.data();
  uint64_t* kdst = keys_b.data();
  int64_t* psrc = order_out;
  int64_t* pdst = perm_b.data();
  // 4 passes of 16-bit digits over the full 64-bit hash
  for (int shift = 0; shift < 64; shift += 16) {
    std::vector<std::vector<int64_t>> counts(
        nt, std::vector<int64_t>(65536, 0));
    parallel_ranges(n, nt, [&](int t, int64_t lo, int64_t hi) {
      auto& c = counts[t];
      for (int64_t i = lo; i < hi; ++i)
        ++c[(ksrc[i] >> shift) & 0xffff];
    });
    // digit-major exclusive prefix across (digit, thread): keeps each
    // thread's scatter region contiguous per digit (stable)
    int64_t running = 0;
    for (int b = 0; b < 65536; ++b) {
      for (int t = 0; t < nt; ++t) {
        const int64_t c = counts[t][b];
        counts[t][b] = running;
        running += c;
      }
    }
    parallel_ranges(n, nt, [&](int t, int64_t lo, int64_t hi) {
      auto& pos = counts[t];
      for (int64_t i = lo; i < hi; ++i) {
        const int64_t j = pos[(ksrc[i] >> shift) & 0xffff]++;
        kdst[j] = ksrc[i];
        pdst[j] = psrc[i];
      }
    });
    std::swap(ksrc, kdst);
    std::swap(psrc, pdst);
  }
  // 4 passes = even number of swaps: results are back in keys_a/order_out
  std::memcpy(hashes_out, ksrc, n * sizeof(uint64_t));
  if (psrc != order_out)
    std::memcpy(order_out, psrc, n * sizeof(int64_t));
}

