// Host-side graph-builder core for the TPU engine.
//
// The reference is pure Go and delegates graph traversal to SpiceDB
// (SURVEY.md §2.5: no native components exist upstream); this library is the
// NEW native tier the rebuild mandates: the host-side hot path that turns
// relationship columns into device-ready edge tensors. Two operations
// dominate snapshot refresh at the 10M-relationship scale (BASELINE.md):
//
//   1. bulk string interning (unique + inverse over id columns)
//   2. the stable sort of edges by destination slot
//
// Both are pure functions over flat buffers so the Python side (ctypes, see
// __init__.py) keeps ownership of all state and falls back to numpy when the
// library is unavailable.
//
// Build: g++ -O3 -std=c++17 -fPIC -shared graphcore.cpp -o libgraphcore.so

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// FNV-1a over a fixed-width field (NUL padding participates on both sides of
// any comparison, so padded equality is exact equality).
static inline uint64_t hash_bytes(const char* p, int64_t len) {
  uint64_t h = 1469598103934665603ull;
  for (int64_t i = 0; i < len; ++i) {
    h ^= static_cast<unsigned char>(p[i]);
    h *= 1099511628211ull;
  }
  return h;
}

struct Slot {
  int64_t row;   // first-occurrence row index, -1 = empty
  uint64_t hash;
};

}  // namespace

extern "C" {

// Hash-based unique+inverse over a fixed-width string column (numpy 'S'
// layout: n rows of `width` bytes). Writes the inverse (id per row, dense in
// first-occurrence order) to inv_out[n] and first-occurrence row indices to
// uniq_rows_out (capacity n). Returns the unique count.
int64_t unique_inverse_fixed(const char* data, int64_t width, int64_t n,
                             int32_t* inv_out, int64_t* uniq_rows_out) {
  if (n <= 0) return 0;
  // open addressing, power-of-two capacity >= 2n
  uint64_t cap = 16;
  while (cap < static_cast<uint64_t>(n) * 2) cap <<= 1;
  std::vector<Slot> table(cap, Slot{-1, 0});
  const uint64_t mask = cap - 1;
  int64_t n_uniq = 0;
  for (int64_t i = 0; i < n; ++i) {
    const char* s = data + i * width;
    const uint64_t h = hash_bytes(s, width);
    uint64_t j = h & mask;
    for (;;) {
      Slot& slot = table[j];
      if (slot.row < 0) {
        slot.row = i;
        slot.hash = h;
        uniq_rows_out[n_uniq] = i;
        inv_out[i] = static_cast<int32_t>(n_uniq);
        ++n_uniq;
        break;
      }
      if (slot.hash == h &&
          std::memcmp(data + slot.row * width, s, width) == 0) {
        inv_out[i] = inv_out[slot.row];
        break;
      }
      j = (j + 1) & mask;
    }
  }
  return n_uniq;
}

// Stable ascending sort permutation of non-negative int64 keys (LSD radix,
// 16-bit digits). out_perm[n] receives row indices; equal keys keep input
// order — compile_graph relies on this to keep residual edges dst-sorted.
void sort_perm_i64(const int64_t* keys, int64_t n, int64_t* out_perm) {
  if (n <= 0) return;
  int64_t max_key = 0;
  for (int64_t i = 0; i < n; ++i) {
    out_perm[i] = i;
    if (keys[i] > max_key) max_key = keys[i];
  }
  std::vector<int64_t> tmp(n);
  int64_t* src = out_perm;
  int64_t* dst = tmp.data();
  for (int shift = 0; shift < 64 && (max_key >> shift) != 0; shift += 16) {
    int64_t counts[65536] = {0};
    for (int64_t i = 0; i < n; ++i)
      ++counts[(keys[src[i]] >> shift) & 0xffff];
    int64_t total = 0;
    for (int b = 0; b < 65536; ++b) {
      int64_t c = counts[b];
      counts[b] = total;
      total += c;
    }
    for (int64_t i = 0; i < n; ++i)
      dst[counts[(keys[src[i]] >> shift) & 0xffff]++] = src[i];
    std::swap(src, dst);
  }
  if (src != out_perm) std::memcpy(out_perm, src, n * sizeof(int64_t));
}

}  // extern "C"

// Row-key index build for the relationship store (engine/store.py
// StoreIndex): mix the six int32 key columns into 64-bit hashes — the
// arithmetic MUST match _hash_key_cols in store.py, which hashes single
// lookup keys against this output — then produce the ascending-hash
// permutation with a multithreaded LSD radix sort. Stability is
// irrelevant (collisions are verified against the columns at lookup), but
// LSD radix is stable anyway.
namespace {

static inline uint64_t mix_key(int32_t rt, int32_t rid, int32_t rl,
                               int32_t st, int32_t sid, int32_t srl) {
  const uint64_t M1 = 0x9E3779B97F4A7C15ull;
  const uint64_t M2 = 0xBF58476D1CE4E5B9ull;
  uint64_t h = static_cast<uint64_t>(rt);
  const int32_t cs[5] = {rid, rl, st, sid, srl};
  for (int i = 0; i < 5; ++i) {
    h = (h ^ static_cast<uint64_t>(cs[i])) * M1;
    h ^= h >> 29;
  }
  h *= M2;
  return h ^ (h >> 32);
}

static inline int pick_threads(int64_t n) {
  if (n < (1 << 20)) return 1;
  unsigned hw = std::thread::hardware_concurrency();
  int t = hw ? static_cast<int>(hw) : 4;
  return t > 16 ? 16 : t;
}

template <typename F>
static void parallel_ranges(int64_t n, int nt, F f) {
  if (nt <= 1) {
    f(0, 0, n);
    return;
  }
  std::vector<std::thread> ts;
  const int64_t step = (n + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    const int64_t lo = t * step;
    const int64_t hi = lo + step < n ? lo + step : n;
    if (lo >= hi) break;
    ts.emplace_back([=] { f(t, lo, hi); });
  }
  for (auto& th : ts) th.join();
}

}  // namespace

extern "C" void index_build_u64(
    const int32_t* rt, const int32_t* rid, const int32_t* rl,
    const int32_t* st, const int32_t* sid, const int32_t* srl, int64_t n,
    uint64_t* hashes_out, int64_t* order_out) {
  if (n <= 0) return;
  const int nt = pick_threads(n);
  std::vector<uint64_t> keys_a(n), keys_b(n);
  std::vector<int64_t> perm_b(n);
  parallel_ranges(n, nt, [&](int, int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      keys_a[i] = mix_key(rt[i], rid[i], rl[i], st[i], sid[i], srl[i]);
      order_out[i] = i;
    }
  });
  uint64_t* ksrc = keys_a.data();
  uint64_t* kdst = keys_b.data();
  int64_t* psrc = order_out;
  int64_t* pdst = perm_b.data();
  // 4 passes of 16-bit digits over the full 64-bit hash
  for (int shift = 0; shift < 64; shift += 16) {
    std::vector<std::vector<int64_t>> counts(
        nt, std::vector<int64_t>(65536, 0));
    parallel_ranges(n, nt, [&](int t, int64_t lo, int64_t hi) {
      auto& c = counts[t];
      for (int64_t i = lo; i < hi; ++i)
        ++c[(ksrc[i] >> shift) & 0xffff];
    });
    // digit-major exclusive prefix across (digit, thread): keeps each
    // thread's scatter region contiguous per digit (stable)
    int64_t running = 0;
    for (int b = 0; b < 65536; ++b) {
      for (int t = 0; t < nt; ++t) {
        const int64_t c = counts[t][b];
        counts[t][b] = running;
        running += c;
      }
    }
    parallel_ranges(n, nt, [&](int t, int64_t lo, int64_t hi) {
      auto& pos = counts[t];
      for (int64_t i = lo; i < hi; ++i) {
        const int64_t j = pos[(ksrc[i] >> shift) & 0xffff]++;
        kdst[j] = ksrc[i];
        pdst[j] = psrc[i];
      }
    });
    std::swap(ksrc, kdst);
    std::swap(psrc, pdst);
  }
  // 4 passes = even number of swaps: results are back in keys_a/order_out
  std::memcpy(hashes_out, ksrc, n * sizeof(uint64_t));
  if (psrc != order_out)
    std::memcpy(order_out, psrc, n * sizeof(int64_t));
}


// ---------------------------------------------------------------------------
// JSON list scanner (authz/filterer.py): one pass over a kube List response
// body locating the top-level "kind" value, the top-level `items_key` array,
// every element's byte span, and each element's metadata.name /
// metadata.namespace string-value spans (raw bytes between the quotes —
// escape decoding, when needed, happens Python-side). Lets the filter keep
// items BYTE-IDENTICAL and skip json.loads on multi-MB bodies.
//
// Returns the item count (>= 0) on success, or a negative bail code — the
// caller then falls back to the Python json path, so this scanner is
// conservative: anything structurally surprising (escaped keys,
// non-object items, duplicate items keys, trailing garbage, malformed
// strings or scalar tokens anywhere) bails rather than risking
// semantics that differ from json.loads. Known disclosed laxity: the
// comma/colon PLACEMENT inside skipped substructure is not re-validated
// — a body like {"spec":{"a" "b"}} passes here where json.loads raises
// (which the Python path turns into a 401); an apiserver never emits
// such bodies, and no AUTHORIZATION decision depends on skipped bytes.

namespace jsonscan {

struct Scan {
  const char* b;
  int64_t n;
  int64_t i = 0;
  bool fail = false;

  void ws() {
    while (i < n) {
      const char c = b[i];
      if (c == ' ' || c == '\t' || c == '\n' || c == '\r') ++i;
      else break;
    }
  }
  bool at(char c) { return i < n && b[i] == c; }
  static bool hex(unsigned char c) {
    return (c >= '0' && c <= '9') || (c >= 'a' && c <= 'f') ||
           (c >= 'A' && c <= 'F');
  }
  // raw string content span [s, e); has_esc set when a backslash occurs.
  // Validates exactly what json.loads does at the string level: literal
  // control bytes (< 0x20) fail (strict mode — which also guarantees
  // raw spans never contain the 0x1f/0x1e record separators of the key
  // buffer), escape sequences must be well-formed, and the bytes must
  // be valid UTF-8 (no overlongs, no surrogates, <= U+10FFFF) so raw
  // byte comparison is equivalent to decoded string comparison.
  bool str_span(int64_t* s, int64_t* e, bool* has_esc) {
    if (!at('"')) { fail = true; return false; }
    ++i;
    *s = i;
    *has_esc = false;
    while (i < n) {
      const unsigned char c = b[i];
      if (c < 0x20) { fail = true; return false; }
      if (c == '\\') {
        *has_esc = true;
        if (i + 1 >= n) { fail = true; return false; }
        const unsigned char esc = b[i + 1];
        if (esc == 'u') {
          if (i + 5 >= n || !hex(b[i + 2]) || !hex(b[i + 3]) ||
              !hex(b[i + 4]) || !hex(b[i + 5])) {
            fail = true;
            return false;
          }
          i += 6;
        } else if (esc == '"' || esc == '\\' || esc == '/' ||
                   esc == 'b' || esc == 'f' || esc == 'n' ||
                   esc == 'r' || esc == 't') {
          i += 2;
        } else {
          fail = true;  // invalid escape: json.loads rejects
          return false;
        }
        continue;
      }
      if (c == '"') { *e = i; ++i; return true; }
      if (c < 0x80) { ++i; continue; }
      // multi-byte UTF-8, validated like CPython's decoder
      int need;
      unsigned char lo = 0x80, hi = 0xBF;
      if (c >= 0xC2 && c <= 0xDF) need = 1;
      else if (c == 0xE0) { need = 2; lo = 0xA0; }
      else if (c >= 0xE1 && c <= 0xEC) need = 2;
      else if (c == 0xED) { need = 2; hi = 0x9F; }  // no surrogates
      else if (c == 0xEE || c == 0xEF) need = 2;
      else if (c == 0xF0) { need = 3; lo = 0x90; }
      else if (c >= 0xF1 && c <= 0xF3) need = 3;
      else if (c == 0xF4) { need = 3; hi = 0x8F; }  // <= U+10FFFF
      else { fail = true; return false; }
      if (i + need >= n) { fail = true; return false; }
      unsigned char c1 = b[i + 1];
      if (c1 < lo || c1 > hi) { fail = true; return false; }
      for (int k = 2; k <= need; ++k) {
        const unsigned char ck = b[i + k];
        if (ck < 0x80 || ck > 0xBF) { fail = true; return false; }
      }
      i += need + 1;
    }
    fail = true;
    return false;
  }
  bool key_is(int64_t s, int64_t e, const char* lit) {
    const int64_t m = (int64_t)strlen(lit);
    return e - s == m && memcmp(b + s, lit, (size_t)m) == 0;
  }
  // strict scalar token: number / true / false / null / NaN / ±Infinity
  // — the exact forms json.loads accepts, number grammar included
  // (leading zeros, '+' signs, dangling exponents all fail)
  void scalar() {
    const int64_t s = i;
    while (i < n) {
      const char c = b[i];
      if (c == ',' || c == '}' || c == ']' || c == ':' || c == ' ' ||
          c == '\t' || c == '\n' || c == '\r')
        break;
      ++i;
    }
    const int64_t m = i - s;
    if (m <= 0) { fail = true; return; }
    auto is = [&](const char* lit) {
      return (int64_t)strlen(lit) == m && memcmp(b + s, lit, (size_t)m) == 0;
    };
    if (is("true") || is("false") || is("null") || is("NaN") ||
        is("Infinity") || is("-Infinity"))
      return;
    // -?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][+-]?[0-9]+)?
    const char* p = b + s;
    int64_t k = 0;
    auto dig = [&](int64_t j) {
      return j < m && p[j] >= '0' && p[j] <= '9';
    };
    if (k < m && p[k] == '-') ++k;
    if (!dig(k)) { fail = true; return; }
    if (p[k] == '0') ++k;
    else while (dig(k)) ++k;
    if (k < m && p[k] == '.') {
      ++k;
      if (!dig(k)) { fail = true; return; }
      while (dig(k)) ++k;
    }
    if (k < m && (p[k] == 'e' || p[k] == 'E')) {
      ++k;
      if (k < m && (p[k] == '+' || p[k] == '-')) ++k;
      if (!dig(k)) { fail = true; return; }
      while (dig(k)) ++k;
    }
    if (k != m) fail = true;
  }
  // Skip any value. Containers are walked iteratively with every string
  // and scalar TOKEN validated (so `@@@` or `1e+e+5` anywhere bails);
  // comma/colon PLACEMENT inside skipped substructure is not re-checked
  // — that is the one laxity vs json.loads, disclosed in the entry
  // point's contract comment.
  void skip_value() {
    ws();
    if (fail || i >= n) { fail = true; return; }
    const char c0 = b[i];
    if (c0 == '"') {
      int64_t s, e;
      bool esc;
      str_span(&s, &e, &esc);
      return;
    }
    if (c0 == '{' || c0 == '[') {
      int64_t depth = 0;
      while (i < n) {
        const char c = b[i];
        if (c == ' ' || c == '\t' || c == '\n' || c == '\r' ||
            c == ',' || c == ':') {
          ++i;
          continue;
        }
        if (c == '"') {
          int64_t s, e;
          bool esc;
          if (!str_span(&s, &e, &esc)) return;
          continue;
        }
        if (c == '{' || c == '[') { ++depth; ++i; continue; }
        if (c == '}' || c == ']') {
          --depth;
          ++i;
          if (depth == 0) return;
          if (depth < 0) { fail = true; return; }
          continue;
        }
        scalar();
        if (fail) return;
      }
      fail = true;
      return;
    }
    scalar();
  }
};

}  // namespace jsonscan

extern "C" int64_t json_list_spans(
    const char* buf, int64_t n, const char* items_key,
    int64_t* kind_span,   // [2] raw value span, -1,-1 when absent
    int64_t* arr_span,    // [2] start = after '[', end = index of ']'
    int64_t* item_spans,  // [2 * max_items]
    char* key_buf,        // >= n + 3*max_items bytes; per item one record
                          // [esc '0'|'1'] ns_raw 0x1f name_raw 0x1e (raw =
                          // undecoded string content; missing -> empty)
    int64_t* key_len,     // out: bytes written into key_buf
    int64_t nested,       // 0: metadata at item top level (List items);
                          // 1: inside item["object"] (Table rows)
    int64_t max_items) {
  jsonscan::Scan sc{buf, n};
  kind_span[0] = kind_span[1] = -1;
  arr_span[0] = arr_span[1] = -1;
  *key_len = 0;
  int64_t count = 0;
  bool items_seen = false;
  // per-item metadata string spans (last-wins under duplicate keys, so
  // the record is emitted only when the item closes)
  int64_t nm_s, nm_e, ns_s, ns_e;
  bool nm_esc, ns_esc;

  // one object level: dispatch(key_s, key_e) -> true when it consumed the
  // value itself; false means "skip it here"
  auto walk_object = [&](auto&& on_key) -> bool {
    sc.ws();
    if (!sc.at('{')) { sc.fail = true; return false; }
    ++sc.i;
    sc.ws();
    if (sc.at('}')) { ++sc.i; return true; }
    while (true) {
      sc.ws();
      int64_t ks, ke;
      bool kesc;
      if (!sc.str_span(&ks, &ke, &kesc)) return false;
      if (kesc) { sc.fail = true; return false; }  // escaped key: bail
      sc.ws();
      if (!sc.at(':')) { sc.fail = true; return false; }
      ++sc.i;
      if (!on_key(ks, ke)) sc.skip_value();
      if (sc.fail) return false;
      sc.ws();
      if (sc.at(',')) { ++sc.i; continue; }
      if (sc.at('}')) { ++sc.i; return true; }
      sc.fail = true;
      return false;
    }
  };

  auto parse_metadata = [&]() -> bool {
    // last-wins like dict construction: reset, then fill
    nm_s = nm_e = ns_s = ns_e = -1;
    nm_esc = ns_esc = false;
    sc.ws();
    if (!sc.at('{')) { sc.fail = true; return false; }
    return walk_object([&](int64_t ks, int64_t ke) -> bool {
      const bool is_name = sc.key_is(ks, ke, "name");
      const bool is_ns = !is_name && sc.key_is(ks, ke, "namespace");
      if (!is_name && !is_ns) return false;
      sc.ws();
      if (!sc.at('"')) {
        // non-string name/namespace: Python's or-coercion semantics
        // differ from treat-as-missing — bail to the json path
        sc.fail = true;
        return true;
      }
      int64_t vs, ve;
      bool vesc;
      if (!sc.str_span(&vs, &ve, &vesc)) return true;
      if (is_name) { nm_s = vs; nm_e = ve; nm_esc = vesc; }
      else { ns_s = vs; ns_e = ve; ns_esc = vesc; }
      return true;
    });
  };

  auto parse_item = [&]() -> bool {
    if (count >= max_items) { sc.fail = true; return false; }
    const int64_t idx = count;
    nm_s = nm_e = ns_s = ns_e = -1;
    nm_esc = ns_esc = false;
    sc.ws();
    const int64_t start = sc.i;
    if (!sc.at('{')) { sc.fail = true; return false; }  // non-object item
    const bool walked =
        nested
            ? walk_object([&](int64_t ks, int64_t ke) -> bool {
                // Table row: the keyable object rides row["object"]
                // (reference filters rows by that object's metadata)
                if (!sc.key_is(ks, ke, "object")) return false;
                sc.ws();
                if (!sc.at('{')) { sc.fail = true; return true; }
                // last-wins under duplicate "object" keys: a later
                // object without metadata must CLEAR earlier spans
                nm_s = nm_e = ns_s = ns_e = -1;
                nm_esc = ns_esc = false;
                return walk_object([&](int64_t ks2, int64_t ke2) -> bool {
                  if (!sc.key_is(ks2, ke2, "metadata")) return false;
                  return parse_metadata();
                });
              })
            : walk_object([&](int64_t ks, int64_t ke) -> bool {
                if (!sc.key_is(ks, ke, "metadata")) return false;
                return parse_metadata();
              });
    if (!walked) return false;
    item_spans[2 * idx] = start;
    item_spans[2 * idx + 1] = sc.i;  // exclusive, after the closing '}'
    char* kb = key_buf + *key_len;
    *kb++ = (nm_esc || ns_esc) ? '1' : '0';
    if (ns_s >= 0) {
      memcpy(kb, buf + ns_s, (size_t)(ns_e - ns_s));
      kb += ns_e - ns_s;
    }
    *kb++ = '\x1f';
    if (nm_s >= 0) {
      memcpy(kb, buf + nm_s, (size_t)(nm_e - nm_s));
      kb += nm_e - nm_s;
    }
    *kb++ = '\x1e';
    *key_len = kb - key_buf;
    ++count;
    return true;
  };

  auto parse_items_array = [&]() -> bool {
    sc.ws();
    if (!sc.at('[')) { sc.fail = true; return false; }
    ++sc.i;
    arr_span[0] = sc.i;
    sc.ws();
    if (sc.at(']')) { arr_span[1] = sc.i; ++sc.i; return true; }
    while (true) {
      if (!parse_item()) return false;
      sc.ws();
      if (sc.at(',')) { ++sc.i; continue; }
      if (sc.at(']')) { arr_span[1] = sc.i; ++sc.i; return true; }
      sc.fail = true;
      return false;
    }
  };

  const bool ok = walk_object([&](int64_t ks, int64_t ke) -> bool {
    if (sc.key_is(ks, ke, "kind")) {
      sc.ws();
      if (!sc.at('"')) return false;  // non-string kind: skip
      int64_t vs, ve;
      bool vesc;
      if (!sc.str_span(&vs, &ve, &vesc)) return true;
      if (vesc) { sc.fail = true; return true; }  // escaped kind: bail
      // last-wins duplicate kind, like dict construction
      kind_span[0] = vs;
      kind_span[1] = ve;
      return true;
    }
    if (sc.key_is(ks, ke, items_key)) {
      if (items_seen) { sc.fail = true; return true; }  // dup items: bail
      items_seen = true;
      parse_items_array();
      return true;
    }
    return false;
  });
  if (!ok || sc.fail) return -1;
  sc.ws();
  if (sc.i != n) return -1;  // trailing garbage: json.loads would raise
  // items_key absent entirely: legal (count 0, arr_span -1) — the
  // caller may only need the kind (e.g. to rescan a Table under "rows")
  return count;
}

// Bumped on ANY exported-signature change: the loader refuses a library
// whose ABI differs (a stale cached .so with preserved mtimes would
// otherwise bind by name and silently misread arguments).
extern "C" int64_t graphcore_abi_version() { return 4; }

// ---------------------------------------------------------------------------
// Protobuf list scanner (authz/filterer.py filter_body_proto): one pass
// over a kube *List message's bytes (the runtime.Unknown `raw` field,
// magic stripped) locating every repeated `items` element's full chunk
// span (tag included) and packing the same per-item key records the JSON
// scanner emits: '0' ns 0x1f name 0x1e. First-occurrence field semantics
// mirror the Python walker (kubeproto._field). Bails (-1) on truncated
// wire data, or on names/namespaces containing control bytes (< 0x20 —
// would collide with the record separators) or invalid UTF-8 (the Python
// path decodes with errors="replace"; such names cannot legitimately
// exist in kube and authority stays with the slow path).

namespace protoscan {

struct PScan {
  const unsigned char* b;
  int64_t n;
  int64_t i = 0;
  bool fail = false;

  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (i < n) {
      const unsigned char c = b[i++];
      v |= (uint64_t)(c & 0x7F) << shift;
      if (!(c & 0x80)) return v;
      shift += 7;
      if (shift > 63) { fail = true; return 0; }
    }
    fail = true;
    return 0;
  }
  // skip one field of wire type wt (tag already consumed)
  void skip(int wt) {
    switch (wt) {
      case 0: varint(); return;
      case 1: i += 8; if (i > n) fail = true; return;
      case 2: {
        const uint64_t len = varint();
        if (fail) return;
        // validate BEFORE the signed cast: a huge length varint would
        // otherwise wrap negative and walk backward / spin forever
        if (len > (uint64_t)(n - i)) { fail = true; return; }
        i += (int64_t)len;
        return;
      }
      case 5: i += 4; if (i > n) fail = true; return;
      default: fail = true; return;
    }
  }
};

// valid UTF-8 with no control bytes (< 0x20)?
static bool clean_utf8(const unsigned char* p, int64_t m) {
  int64_t i = 0;
  while (i < m) {
    const unsigned char c = p[i];
    if (c < 0x20) return false;
    if (c < 0x80) { ++i; continue; }
    int need;
    unsigned char lo = 0x80, hi = 0xBF;
    if (c >= 0xC2 && c <= 0xDF) need = 1;
    else if (c == 0xE0) { need = 2; lo = 0xA0; }
    else if (c >= 0xE1 && c <= 0xEC) need = 2;
    else if (c == 0xED) { need = 2; hi = 0x9F; }
    else if (c == 0xEE || c == 0xEF) need = 2;
    else if (c == 0xF0) { need = 3; lo = 0x90; }
    else if (c >= 0xF1 && c <= 0xF3) need = 3;
    else if (c == 0xF4) { need = 3; hi = 0x8F; }
    else return false;
    if (i + need >= m) return false;
    if (p[i + 1] < lo || p[i + 1] > hi) return false;
    for (int k = 2; k <= need; ++k)
      if (p[i + k] < 0x80 || p[i + k] > 0xBF) return false;
    i += need + 1;
  }
  return true;
}

// Find the length-delimited field `fno` within [start, end): first or
// last occurrence (kubeproto._field vs decode_unknown semantics).
// Returns false on malformed wire (caller bails); absent field leaves
// *s == -1 and returns true.
static bool find_ld_field(const unsigned char* buf, int64_t start,
                          int64_t end, uint64_t fno, bool last_wins,
                          int64_t* s, int64_t* e) {
  PScan p{buf, end, start};
  *s = *e = -1;
  while (p.i < end) {
    const uint64_t tag = p.varint();
    if (p.fail) return false;
    const uint64_t f = tag >> 3;
    const int wt = (int)(tag & 7);
    if (f == fno && wt == 2 && (last_wins || *s < 0)) {
      const uint64_t len = p.varint();
      if (p.fail) return false;
      if (len > (uint64_t)(end - p.i)) return false;
      *s = p.i;
      *e = p.i + (int64_t)len;
      p.i = *e;
    } else {
      p.skip(wt);
      if (p.fail) return false;
    }
  }
  return true;
}

}  // namespace protoscan

extern "C" int64_t proto_list_spans(
    const char* buf_, int64_t n,
    int64_t* item_spans,  // [2*max_items] full chunk spans (tag included)
    char* key_buf,        // >= n + 3*max_items; '0' ns 0x1f name 0x1e
    int64_t* key_len, int64_t max_items) {
  using protoscan::PScan;
  const unsigned char* buf = (const unsigned char*)buf_;
  PScan sc{buf, n};
  *key_len = 0;
  int64_t count = 0;
  while (sc.i < n) {
    const int64_t tag_start = sc.i;
    const uint64_t tag = sc.varint();
    if (sc.fail) return -1;
    // field numbers compared at full 64-bit width: truncation could
    // alias a huge field number onto 2 and mis-key a chunk as an item
    const uint64_t fno = tag >> 3;
    const int wt = (int)(tag & 7);
    if (fno != 2 || wt != 2) {  // every XList: repeated items = field 2
      sc.skip(wt);
      if (sc.fail) return -1;
      continue;
    }
    const uint64_t ilen = sc.varint();
    if (sc.fail) return -1;
    if (ilen > (uint64_t)(n - sc.i)) return -1;
    const int64_t istart = sc.i, iend = sc.i + (int64_t)ilen;
    if (count >= max_items) return -2;  // caller grows and retries
    // first metadata (field 1) inside the item; within it the first
    // name (1) / namespace (3) — kubeproto._field semantics
    int64_t meta_s, meta_e;
    int64_t nm_s = -1, nm_e = -1, ns_s = -1, ns_e = -1;
    if (!protoscan::find_ld_field(buf, istart, iend, 1, false,
                                  &meta_s, &meta_e))
      return -1;
    if (meta_s >= 0) {
      if (!protoscan::find_ld_field(buf, meta_s, meta_e, 1, false,
                                    &nm_s, &nm_e))
        return -1;
      if (!protoscan::find_ld_field(buf, meta_s, meta_e, 3, false,
                                    &ns_s, &ns_e))
        return -1;
    }
    if (nm_s >= 0 &&
        !protoscan::clean_utf8(buf + nm_s, nm_e - nm_s))
      return -1;
    if (ns_s >= 0 &&
        !protoscan::clean_utf8(buf + ns_s, ns_e - ns_s))
      return -1;
    item_spans[2 * count] = tag_start;
    item_spans[2 * count + 1] = iend;
    char* kb = key_buf + *key_len;
    *kb++ = '0';
    if (ns_s >= 0) {
      memcpy(kb, buf + ns_s, (size_t)(ns_e - ns_s));
      kb += ns_e - ns_s;
    }
    *kb++ = '\x1f';
    if (nm_s >= 0) {
      memcpy(kb, buf + nm_s, (size_t)(nm_e - nm_s));
      kb += nm_e - nm_s;
    }
    *kb++ = '\x1e';
    *key_len = kb - key_buf;
    ++count;
    sc.i = iend;
  }
  return count;
}

// Protobuf Table scanner: rows = repeated field 3 of meta.k8s.io Table;
// each row's keyable object rides row.object (RawExtension, field 3)
// whose raw bytes (field 1, FIRST occurrence like kubeproto._field) are
// either a magic-prefixed runtime.Unknown (raw = field 2, LAST
// occurrence like kubeproto.decode_unknown) or a bare
// PartialObjectMetadata. Emits the same spans + key records as
// proto_list_spans. Bails (-1) on any row without a keyable object or
// with an empty name — the Python walker raises ProtoError there
// (clean 401) and keeps authority.
extern "C" int64_t proto_table_spans(
    const char* buf_, int64_t n,
    int64_t* item_spans, char* key_buf, int64_t* key_len,
    int64_t max_items) {
  using protoscan::PScan;
  const unsigned char* buf = (const unsigned char*)buf_;
  PScan sc{buf, n};
  *key_len = 0;
  int64_t count = 0;
  while (sc.i < n) {
    const int64_t tag_start = sc.i;
    const uint64_t tag = sc.varint();
    if (sc.fail) return -1;
    const uint64_t fno = tag >> 3;
    const int wt = (int)(tag & 7);
    if (fno != 3 || wt != 2) {  // Table: repeated rows = field 3
      sc.skip(wt);
      if (sc.fail) return -1;
      continue;
    }
    const uint64_t rlen = sc.varint();
    if (sc.fail) return -1;
    if (rlen > (uint64_t)(n - sc.i)) return -1;
    const int64_t rstart = sc.i, rend = sc.i + (int64_t)rlen;
    if (count >= max_items) return -2;
    // row.object -> RawExtension.raw -> (magic Unknown?) -> metadata
    // -> name/namespace, all via the shared bounded field finder
    int64_t ext_s, ext_e;
    if (!protoscan::find_ld_field(buf, rstart, rend, 3, false,
                                  &ext_s, &ext_e))
      return -1;
    if (ext_s < 0) return -1;  // no object: Python raises (401)
    int64_t raw_s, raw_e;
    if (!protoscan::find_ld_field(buf, ext_s, ext_e, 1, false,
                                  &raw_s, &raw_e))
      return -1;
    if (raw_s < 0) return -1;  // no raw bytes: Python raises
    // magic-prefixed Unknown? take its raw (field 2, LAST occurrence —
    // decode_unknown's loop overwrites)
    int64_t obj_s = raw_s, obj_e = raw_e;
    if (raw_e - raw_s >= 4 && memcmp(buf + raw_s, "k8s\x00", 4) == 0) {
      if (!protoscan::find_ld_field(buf, raw_s + 4, raw_e, 2, true,
                                    &obj_s, &obj_e))
        return -1;
      if (obj_s < 0) obj_s = obj_e = raw_s;  // no raw: empty object
    }
    int64_t meta_s, meta_e;
    int64_t nm_s = -1, nm_e = -1, ns_s = -1, ns_e = -1;
    if (!protoscan::find_ld_field(buf, obj_s, obj_e, 1, false,
                                  &meta_s, &meta_e))
      return -1;
    if (meta_s >= 0) {
      if (!protoscan::find_ld_field(buf, meta_s, meta_e, 1, false,
                                    &nm_s, &nm_e))
        return -1;
      if (!protoscan::find_ld_field(buf, meta_s, meta_e, 3, false,
                                    &ns_s, &ns_e))
        return -1;
    }
    if (nm_s < 0 || nm_e == nm_s) return -1;  // empty name: Python raises
    if (!protoscan::clean_utf8(buf + nm_s, nm_e - nm_s)) return -1;
    if (ns_s >= 0 &&
        !protoscan::clean_utf8(buf + ns_s, ns_e - ns_s))
      return -1;
    item_spans[2 * count] = tag_start;
    item_spans[2 * count + 1] = rend;
    char* kb = key_buf + *key_len;
    *kb++ = '0';
    if (ns_s >= 0) {
      memcpy(kb, buf + ns_s, (size_t)(ns_e - ns_s));
      kb += ns_e - ns_s;
    }
    *kb++ = '\x1f';
    memcpy(kb, buf + nm_s, (size_t)(nm_e - nm_s));
    kb += nm_e - nm_s;
    *kb++ = '\x1e';
    *key_len = kb - key_buf;
    ++count;
    sc.i = rend;
  }
  return count;
}
