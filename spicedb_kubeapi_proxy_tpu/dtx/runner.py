"""Deterministic, durable workflow runner over a SQLite event log.

The durability contract of the reference's go-workflows engine
(/root/reference/pkg/authz/distributedtx/client.go:32-62): every activity
result is event-sourced; a crash mid-workflow leaves the instance
incomplete, and a restarted worker replays the recorded events through the
workflow code (which must be deterministic) and continues from the first
unrecorded step. Activities therefore run at-least-once — exactly-once
effects come from idempotency keys (activity.py), like the reference
(activity.go:49-76).

Workflows are generator functions::

    def my_workflow(ctx, input):
        result = yield ctx.call("activity_name", arg1=..., arg2=...)
        yield ctx.sleep(0.1)
        return {"done": result}

Activity errors are re-raised into the generator as ActivityError so
workflow code can implement retry/rollback (the reference's pattern). A
WorkflowCrash escaping an activity abandons the instance without recording
— simulating a process kill at a side-effect edge (the failpoint e2e
matrix, reference proxy_test.go:650-860).
"""

from __future__ import annotations

import asyncio
import json
import sqlite3
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..utils.failpoints import FailPointError


class WorkflowTimeout(TimeoutError):
    pass


class WorkflowCrash(RuntimeError):
    """Simulated process death: abandon the instance (no event recorded)."""


class ActivityError(RuntimeError):
    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


@dataclass
class _Call:
    kind: str  # "call" | "sleep"
    name: str
    args: dict


class WorkflowContext:
    def __init__(self, instance_id: str):
        self.instance_id = instance_id

    def call(self, name: str, **args) -> _Call:
        return _Call("call", name, args)

    def sleep(self, seconds: float) -> _Call:
        return _Call("sleep", "", {"seconds": seconds})


class WorkflowEngine:
    """Client + worker in one process (the reference's monoprocess backend,
    client.go:39)."""

    def __init__(self, db_path: str = ":memory:",
                 activities: Optional[dict[str, Callable]] = None,
                 workflows: Optional[dict[str, Callable]] = None):
        self.db_path = db_path
        self.activities = dict(activities or {})
        self.workflows = dict(workflows or {})
        self._db = sqlite3.connect(db_path, check_same_thread=False)
        self._db_lock = threading.Lock()
        self._done_events: dict[str, asyncio.Event] = {}
        self._tasks: set[asyncio.Task] = set()
        with self._db_lock:
            self._db.executescript("""
                CREATE TABLE IF NOT EXISTS instances (
                    id TEXT PRIMARY KEY,
                    workflow TEXT NOT NULL,
                    input TEXT NOT NULL,
                    status TEXT NOT NULL,
                    result TEXT,
                    error TEXT,
                    created REAL NOT NULL
                );
                CREATE TABLE IF NOT EXISTS events (
                    instance_id TEXT NOT NULL,
                    seq INTEGER NOT NULL,
                    kind TEXT NOT NULL,
                    name TEXT NOT NULL,
                    result TEXT,
                    error TEXT,
                    PRIMARY KEY (instance_id, seq)
                );
            """)
            self._db.commit()

    def register_activity(self, name: str, fn: Callable) -> None:
        self.activities[name] = fn

    def register_workflow(self, name: str, fn: Callable) -> None:
        self.workflows[name] = fn

    # -- client API ---------------------------------------------------------

    async def create_instance(self, workflow: str, input: Any,
                              instance_id: Optional[str] = None) -> str:
        if workflow not in self.workflows:
            raise KeyError(f"unknown workflow {workflow!r}")
        iid = instance_id or uuid.uuid4().hex
        # sqlite commit fsyncs when db_path is a real file (<data-dir>/
        # dtx.sqlite): keep it off the event loop, or every in-flight
        # request stalls behind this write's disk latency while
        # _db_lock is held (check_same_thread=False + _db_lock make the
        # connection safe to drive from a worker thread)
        await asyncio.to_thread(self._insert_instance, iid, workflow,
                                input)
        self._spawn(iid)
        return iid

    def _insert_instance(self, iid: str, workflow: str,
                         input: Any) -> None:
        with self._db_lock:
            self._db.execute(
                "INSERT INTO instances (id, workflow, input, status, created) "
                "VALUES (?, ?, ?, 'running', ?)",
                (iid, workflow, json.dumps(input), time.time()),
            )
            self._db.commit()

    async def get_result(self, instance_id: str, timeout: float = 30.0) -> Any:
        """Wait for completion (reference dualWrite waits ≤30s,
        update.go:146-195 / workflow.go:31)."""
        ev = self._done_events.setdefault(instance_id, asyncio.Event())
        row = await asyncio.to_thread(self._instance_row, instance_id)
        if row is None:
            raise KeyError(f"unknown workflow instance {instance_id}")
        if row["status"] in ("completed", "failed"):
            return self._result_of(row)
        try:
            await asyncio.wait_for(ev.wait(), timeout)
        except asyncio.TimeoutError:
            raise WorkflowTimeout(
                f"workflow {instance_id} did not complete in {timeout}s"
            ) from None
        finally:
            # bound _done_events: the result lives in the DB from here on
            self._done_events.pop(instance_id, None)
        return self._result_of(
            await asyncio.to_thread(self._instance_row, instance_id))

    async def resume_pending(self) -> list[str]:
        """Start every incomplete instance (crash recovery on boot)."""
        def select_running():
            with self._db_lock:
                return self._db.execute(
                    "SELECT id FROM instances WHERE status = 'running'"
                ).fetchall()
        rows = await asyncio.to_thread(select_running)
        ids = [r[0] for r in rows]
        for iid in ids:
            self._spawn(iid)
        return ids

    def pending_count(self) -> int:
        with self._db_lock:
            (n,) = self._db.execute(
                "SELECT COUNT(*) FROM instances WHERE status = 'running'"
            ).fetchone()
        return int(n)

    async def shutdown(self) -> None:
        for t in list(self._tasks):
            t.cancel()
        for t in list(self._tasks):
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass

        # close under _db_lock on a worker thread: client coroutines
        # (create_instance/get_result) run their DB ops via
        # asyncio.to_thread and are not in self._tasks — closing
        # unlocked could interleave mid-execute on the shared connection
        def close_db():
            with self._db_lock:
                self._db.close()
        await asyncio.to_thread(close_db)

    # -- internals ----------------------------------------------------------

    def _instance_row(self, iid: str) -> Optional[dict]:
        with self._db_lock:
            row = self._db.execute(
                "SELECT id, workflow, input, status, result, error "
                "FROM instances WHERE id = ?", (iid,)
            ).fetchone()
        if row is None:
            return None
        return dict(zip(("id", "workflow", "input", "status", "result",
                         "error"), row))

    @staticmethod
    def _result_of(row: dict) -> Any:
        if row["status"] == "failed":
            raise ActivityError(row["error"] or "workflow failed")
        return json.loads(row["result"]) if row["result"] else None

    def _spawn(self, iid: str) -> None:
        task = asyncio.get_running_loop().create_task(self._run_instance(iid))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def _events_for(self, iid: str) -> list[dict]:
        with self._db_lock:
            rows = self._db.execute(
                "SELECT seq, kind, name, result, error FROM events "
                "WHERE instance_id = ? ORDER BY seq", (iid,)
            ).fetchall()
        return [dict(zip(("seq", "kind", "name", "result", "error"), r))
                for r in rows]

    def _record_event(self, iid: str, seq: int, call: _Call,
                      result: Any = None, error: Optional[str] = None) -> None:
        with self._db_lock:
            self._db.execute(
                "INSERT INTO events (instance_id, seq, kind, name, result, error) "
                "VALUES (?, ?, ?, ?, ?, ?)",
                (iid, seq, call.kind, call.name,
                 json.dumps(result) if error is None else None, error),
            )
            self._db.commit()

    def _finish_db(self, iid: str, result: Any = None,
                   error: Optional[str] = None) -> None:
        with self._db_lock:
            self._db.execute(
                "UPDATE instances SET status = ?, result = ?, error = ? "
                "WHERE id = ?",
                ("failed" if error is not None else "completed",
                 json.dumps(result) if error is None else None, error, iid),
            )
            self._db.commit()

    async def _finish(self, iid: str, result: Any = None,
                      error: Optional[str] = None) -> None:
        # DB commit off-loop; the asyncio.Event is NOT thread-safe, so
        # signal waiters back on the loop after the write is durable
        await asyncio.to_thread(self._finish_db, iid, result, error)
        ev = self._done_events.setdefault(iid, asyncio.Event())
        ev.set()
        # waiters hold their own reference; drop ours so fire-and-forget
        # instances don't leak one Event each
        self._done_events.pop(iid, None)

    async def _run_instance(self, iid: str) -> None:
        # every event-log read/append goes through asyncio.to_thread:
        # sqlite commits fsync on real files, and a loop-side commit
        # under _db_lock would stall every concurrent request/workflow
        row = await asyncio.to_thread(self._instance_row, iid)
        if row is None or row["status"] != "running":
            return
        wf = self.workflows[row["workflow"]]
        ctx = WorkflowContext(iid)
        gen = wf(ctx, json.loads(row["input"]))
        events = await asyncio.to_thread(self._events_for, iid)
        seq = 0
        to_send: Any = None
        to_throw: Optional[BaseException] = None
        try:
            while True:
                try:
                    if to_throw is not None:
                        call = gen.throw(to_throw)
                        to_throw = None
                    else:
                        call = gen.send(to_send)
                except StopIteration as stop:
                    await self._finish(iid, result=stop.value)
                    return
                if not isinstance(call, _Call):
                    raise RuntimeError(
                        f"workflow yielded {type(call).__name__}, expected "
                        "ctx.call()/ctx.sleep()")
                if seq < len(events):
                    ev = events[seq]
                    if ev["kind"] != call.kind or ev["name"] != call.name:
                        raise RuntimeError(
                            f"non-deterministic workflow replay at seq {seq}: "
                            f"recorded {ev['kind']}:{ev['name']}, "
                            f"replayed {call.kind}:{call.name}")
                    if ev["error"] is not None:
                        to_send, to_throw = None, ActivityError(ev["error"])
                    else:
                        to_send = json.loads(ev["result"]) if ev["result"] else None
                    seq += 1
                    continue
                # live execution
                if call.kind == "sleep":
                    await asyncio.sleep(call.args["seconds"])
                    await asyncio.to_thread(self._record_event, iid, seq,
                                            call)
                    to_send = None
                    seq += 1
                    continue
                fn = self.activities.get(call.name)
                if fn is None:
                    raise RuntimeError(f"unknown activity {call.name!r}")
                try:
                    # activities do blocking I/O (engine sockets for a
                    # remote tcp:// engine, kube HTTP) — keep them off the
                    # event loop so concurrent workflows/requests proceed
                    out = await asyncio.to_thread(fn, ctx, **call.args)
                    if asyncio.iscoroutine(out):
                        out = await out
                except (WorkflowCrash, FailPointError):
                    # simulated process death (armed failpoint at a
                    # side-effect edge): nothing recorded; the instance
                    # stays 'running' for resume_pending()
                    return
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 - activity boundary
                    await asyncio.to_thread(self._record_event, iid, seq,
                                            call, None, str(e))
                    to_send, to_throw = None, ActivityError(str(e))
                    seq += 1
                    continue
                await asyncio.to_thread(self._record_event, iid, seq,
                                        call, out)
                to_send = out
                seq += 1
        except asyncio.CancelledError:
            raise
        except Exception as e:  # workflow-level failure
            await self._finish(iid, error=f"{type(e).__name__}: {e}")
