"""Durable dual-write: deterministic workflow engine + the two lock-mode
workflows (reference pkg/authz/distributedtx).

The reference uses github.com/cschleiden/go-workflows with a SQLite
event-sourced backend run in-process ("monoprocess",
/root/reference/pkg/authz/distributedtx/client.go:18-62). Here the same
durability contract is provided by runner.py: workflows are Python
generator functions whose activity calls are event-sourced to SQLite and
deterministically replayed after a crash.
"""

from .runner import (  # noqa: F401
    ActivityError,
    WorkflowCrash,
    WorkflowEngine,
    WorkflowTimeout,
)
from .workflow import (  # noqa: F401
    KubeResp,
    LOCK_MODE_OPTIMISTIC,
    LOCK_MODE_PESSIMISTIC,
    WorkflowInput,
    register_workflows,
)
from .activity import ActivityHandler  # noqa: F401
