"""The two lock-mode dual-write workflows.

Mirrors /root/reference/pkg/authz/distributedtx/workflow.go:

- Pessimistic (workflow.go:134-250): acquire a SpiceDB lock tuple
  ``lock:{hash(path/name/verb)}#workflow@workflow:{instanceID}`` with a
  must-not-exist precondition, write the relationships, then write to kube
  with bounded backoff honoring Retry-After; roll back relationships (ops
  inverted, retried until success) on failure; always release the lock.
- Optimistic (workflow.go:280-352): write relationships, write kube; on an
  ambiguous kube failure probe resource existence and roll back the
  relationship write iff the kube write did not land.

Workflow code is deterministic (no clocks/randomness — the backoff schedule
is fixed) so the event-sourced replay in runner.py is exact.
"""

from __future__ import annotations

import base64
import hashlib
import json
from dataclasses import dataclass, field
from typing import Optional

from .runner import ActivityError, WorkflowContext

LOCK_MODE_PESSIMISTIC = "Pessimistic"
LOCK_MODE_OPTIMISTIC = "Optimistic"

LOCK_RESOURCE_TYPE = "lock"
LOCK_RELATION = "workflow"
WORKFLOW_TYPE = "workflow"

MAX_KUBE_ATTEMPTS = 5
# 100ms base, x2 backoff (reference KubeBackoff, workflow.go:34-39; jitter
# dropped: workflow code must be deterministic for replay)
KUBE_BACKOFF_BASE = 0.1
KUBE_BACKOFF_FACTOR = 2.0


@dataclass
class WorkflowInput:
    """JSON-serializable input (reference WriteObjInput, workflow.go:41-54)."""

    verb: str
    path: str  # request path (lock key component)
    uri: str  # full request URI for raw replay
    headers: dict
    user_name: str
    object_name: str  # object meta name, falls back to request name
    namespace: str
    api_group: str
    resource: str
    body_b64: str = ""
    preconditions: list = field(default_factory=list)
    creates: list = field(default_factory=list)  # rel strings
    touches: list = field(default_factory=list)
    deletes: list = field(default_factory=list)
    delete_by_filter: list = field(default_factory=list)  # filter dicts

    def to_dict(self) -> dict:
        return self.__dict__.copy()

    @staticmethod
    def from_dict(d: dict) -> "WorkflowInput":
        return WorkflowInput(**d)


@dataclass
class KubeResp:
    status: int
    headers: dict
    body: bytes

    @staticmethod
    def from_activity(out: dict) -> "KubeResp":
        return KubeResp(
            status=out["status"],
            headers=out.get("headers") or {},
            body=base64.b64decode(out.get("body_b64", "")),
        )


def resource_lock_rel(input: WorkflowInput, workflow_id: str) -> str:
    """lock:{hash(path/name/verb)}#workflow@workflow:{id}
    (reference ResourceLockRel, workflow.go:393-419)."""
    lock_key = f"{input.path}/{input.object_name}/{input.verb}"
    lock_hash = hashlib.blake2s(lock_key.encode()).hexdigest()[:16]
    return (f"{LOCK_RESOURCE_TYPE}:{lock_hash}#{LOCK_RELATION}"
            f"@{WORKFLOW_TYPE}:{workflow_id}")


def lock_does_not_exist_precondition(lock_rel: str) -> dict:
    lock_id = lock_rel.split(":", 1)[1].split("#", 1)[0]
    return {
        "must_exist": False,
        "filter": {
            "resource_type": LOCK_RESOURCE_TYPE,
            "resource_id": lock_id,
            "relation": LOCK_RELATION,
            "subject_type": WORKFLOW_TYPE,
        },
    }


def kube_conflict_resp(err: str, input: WorkflowInput) -> dict:
    """SpiceDB failures surface as kube 409 Conflict so clients retry
    (reference KubeConflict, workflow.go:421-457)."""
    status = {
        "kind": "Status",
        "apiVersion": "v1",
        "metadata": {},
        "status": "Failure",
        "message": (
            f'Operation cannot be fulfilled on {input.resource} '
            f'"{input.object_name}": {err}'
        ),
        "reason": "Conflict",
        "details": {"group": input.api_group, "kind": input.resource,
                    "name": input.object_name},
        "code": 409,
    }
    return {
        "status": 409,
        "headers": {"Content-Type": "application/json"},
        "body_b64": base64.b64encode(json.dumps(status).encode()).decode(),
        "retry_after": 0,
    }


def _base_updates(input: WorkflowInput) -> list[dict]:
    return (
        [{"op": "create", "rel": r} for r in input.creates]
        + [{"op": "touch", "rel": r} for r in input.touches]
        + [{"op": "delete", "rel": r} for r in input.deletes]
    )


def _invert(updates: list[dict]) -> list[dict]:
    """CREATE/TOUCH -> DELETE, DELETE -> TOUCH (workflow.go:86-99)."""
    out = []
    for u in updates:
        op = "delete" if u["op"] in ("create", "touch") else "touch"
        out.append({"op": op, "rel": u["rel"]})
    return out


def _cleanup(ctx: WorkflowContext, workflow_id: str, updates: list[dict]):
    """Invert and retry until success (reference Cleanup,
    workflow.go:86-129). Generator: delegate with `yield from`."""
    inverted = _invert(updates)
    attempt = 0
    while True:
        try:
            yield ctx.call("write_to_spicedb", updates=inverted,
                           preconditions=[], workflow_id=workflow_id)
            return
        except ActivityError as e:
            if "invalid" in str(e).lower() or "SchemaViolation" in str(e):
                return  # unrecoverable (workflow.go:116-121)
            attempt += 1
            yield ctx.sleep(min(0.05 * attempt, 1.0))


def _expand_delete_filters(ctx, input: WorkflowInput, updates: list[dict]):
    """Read matching relationships and append concrete deletes so retries
    delete a stable set (reference appendDeletesFromFilters,
    workflow.go:354-389)."""
    for f in input.delete_by_filter:
        rels = yield ctx.call("read_relationships", filter=f)
        for r in rels:
            updates.append({"op": "delete", "rel": r})


def _kube_req(input: WorkflowInput) -> dict:
    return {
        "verb": input.verb,
        "uri": input.uri,
        "headers": input.headers,
        "body_b64": input.body_b64,
    }


def _is_successful(verb: str, status: int) -> bool:
    """Verb-aware success semantics (workflow.go:252-275): a delete of an
    already-gone object (404) and a create of an already-present object
    (409) both count as applied. Any other verb is unsupported for
    dual-writes (workflow.go:264-266 errors rather than guessing) —
    raising here rolls everything back and surfaces the error."""
    if verb == "delete":
        return status in (404, 200)
    if verb in ("create", "update", "patch"):
        return status in (409, 201, 200)
    raise ActivityError(f"unsupported kube verb for dual-write: {verb}")


SUPPORTED_VERBS = ("create", "update", "patch", "delete")


def _validate_verb(verb: str) -> None:
    """Reject unsupported verbs BEFORE any side effect: past this point a
    deterministic verb error would either burn the kube retry budget
    (pessimistic — the activity's error is indistinguishable from a
    transient one) or, worse, pass the optimistic path's existence
    arbitration (a collection GET answers 200) and fabricate success
    over committed relationship writes."""
    if verb not in SUPPORTED_VERBS:
        raise ActivityError(
            f"unsupported kube verb for dual-write: {verb!r} "
            f"(supported: {', '.join(SUPPORTED_VERBS)})")


def pessimistic_write(ctx: WorkflowContext, input_dict: dict):
    input = WorkflowInput.from_dict(input_dict)
    _validate_verb(input.verb)
    lock_rel = resource_lock_rel(input, ctx.instance_id)
    lock_update = {"op": "create", "rel": lock_rel}

    updates = _base_updates(input)
    yield from _expand_delete_filters(ctx, input, updates)

    preconditions = [lock_does_not_exist_precondition(lock_rel)] \
        + list(input.preconditions)

    try:
        yield ctx.call(
            "write_to_spicedb",
            updates=updates + [lock_update],
            preconditions=preconditions,
            workflow_id=ctx.instance_id,
        )
    except ActivityError as e:
        # any SpiceDB failure (incl. lock conflict) -> rollback + kube 409
        # (workflow.go:189-202)
        yield from _cleanup(ctx, ctx.instance_id, updates + [lock_update])
        return kube_conflict_resp(str(e), input)

    backoff = KUBE_BACKOFF_BASE
    for _ in range(MAX_KUBE_ATTEMPTS + 1):
        try:
            out = yield ctx.call("write_to_kube", req=_kube_req(input))
        except ActivityError:
            yield ctx.sleep(backoff)
            backoff *= KUBE_BACKOFF_FACTOR
            continue
        if out.get("retry_after", 0) > 0:
            yield ctx.sleep(out["retry_after"])
            continue
        try:
            ok = _is_successful(input.verb, out["status"])
        except ActivityError:
            # unsupported verb: roll back BEFORE surfacing the error
            # (workflow.go:264-266 — cleanup precedes the error return)
            yield from _cleanup(ctx, ctx.instance_id,
                                updates + [lock_update])
            raise
        if ok:
            yield from _cleanup(ctx, ctx.instance_id, [lock_update])
            return out
        # kube rejected the operation: roll back everything
        yield from _cleanup(ctx, ctx.instance_id, updates + [lock_update])
        return out
    yield from _cleanup(ctx, ctx.instance_id, updates + [lock_update])
    raise ActivityError(
        f"failed to communicate with kubernetes after {MAX_KUBE_ATTEMPTS} attempts")


def optimistic_write(ctx: WorkflowContext, input_dict: dict):
    input = WorkflowInput.from_dict(input_dict)
    _validate_verb(input.verb)
    updates = _base_updates(input)
    yield from _expand_delete_filters(ctx, input, updates)

    try:
        yield ctx.call(
            "write_to_spicedb",
            updates=updates,
            preconditions=list(input.preconditions),
            workflow_id=ctx.instance_id,
        )
    except ActivityError as e:
        yield from _cleanup(ctx, ctx.instance_id, updates)
        return kube_conflict_resp(str(e), input)

    try:
        out = yield ctx.call("write_to_kube", req=_kube_req(input))
    except ActivityError as e:
        # ambiguous failure: did the kube write land? (workflow.go:335-348)
        exists = yield ctx.call("check_kube_resource",
                                path=_resource_path(input))
        if not exists:
            yield from _cleanup(ctx, ctx.instance_id, updates)
            raise ActivityError(f"kube write failed: {e}")
        out = {"status": 200, "headers": {},
               "body_b64": "", "retry_after": 0}
    return out


def _resource_path(input: WorkflowInput) -> str:
    path = input.path
    if input.verb == "create":
        # POST path has no name segment; the existence probe needs it
        name = input.object_name
        if name:
            path = path.rstrip("/") + "/" + name
    return path


def register_workflows(runner) -> None:
    runner.register_workflow(LOCK_MODE_PESSIMISTIC, pessimistic_write)
    runner.register_workflow(LOCK_MODE_OPTIMISTIC, optimistic_write)
