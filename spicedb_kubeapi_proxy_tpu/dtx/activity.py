"""Workflow activities: the side-effect edges of the dual-write.

Mirrors /root/reference/pkg/authz/distributedtx/activity.go:41-250:
WriteToSpiceDB (with idempotency-key relationships so at-least-once
execution yields exactly-once effects), ReadRelationships, WriteToKube (raw
URI replay against the upstream with admin credentials), and
CheckKubeResource. Every side-effect edge carries failpoint hooks
(activity.go:48,61,153,155,176,213) which simulate process death.
"""

from __future__ import annotations

import base64
import hashlib
import json
import time
from typing import Optional

from ..engine import Engine, Precondition, RelationshipFilter, WriteOp
from ..engine.store import PreconditionFailed, StoreError
from ..models.tuples import Relationship, parse_relationship
from ..proxy.types import ProxyRequest, ProxyResponse, Upstream
from ..utils.failpoints import failpoints

IDEMPOTENCY_KEY_RELATION = "idempotency_key"
WORKFLOW_TYPE = "workflow"
ACTIVITY_TYPE = "activity"
IDEMPOTENCY_KEY_TTL = 24 * 3600.0  # 24h expiration (activity.go:80-102)

_VERB_METHODS = {
    "create": "POST",
    "update": "PUT",
    "patch": "PATCH",
    "delete": "DELETE",
}


def filter_from_dict(d: dict) -> RelationshipFilter:
    return RelationshipFilter(
        resource_type=d.get("resource_type") or None,
        resource_id=d.get("resource_id") or None,
        relation=d.get("relation") or None,
        subject_type=d.get("subject_type") or None,
        subject_id=d.get("subject_id") or None,
        subject_relation=d.get("subject_relation") or None,
    )


class ActivityHandler:
    """Bound to the engine and the admin-credentialed upstream
    (reference ActivityHandler, activity.go:41-46)."""

    def __init__(self, engine: Engine, upstream: Upstream):
        self.engine = engine
        self.upstream = upstream

    def register(self, runner) -> None:
        runner.register_activity("write_to_spicedb", self.write_to_spicedb)
        runner.register_activity("read_relationships", self.read_relationships)
        runner.register_activity("write_to_kube", self.write_to_kube)
        runner.register_activity("check_kube_resource", self.check_kube_resource)

    # -- spicedb side --------------------------------------------------------

    def _idempotency_key(self, workflow_id: str, payload: str) -> Relationship:
        digest = hashlib.blake2s(payload.encode()).hexdigest()[:16]
        return Relationship(
            WORKFLOW_TYPE, workflow_id, IDEMPOTENCY_KEY_RELATION,
            ACTIVITY_TYPE, digest, expiration=time.time() + IDEMPOTENCY_KEY_TTL,
        )

    def write_to_spicedb(self, ctx, updates: list, preconditions: list,
                         workflow_id: str):
        """updates: [{"op": create|touch|delete, "rel": <rel string>}];
        preconditions: [{"must_exist": bool, "filter": {...}}]."""
        failpoints.hit("panicWriteSpiceDB")
        payload = json.dumps([updates, preconditions], sort_keys=True)
        key_rel = self._idempotency_key(workflow_id, payload)
        ops = [WriteOp(u["op"], parse_relationship(u["rel"])) for u in updates]
        ops.append(WriteOp("touch", key_rel))
        pcs = [
            Precondition(filter_from_dict(p["filter"]), bool(p["must_exist"]))
            for p in preconditions
        ]
        try:
            self.engine.write_relationships(ops, pcs)
        except (PreconditionFailed, StoreError) as e:
            # The write may have already been applied by a previous attempt
            # that crashed after the side effect: the idempotency key tells
            # us (activity.go:63-74).
            if self.engine.store.exists(RelationshipFilter(
                resource_type=WORKFLOW_TYPE,
                resource_id=workflow_id,
                relation=IDEMPOTENCY_KEY_RELATION,
                subject_type=ACTIVITY_TYPE,
                subject_id=key_rel.subject_id,
            )):
                failpoints.hit("panicSpiceDBReadResp")
                return {"applied": True, "deduped": True}
            raise
        failpoints.hit("panicSpiceDBReadResp")
        return {"applied": True, "revision": self.engine.revision}

    def read_relationships(self, ctx, filter: dict) -> list:
        failpoints.hit("panicReadSpiceDB")
        rels = [str(r.without_expiration())
                for r in self.engine.read_relationships(filter_from_dict(filter))]
        failpoints.hit("panicSpiceDBReadRelResp")
        return rels

    # -- kube side -----------------------------------------------------------

    async def write_to_kube(self, ctx, req: dict) -> dict:
        """Raw request replay against the upstream with the original
        headers/body (activity.go:175-231)."""
        failpoints.hit("panicKubeWrite")
        method = _VERB_METHODS.get(req["verb"])
        if method is None:
            raise ValueError(f"unsupported kube verb {req['verb']!r}")
        body = base64.b64decode(req.get("body_b64", "")) if req.get("body_b64") \
            else b""
        path, query = _split_uri(req["uri"])
        resp: ProxyResponse = await self.upstream(ProxyRequest(
            method=method, path=path, query=query,
            headers=dict(req.get("headers") or {}), body=body,
        ))
        failpoints.hit("panicKubeReadResp")
        retry_after = 0
        ra = resp.headers.get("Retry-After")
        if ra:
            try:
                retry_after = int(ra)
            except ValueError:
                retry_after = 0
        return {
            "status": resp.status,
            "headers": dict(resp.headers),
            "body_b64": base64.b64encode(resp.body).decode(),
            "retry_after": retry_after,
        }

    async def check_kube_resource(self, ctx, path: str) -> bool:
        """Existence probe after ambiguous kube failures
        (activity.go:233-247)."""
        failpoints.hit("panicCheckKube")
        resp: ProxyResponse = await self.upstream(
            ProxyRequest(method="GET", path=path))
        return resp.status == 200


def _split_uri(uri: str) -> tuple[str, dict]:
    from urllib.parse import parse_qs, unquote, urlsplit

    u = urlsplit(uri)
    return unquote(u.path), parse_qs(u.query, keep_blank_values=True)
